#!/usr/bin/env python
"""Offline "why is my job still pending?" — the /api/explain answer
from a flight-recorder journal dump.

The dashboard answers live from the in-process recorder; this prints
the SAME reason chain from a journal written with
``obs.recorder.dump_jsonl(path)`` (or fetched from a live dashboard
with ``--url``), so a post-mortem needs only the dump file.

Usage:
    python tools/explain.py --journal decisions.jsonl default/my-job
    python tools/explain.py --journal decisions.jsonl            # summary
    python tools/explain.py --journal decisions.jsonl --cycles 5
    python tools/explain.py --url http://127.0.0.1:8080 default/my-job
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

# allow running straight from a checkout: tools/ sits next to the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kueue_oss_tpu.obs import CYCLE_SCOPE, DecisionEvent, load_jsonl  # noqa: E402


def _fmt_event(ev: DecisionEvent) -> str:
    line = (f"  cycle {ev.cycle:>6}  [{ev.path:>6}] {ev.kind:<16} "
            f"{ev.reason or ev.reason_slug or '(no reason recorded)'}")
    if ev.breaker != "closed":
        line += f"  (breaker {ev.breaker})"
    if ev.detail:
        line += f"\n{'':16}detail: {json.dumps(ev.detail, sort_keys=True)}"
    return line


def explain_workload(events: list[DecisionEvent], key: str,
                     out) -> int:
    chain = [ev for ev in events if ev.workload == key]
    chain.sort(key=lambda ev: ev.seq, reverse=True)
    if not chain:
        print(f"no decisions recorded for workload {key}", file=out)
        return 1
    newest = chain[0]
    print(f"workload {key} — {len(chain)} decision(s), newest first "
          f"(latest: {newest.kind}"
          + (f" in ClusterQueue {newest.cluster_queue}"
             if newest.cluster_queue else "") + ")", file=out)
    fence = next(
        (ev for ev in chain
         if (ev.reason_slug or "").startswith("stream_fence_")
         or ev.reason_slug == "stream_parked"), None)
    if fence is not None:
        slug = fence.reason_slug or ""
        what = (fence.detail or {}).get(
            "fence",
            slug[len("stream_fence_"):] if slug.startswith(
                "stream_fence_") else "parked")
        print(f"streaming: not admitted sub-cycle — fence "
              f"'{what}' at cycle {fence.cycle}: "
              f"{fence.reason or slug}", file=out)
    for ev in chain:
        print(_fmt_event(ev), file=out)
    return 0


def summarize(events: list[DecisionEvent], out) -> int:
    latest: dict[str, DecisionEvent] = {}
    for ev in events:
        if ev.workload == CYCLE_SCOPE:
            continue
        cur = latest.get(ev.workload)
        if cur is None or ev.seq > cur.seq:
            latest[ev.workload] = ev
    if not latest:
        print("journal holds no per-workload decisions", file=out)
        return 1
    print(f"{len(latest)} workload(s) in the journal; latest decision "
          "each:", file=out)
    for key in sorted(latest):
        ev = latest[key]
        print(f"  {key:<40} cycle {ev.cycle:>6} [{ev.path:>6}] "
              f"{ev.kind:<16} {ev.reason_slug or ev.reason[:60]}",
              file=out)
    return 0


def show_cycles(events: list[DecisionEvent], n: int, out) -> int:
    by_cycle: dict[int, list[DecisionEvent]] = {}
    for ev in events:
        by_cycle.setdefault(ev.cycle, []).append(ev)
    for c in sorted(by_cycle, reverse=True)[:n]:
        print(f"cycle {c}:", file=out)
        for ev in sorted(by_cycle[c], key=lambda e: e.seq):
            who = ev.workload if ev.workload != CYCLE_SCOPE else "(cycle)"
            print(f"  [{ev.path:>6}] {ev.kind:<16} {who:<40} "
                  f"{ev.reason_slug or ev.reason[:60]}", file=out)
    return 0


def _fetch_url(url: str, key: str) -> list[DecisionEvent]:
    ns, name = key.split("/", 1)
    try:
        data = json.loads(urllib.request.urlopen(
            f"{url.rstrip('/')}/api/workloads/{ns}/{name}/explain",
            timeout=10).read())
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return []  # unknown workload: same answer as an empty journal
        raise SystemExit(f"dashboard returned HTTP {e.code} for {key}")
    except urllib.error.URLError as e:
        raise SystemExit(f"dashboard unreachable at {url}: {e.reason}")
    return [DecisionEvent.from_dict(d) for d in data.get("events", [])]


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    p = argparse.ArgumentParser(
        prog="explain.py",
        description="Explain workload admission decisions from a "
                    "flight-recorder journal dump (or a live dashboard).")
    p.add_argument("workload", nargs="?",
                   help="workload key (namespace/name); omit for a "
                        "per-workload summary")
    p.add_argument("--journal", help="journal dump path (JSONL, written "
                                     "by recorder.dump_jsonl)")
    p.add_argument("--url", help="live dashboard base URL instead of a "
                                 "journal (requires a workload key)")
    p.add_argument("--cycles", type=int, default=0,
                   help="show the last N cycles' full decision groups")
    args = p.parse_args(argv)

    if args.url:
        if not args.workload:
            p.error("--url requires a workload key")
        return explain_workload(_fetch_url(args.url, args.workload),
                                args.workload, out)
    if not args.journal:
        p.error("--journal (or --url) is required")
    events = load_jsonl(args.journal)
    if args.cycles:
        return show_cycles(events, args.cycles, out)
    if args.workload:
        return explain_workload(events, args.workload, out)
    return summarize(events, out)


if __name__ == "__main__":
    raise SystemExit(main())
