"""Quick host-vs-kernel parity smoke on a shrunken large-scale shape.

Usage: python tools/smoke_kernel.py [n_cohorts] [cqs_per_cohort] [div]
Forces the CPU backend (the ambient axon TPU plugin overrides
JAX_PLATFORMS and hangs when the tunnel is down).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.perf.generator import GeneratorConfig, generate
from kueue_oss_tpu.scheduler.scheduler import Scheduler
from kueue_oss_tpu.solver.engine import SolverEngine


def main() -> None:
    n_cohorts = int(sys.argv[1]) if len(sys.argv) > 1 else 1
    cqs = int(sys.argv[2]) if len(sys.argv) > 2 else 2
    div = int(sys.argv[3]) if len(sys.argv) > 3 else 5
    config = GeneratorConfig.large_scale(preemption=True)
    config.n_cohorts, config.cqs_per_cohort = n_cohorts, cqs
    for wc in config.classes:
        wc.count = max(1, wc.count // div)

    t0 = time.time()
    store, schedule = generate(config)
    for g in schedule:
        store.add_workload(g.workload)
    queues = QueueManager(store)
    engine = SolverEngine(store, queues)
    print(f"setup {time.time() - t0:.1f}s "
          f"(W={len(schedule)} C={n_cohorts * cqs})", flush=True)
    t0 = time.time()
    r = engine.drain(now=0.0)
    print(f"kernel admitted={r.admitted} evicted={r.evicted} "
          f"rounds={r.rounds} solve={r.solver_time_s:.2f}s "
          f"total={time.time() - t0:.1f}s", flush=True)

    t0 = time.time()
    store2, schedule2 = generate(config)
    for g in schedule2:
        store2.add_workload(g.workload)
    queues2 = QueueManager(store2)
    Scheduler(store2, queues2).run_until_quiet(
        now=0.0, max_cycles=20000, tick=1.0)
    adm_h = {k for k, w in store2.workloads.items() if w.is_quota_reserved}
    adm_k = {k for k, w in store.workloads.items() if w.is_quota_reserved}
    print(f"host admitted={len(adm_h)} ({time.time() - t0:.1f}s) "
          f"agree={len(adm_h & adm_k)} union={len(adm_h | adm_k)}",
          flush=True)
    if adm_h != adm_k:
        print("MISMATCH only-host:", sorted(adm_h - adm_k)[:6])
        print("MISMATCH only-kernel:", sorted(adm_k - adm_h)[:6])
        raise SystemExit(1)
    print("PARITY OK", flush=True)


if __name__ == "__main__":
    main()
