#!/usr/bin/env python
"""Offline fabric-timeline join — one per-cycle report from the three
observability artifacts, keyed by the shared cycle id.

The dashboard answers live (/api/trace, /api/decisions); this joins the
SAME three records from their dump files, so a post-mortem needs only
the artifacts beside a checkpoint:

  * a Chrome-trace export (``GET /api/trace`` or
    ``tracer.chrome_trace()`` saved to a file) — host drain spans, farm
    grant-waits, sidecar/mesh solves on their synthetic tracks;
  * a cycle-ledger dump (``obs.cycle_ledger.dump_jsonl``) — per-drain
    arm/frame/wall/grant-wait/device-transfer rows;
  * a decision-journal dump (``obs.recorder.dump_jsonl``) — the
    per-workload reason chain.

All three tag their records with the host cycle id, so one join key
reconstructs "what happened in cycle N" across processes and tenants.

Usage:
    python tools/trace.py --trace trace.json --ledger ledger.jsonl \
        --journal decisions.jsonl
    python tools/trace.py --trace trace.json --cycles 5    # newest 5
    python tools/trace.py --ledger ledger.jsonl --cycle 42 # one cycle

Exit status: 0 on a report, 1 when no input yields any cycles.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

# allow running straight from a checkout: tools/ sits next to the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kueue_oss_tpu.obs import load_jsonl  # noqa: E402
from kueue_oss_tpu.obs.ledger import load_ledger_jsonl  # noqa: E402


def load_trace(path: str) -> tuple[list[dict], dict[int, str]]:
    """Chrome-trace file -> (X events, tid -> track label). Accepts
    both the bare ``{"traceEvents": [...]}`` export and a raw list."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", doc) if isinstance(doc, dict) else doc
    spans, labels = [], {}
    for ev in events:
        if not isinstance(ev, dict):
            continue
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            args = ev.get("args") or {}
            labels[int(ev.get("tid", 0))] = str(args.get("name", ""))
        elif ev.get("ph") == "X":
            spans.append(ev)
    return spans, labels


def span_cycle(ev: dict):
    args = ev.get("args") or {}
    c = args.get("cycle")
    return int(c) if isinstance(c, (int, float)) else None


def _fmt_span(ev: dict, labels: dict[int, str]) -> str:
    tid = int(ev.get("tid", 0))
    track = labels.get(tid) or f"host:{tid}"
    dur_ms = float(ev.get("dur", 0)) / 1000.0
    args = ev.get("args") or {}
    extra = ""
    if args.get("tenant"):
        extra = f"  tenant={args['tenant']}"
    return (f"    span {ev.get('name', '?'):<18} {dur_ms:9.3f} ms"
            f"  [{track}]{extra}")


def _fmt_ledger_row(row) -> str:
    if row.kind == "host":
        line = (f"    ledger host    {row.duration_s * 1e3:9.3f} ms"
                f"  admitted={row.admitted} preempted={row.preempted}"
                f" skipped={row.skipped}")
    else:
        line = (f"    ledger {row.kind:<7} {row.duration_s * 1e3:9.3f} ms"
                f"  arm={row.solver_arm or '?'}"
                f" frame={row.frame_kind or '-'}"
                f" admitted={row.admitted}")
        if row.grant_wait_ms:
            line += f" grantWait={row.grant_wait_ms:.3f}ms"
        dev = row.device or {}
        moved = sum(int(dev.get(k, 0)) for k in
                    ("donated_update_bytes", "full_upload_bytes"))
        if moved:
            line += f" h2d={moved}B"
        if dev.get("compiles"):
            line += f" compiles={dev['compiles']}"
        if dev.get("hbm_resident_bytes"):
            line += f" hbm={dev['hbm_resident_bytes']}B"
    if row.breaker != "closed":
        line += f"  (breaker {row.breaker})"
    return line


def _fmt_decision(d) -> str:
    return (f"    decide {d.kind:<16} {d.workload}"
            f"  [{d.path}] {d.reason_slug or d.reason or ''}".rstrip())


def report(spans, labels, rows, events, cycles, out) -> int:
    by_cycle: dict[int, dict] = defaultdict(
        lambda: {"spans": [], "rows": [], "events": []})
    for ev in spans:
        c = span_cycle(ev)
        if c is not None:
            by_cycle[c]["spans"].append(ev)
    for row in rows:
        by_cycle[row.cycle]["rows"].append(row)
    for d in events:
        by_cycle[d.cycle]["events"].append(d)
    if not by_cycle:
        print("no cycles found in any input", file=out)
        return 1
    keys = sorted(by_cycle)
    if cycles:
        keys = keys[-cycles:]
    print(f"{len(keys)} cycle(s) "
          f"({len(spans)} spans, {len(rows)} ledger rows, "
          f"{len(events)} decisions joined on the cycle id)", file=out)
    for c in keys:
        bucket = by_cycle[c]
        print(f"\ncycle {c}:", file=out)
        for row in sorted(bucket["rows"], key=lambda r: r.seq):
            print(_fmt_ledger_row(row), file=out)
        for ev in sorted(bucket["spans"], key=lambda e: e.get("ts", 0)):
            print(_fmt_span(ev, labels), file=out)
        for d in sorted(bucket["events"], key=lambda e: e.seq)[:12]:
            print(_fmt_decision(d), file=out)
        if len(bucket["events"]) > 12:
            print(f"    ... {len(bucket['events']) - 12} more "
                  f"decision(s)", file=out)
    return 0


def main(argv=None, out=None) -> int:
    out = out if out is not None else sys.stdout
    p = argparse.ArgumentParser(
        description="Join a Chrome-trace export, a cycle-ledger dump, "
                    "and a decision-journal dump into one per-cycle "
                    "fabric timeline report.")
    p.add_argument("--trace", help="Chrome-trace JSON (GET /api/trace "
                                   "or tracer.chrome_trace())")
    p.add_argument("--ledger", help="cycle-ledger dump (JSONL, written "
                                    "by cycle_ledger.dump_jsonl)")
    p.add_argument("--journal", help="decision-journal dump (JSONL, "
                                     "written by recorder.dump_jsonl)")
    p.add_argument("--cycles", type=int, default=0,
                   help="report only the newest N cycles")
    p.add_argument("--cycle", type=int, default=None,
                   help="report exactly this cycle id")
    args = p.parse_args(argv)
    if not (args.trace or args.ledger or args.journal):
        p.error("at least one of --trace/--ledger/--journal is required")
    spans, labels = load_trace(args.trace) if args.trace else ([], {})
    rows = load_ledger_jsonl(args.ledger) if args.ledger else []
    events = load_jsonl(args.journal) if args.journal else []
    if args.cycle is not None:
        spans = [e for e in spans if span_cycle(e) == args.cycle]
        rows = [r for r in rows if r.cycle == args.cycle]
        events = [d for d in events if d.cycle == args.cycle]
    return report(spans, labels, rows, events, args.cycles, out)


if __name__ == "__main__":
    raise SystemExit(main())
