#!/usr/bin/env python
"""Schema guard for bench.py JSON tails.

bench.py prints exactly one JSON line per run; downstream tooling (the
perf trajectory, BENCH_r*.json archives) indexes those keys blind, so a
silently renamed or dropped field turns a perf regression invisible.
This validates a bench JSON tail against the declared schema: required
keys present, types right, and the acceptance-bearing ratios sane.

Usage:
    python tools/benchcheck.py --json BENCH_r06.json
    python bench.py --scenario megascale | \
        python tools/benchcheck.py --scenario megascale
    python tools/benchcheck.py --json out.json --strict   # floors too

Exit status: 0 valid, 1 schema violation (messages on stderr).
"""

from __future__ import annotations

import argparse
import json
import sys

NUM = (int, float)

#: scenario -> {key: expected type(s)}. Every listed key is REQUIRED in
#: that scenario's tail; extra keys are always allowed (the tails grow).
SCHEMAS = {
    # the megascale scenario's budget tail (bench.py "megascale"):
    # columnar export, delta encode, and the streamed-burst twin
    "megascale": {
        "scenario": str,
        "workloads": int,
        "cqs": int,
        "pending": int,
        "export_ms": NUM,
        "export_walk_warm_ms": NUM,
        "export_columnar_build_ms": NUM,
        "export_ms_unchanged": NUM,
        "export_speedup": NUM,
        "export_speedup_warm": NUM,
        "export_mode_unchanged": str,
        "columnar_identical": bool,
        "churn_rows": int,
        "export_churn_ms": NUM,
        "export_churn_mode": str,
        "export_churn_dirty_rows": int,
        "delta_encode_ms": NUM,
        "delta_frame": str,
        "burst": int,
        "burst_cqs": int,
        "micro_solve_ms": NUM,
        "micro_export_ms": NUM,
        "stream_commit_ms_host": NUM,
        "stream_commit_ms_micro": NUM,
        "stream_e2e_ms_host": NUM,
        "stream_e2e_ms_micro": NUM,
        "arrivals_per_sec": NUM,
        "arrivals_per_sec_host": NUM,
        "arrivals_speedup": NUM,
    },
    # the federation scenario's tail (bench.py "federation"): farm DRR
    # fairness under contended churn + what-if-scored dispatch
    "federation": {
        "scenario": str,
        "tenants": int,
        "members": int,
        "contended_seconds": NUM,
        "farm_solves": int,
        "farm_throttled": int,
        "tenant_wall_share_spread": NUM,
        "zero_cross_tenant": bool,
        "plans_identical_dedicated": bool,
        "whatif_dispatches": int,
        "whatif_oracle_agreement": NUM,
        "dispatch_score_ms_mean": NUM,
        "whatif_time_to_admit_s": NUM,
        "incremental_time_to_admit_s": NUM,
        "whatif_admit_speedup": NUM,
    },
    # the chaoscampaign scenario's tail (bench.py "chaoscampaign"):
    # composed-fault storms + the convergence oracle's aggregate
    # verdicts (docs/ROBUSTNESS.md "Chaos campaigns")
    "chaoscampaign": {
        "scenario": str,
        "seed": int,
        "seconds": NUM,
        "profiles": dict,
        "converged_all": bool,
        "recovered_identical": bool,
        "convergence_cycles": int,
        "max_degradation_level": int,
        "availability": NUM,
        "unavailable_wall_ms": NUM,
        "invariant_violations": int,
        "faults_injected": int,
    },
    # the telemetry scenario's tail (bench.py "telemetry"): devtel
    # collector off/on twin + the on-arm evidence bundle
    # (docs/OBSERVABILITY.md "Device telemetry & fabric tracing")
    "telemetry": {
        "scenario": str,
        "workloads": int,
        "cycles": int,
        "seconds_devtel_off": NUM,
        "seconds_devtel_on": NUM,
        "devtel_overhead_pct": NUM,
        "compiles_detected": int,
        "transfer_bytes_total": int,
        "grant_wait_ms_p50": NUM,
        "trace_tracks": int,
        "capture_trigger_works": bool,
    },
    # the fullsweep scenario's tail (bench.py "fullsweep"): chunked
    # FULL-kernel sweeps vs the sequential FULL oracle + the resident
    # and relax-tier measurements (docs/SIMULATOR.md "FULL-kernel
    # sweeps, lane budgets & resident state")
    "fullsweep": {
        "scenario": str,
        "scenarios": int,
        "workloads": int,
        "padded_workloads": int,
        "chunk_width": int,
        "chunks": int,
        "chunked_wall_s": NUM,
        "sequential_wall_s": NUM,
        "full_speedup": NUM,
        "plans_identical": bool,
        "preemptions_total": int,
        "resident_sweep_s": NUM,
        "reupload_sweep_s": NUM,
        "resident_win": NUM,
        "resident_reuses": int,
        "resident_full_uploads": int,
        "relax_scenarios": int,
        "relax_scenarios_per_sec": NUM,
    },
    # the orchestrated run's headline tail (bench.py main): only the
    # always-present core — optional scenarios may drop their fields
    "main": {
        "metric": str,
        "value": NUM,
        "unit": str,
        "vs_baseline": NUM,
        "preempt_drain_admissions_per_s": NUM,
        "preempt_drain_decisions_per_s": NUM,
        "cycle_ms_p50_50k_1k": NUM,
        "cycle_ms_p99_50k_1k": NUM,
        "platform": str,
    },
}

#: --strict acceptance floors per scenario (the documented targets;
#: soft-skipped otherwise so a smoke-shape tail still validates shape)
FLOORS = {
    "megascale": {
        "export_speedup": 20.0,
        "arrivals_speedup": 10.0,
    },
    "federation": {
        "whatif_oracle_agreement": 0.95,
        "whatif_admit_speedup": 1.0,
    },
    "chaoscampaign": {
        # worst profile still admits in most eligible cycles (the
        # degraded-but-available claim; pod-loss's fenced streaming
        # cycles are the binding case)
        "availability": 0.6,
    },
    "telemetry": {
        # the acceptance bar wants non-trivial evidence, not a tail of
        # zeros: at least one compile event, some transferred bytes,
        # and the merged timeline's synthetic tracks (sidecar + farm)
        "compiles_detected": 1,
        "transfer_bytes_total": 1,
        "trace_tracks": 2,
    },
    "fullsweep": {
        # the ISSUE's acceptance bar: >= 3x chunked-vs-sequential FULL
        # sweep wall, a resident-state win (never slower than fresh
        # uploads), and preemption traffic proving the FULL tier is
        # actually exercised (a zero-victim sweep proves nothing)
        "full_speedup": 3.0,
        "resident_win": 1.0,
        "preemptions_total": 1,
    },
}

#: --strict acceptance ceilings per scenario (upper bounds: fairness
#: spreads and overheads regress UPWARD)
CEILINGS = {
    "federation": {
        "tenant_wall_share_spread": 1.5,
    },
    "chaoscampaign": {
        # the oracle's bound: every profile back to the twin's bytes
        # within this many recovery cycles
        "convergence_cycles": 16,
        "invariant_violations": 0,
    },
    "telemetry": {
        # the collector's overhead contract on the churn shape
        "devtel_overhead_pct": 2.0,
    },
}

#: exact-value requirements per scenario under --strict
STRICT_EQ = {
    "megascale": {
        "columnar_identical": True,
        "export_mode_unchanged": "cached",
        "export_churn_mode": "scatter",
        "delta_frame": "delta",
    },
    "federation": {
        "zero_cross_tenant": True,
        "plans_identical_dedicated": True,
    },
    "chaoscampaign": {
        "converged_all": True,
        "recovered_identical": True,
    },
    "telemetry": {
        "capture_trigger_works": True,
    },
    "fullsweep": {
        # the non-negotiable: chunked plans bit-identical to the
        # sequential FULL oracle at the benched lane budget
        "plans_identical": True,
    },
}


def check(tail: dict, scenario: str, strict: bool = False) -> list[str]:
    """Return a list of violations (empty = valid)."""
    schema = SCHEMAS.get(scenario)
    if schema is None:
        return [f"unknown scenario {scenario!r} "
                f"(known: {', '.join(sorted(SCHEMAS))})"]
    errors = []
    for key, typ in schema.items():
        if key not in tail:
            errors.append(f"missing key: {key}")
            continue
        val = tail[key]
        # bool is an int subclass; an int-typed key must not accept it
        if typ is int and isinstance(val, bool):
            errors.append(f"{key}: expected int, got bool")
        elif typ is bool and not isinstance(val, bool):
            errors.append(f"{key}: expected bool, "
                          f"got {type(val).__name__}")
        elif not isinstance(val, typ):
            name = (typ.__name__ if isinstance(typ, type)
                    else "number")
            errors.append(f"{key}: expected {name}, "
                          f"got {type(val).__name__}")
    if strict and not errors:
        for key, floor in FLOORS.get(scenario, {}).items():
            if tail[key] < floor:
                errors.append(f"{key}: {tail[key]} below the "
                              f"documented floor {floor}")
        for key, ceiling in CEILINGS.get(scenario, {}).items():
            if tail[key] > ceiling:
                errors.append(f"{key}: {tail[key]} above the "
                              f"documented ceiling {ceiling}")
        for key, want in STRICT_EQ.get(scenario, {}).items():
            if tail[key] != want:
                errors.append(f"{key}: expected {want!r}, "
                              f"got {tail[key]!r}")
    return errors


def main(argv=None, out=None) -> int:
    out = out or sys.stderr
    p = argparse.ArgumentParser(
        prog="benchcheck.py",
        description="Validate a bench.py JSON tail against its schema.")
    p.add_argument("--json", help="path to the JSON tail (default: "
                                  "read the last line of stdin)")
    p.add_argument("--scenario",
                   help="schema to check against (default: the tail's "
                        "own 'scenario' key, else 'main')")
    p.add_argument("--strict", action="store_true",
                   help="also enforce documented acceptance floors")
    args = p.parse_args(argv)

    if args.json:
        with open(args.json) as fh:
            text = fh.read()
    else:
        text = sys.stdin.read()
    lines = [ln for ln in text.strip().splitlines() if ln.strip()]
    if not lines:
        print("no input", file=out)
        return 1
    try:
        tail = json.loads(lines[-1])
    except json.JSONDecodeError as e:
        print(f"last line is not JSON: {e}", file=out)
        return 1
    scenario = args.scenario or tail.get("scenario") or "main"
    errors = check(tail, scenario, strict=args.strict)
    for err in errors:
        print(f"[{scenario}] {err}", file=out)
    if not errors:
        print(f"[{scenario}] tail valid "
              f"({len(SCHEMAS[scenario])} required keys)", file=out)
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
