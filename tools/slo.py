#!/usr/bin/env python
"""Offline cluster-health report over dumped cycle ledgers.

The live dashboard answers /api/slo and /api/health from the in-process
ledger and SLO engine; this prints the same per-cycle story from a
ledger dump (written with ``obs.cycle_ledger.dump_jsonl(path)``, or
persisted automatically next to checkpoints as ``ledger-*.jsonl``), so
a post-mortem needs only the dump files.

Usage:
    python tools/slo.py --ledger ledger.jsonl               # summary
    python tools/slo.py --ledger ledger.jsonl --cycles 5    # newest rows
    python tools/slo.py --ledger ledger.jsonl --cycle 17 \
        --journal decisions.jsonl      # one cycle's ledger+decision join
    python tools/slo.py --journal decisions.jsonl --slo \
        --threshold 300 --target 0.99  # recompute queue-wait SLIs
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# allow running straight from a checkout: tools/ sits next to the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kueue_oss_tpu.obs import load_jsonl  # noqa: E402
from kueue_oss_tpu.obs.health import SLOEngine  # noqa: E402
from kueue_oss_tpu.obs.ledger import (  # noqa: E402
    HOST_CYCLE,
    SOLVER_DRAIN,
    STREAM_DRAIN,
    CycleRecord,
    load_ledger_jsonl,
)


def _pct(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    values = sorted(values)
    idx = min(len(values) - 1, int(q * (len(values) - 1) + 0.5))
    return values[idx]


def summarize(rows: list[CycleRecord], out) -> int:
    if not rows:
        print("ledger is empty", file=out)
        return 1
    host = [r for r in rows if r.kind == HOST_CYCLE]
    solver = [r for r in rows if r.kind == SOLVER_DRAIN]
    stream = [r for r in rows if r.kind == STREAM_DRAIN]
    print(f"{len(rows)} ledger row(s): {len(host)} host cycle(s), "
          f"{len(solver)} solver drain(s), {len(stream)} stream "
          f"drain(s); cycles {rows[0].cycle}..{rows[-1].cycle}",
          file=out)
    if host:
        walls = [r.duration_s * 1000 for r in host]
        print(f"host cycles: admitted {sum(r.admitted for r in host)}, "
              f"preempted {sum(r.preempted for r in host)}, "
              f"skipped {sum(r.skipped for r in host)}; "
              f"wall p50 {_pct(walls, 0.5):.2f}ms "
              f"p95 {_pct(walls, 0.95):.2f}ms", file=out)
        slugs: dict[str, int] = {}
        for r in host:
            for slug, n in r.skip_slugs.items():
                slugs[slug] = slugs.get(slug, 0) + n
        if slugs:
            top = sorted(slugs.items(), key=lambda kv: -kv[1])
            print("skips by reason: " + ", ".join(
                f"{s}={n}" for s, n in top), file=out)
    if solver:
        solves = [r.phases.get("solve", 0.0) * 1000 for r in solver]
        arms: dict[str, int] = {}
        frames: dict[str, int] = {}
        bytes_by_kind: dict[str, int] = {}
        for r in solver:
            arms[r.solver_arm] = arms.get(r.solver_arm, 0) + 1
            frames[r.frame_kind] = frames.get(r.frame_kind, 0) + 1
            bytes_by_kind[r.frame_kind] = (
                bytes_by_kind.get(r.frame_kind, 0) + r.frame_bytes)
        print(f"solver drains: admitted "
              f"{sum(r.admitted for r in solver)}, parked "
              f"{sum(r.parked for r in solver)}, evicted "
              f"{sum(r.evicted for r in solver)}; solve p50 "
              f"{_pct(solves, 0.5):.2f}ms p95 "
              f"{_pct(solves, 0.95):.2f}ms", file=out)
        print("arms: " + ", ".join(f"{a}={n}"
                                   for a, n in sorted(arms.items())),
              file=out)
        print("frames: " + ", ".join(
            f"{k}={n} ({bytes_by_kind.get(k, 0)}B)"
            for k, n in sorted(frames.items())), file=out)
        donated = sum(r.device.get("donated_update_bytes", 0)
                      for r in solver)
        avoided = sum(r.device.get("avoided_copy_bytes", 0)
                      for r in solver)
        if donated or avoided:
            print(f"resident buffers: {donated}B donated scatters, "
                  f"{avoided}B full copies avoided", file=out)
        # export-pipeline breakdown (engine phase timers): where the
        # pre-solve wall goes — the dict walk / columnar scatter split
        # plus delta encode and host->device upload
        parts = []
        for label, key in (("export", "export"),
                           ("walk", "export_walk"),
                           ("scatter", "export_scatter"),
                           ("encode", "encode"),
                           ("device_put", "device_put")):
            vals = [r.phases[key] * 1000 for r in solver
                    if key in r.phases]
            if vals:
                parts.append(f"{label} p50 {_pct(vals, 0.5):.2f}ms "
                             f"p95 {_pct(vals, 0.95):.2f}ms")
        if parts:
            print("export pipeline: " + "; ".join(parts), file=out)
        modes: dict[str, int] = {}
        dirty = 0
        exported = 0
        for r in solver:
            m = r.session.get("export_mode")
            if m:
                modes[m] = modes.get(m, 0) + 1
                dirty += int(r.session.get("export_dirty_rows", 0))
                exported += int(r.session.get("export_rows", 0))
        if modes:
            print("columnar exports: " + ", ".join(
                f"{m}={n}" for m, n in sorted(modes.items()))
                + f"; {dirty} dirty row(s) scattered across "
                  f"{exported} exported", file=out)
    if stream:
        micro = [r for r in stream if r.detail.get("microBatch")]
        solves = [r.phases.get("micro_solve", 0.0) * 1000
                  for r in micro]
        line = (f"stream drains: admitted "
                f"{sum(r.admitted for r in stream)}, parked "
                f"{sum(r.parked for r in stream)}")
        if micro:
            line += (f"; micro-solves {len(micro)} "
                     f"({sum(r.detail['microBatch'] for r in micro)} "
                     f"entries) solve p50 {_pct(solves, 0.5):.2f}ms "
                     f"p95 {_pct(solves, 0.95):.2f}ms")
        print(line, file=out)
    return 0


def show_rows(rows: list[CycleRecord], n: int, out) -> int:
    for r in rows[-n:]:
        print(json.dumps(r.to_dict(), sort_keys=True), file=out)
    return 0


def show_cycle(rows: list[CycleRecord], cycle: int,
               journal: list, out) -> int:
    """The ledger↔recorder join for one cycle: every ledger row tagged
    with the cycle id, then that cycle's decision events."""
    matched = [r for r in rows if r.cycle == cycle]
    if not matched:
        print(f"no ledger rows for cycle {cycle}", file=out)
        return 1
    print(f"cycle {cycle}: {len(matched)} ledger row(s)", file=out)
    for r in matched:
        print("  " + json.dumps(r.to_dict(), sort_keys=True), file=out)
    events = [ev for ev in journal if ev.cycle == cycle]
    if events:
        print(f"{len(events)} decision event(s) in cycle {cycle}:",
              file=out)
        for ev in sorted(events, key=lambda e: e.seq):
            print(f"  [{ev.path:>6}] {ev.kind:<16} {ev.workload:<40} "
                  f"{ev.reason_slug or ev.reason[:60]}", file=out)
    elif journal:
        print(f"journal holds no events for cycle {cycle}", file=out)
    return 0


def recompute_slo(journal: list, threshold: float, target: float,
                  out) -> int:
    """Rebuild the queue-wait SLIs from a journal dump (the admission
    events carry waitSeconds in their detail) and print burn rates at
    the journal's final instant — the /api/slo answer, offline."""
    last_ts = max((ev.ts for ev in journal), default=0.0)
    eng = SLOEngine(target=target, threshold_s=threshold,
                    clock=lambda: last_ts)
    fed = eng.replay_journal(journal)
    if not fed:
        print("journal carries no admission waits (pre-health-layer "
              "dump?)", file=out)
        return 1
    report = eng.evaluate(now=last_ts)
    print(f"{fed} admission(s) replayed; objective "
          f"{target:.3f} within {threshold}s", file=out)
    for sli in report["slis"]:
        a = sli["alert"]
        line = (f"  {sli['scope']:>9}/{sli['key']:<24} "
                f"burn fast {sli['burnFast']:>8} slow "
                f"{sli['burnSlow']:>8}  [{a['state']}]")
        if a.get("exemplar"):
            ex = a["exemplar"]
            line += (f"  exemplar: cycle {ex['cycle']} "
                     f"{ex['workload']} ({ex['waitSeconds']}s)")
        print(line, file=out)
    return 0


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    p = argparse.ArgumentParser(
        prog="slo.py",
        description="Cluster-health report from dumped cycle ledgers "
                    "and decision journals.")
    p.add_argument("--ledger", help="ledger dump path (JSONL, written "
                                    "by cycle_ledger.dump_jsonl)")
    p.add_argument("--journal", help="decision journal dump (JSONL) "
                                     "for joins and SLO recompute")
    p.add_argument("--cycles", type=int, default=0,
                   help="print the newest N ledger rows as JSONL")
    p.add_argument("--cycle", type=int,
                   help="show one cycle's ledger rows + decision "
                        "events (the cycle-id join)")
    p.add_argument("--slo", action="store_true",
                   help="recompute queue-wait SLIs from --journal")
    p.add_argument("--threshold", type=float, default=300.0,
                   help="good-admission wait bound, seconds")
    p.add_argument("--target", type=float, default=0.99,
                   help="good-admission target fraction")
    args = p.parse_args(argv)

    journal = load_jsonl(args.journal) if args.journal else []
    if args.slo:
        if not args.journal:
            p.error("--slo requires --journal")
        return recompute_slo(journal, args.threshold, args.target, out)
    if not args.ledger:
        p.error("--ledger (or --slo with --journal) is required")
    rows = load_ledger_jsonl(args.ledger)
    if args.cycle is not None:
        return show_cycle(rows, args.cycle, journal, out)
    if args.cycles:
        return show_rows(rows, args.cycles, out)
    return summarize(rows, out)


if __name__ == "__main__":
    raise SystemExit(main())
