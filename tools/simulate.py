#!/usr/bin/env python
"""What-if capacity planning from the command line (docs/SIMULATOR.md).

Builds a generated cluster shape (or uses a recorded journal as the
baseline anchor), fans a scenario grid — quota factors x arrival-rate
factors — into ONE vmapped solver dispatch, and prints the per-scenario
KPI report as JSON. Deterministic: same arguments => byte-identical
output with --no-timing.

Usage:
    python tools/simulate.py --scenarios 64                  # 64-way batch
    python tools/simulate.py --sweep quota --factors 0.5,1,2,4
    python tools/simulate.py --target 'cohort-0' --factors 0.25,0.5
    python tools/simulate.py --journal decisions.jsonl       # + baseline
    python tools/simulate.py --trace --flap-at 500 --flap-count 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the what-if batch is a planning tool: default to the CPU backend
# unless the caller explicitly picked a platform
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from kueue_oss_tpu.config.configuration import SimulatorConfig  # noqa: E402
from kueue_oss_tpu.perf.generator import GeneratorConfig, generate  # noqa: E402
from kueue_oss_tpu.sim import (  # noqa: E402
    FlapEvent,
    ScenarioSpec,
    WhatIfEngine,
    arrival_sweep,
    cross,
    journal_baseline,
    kind_counts_per_cycle,
    load_events,
    quota_sweep,
    replay,
    simulate_trace,
)

#: deterministic default factor ladders for --scenarios N grids
_QUOTA_LADDER = (0.25, 0.5, 0.75, 1.25, 1.5, 2.0, 3.0, 4.0)
_ARRIVAL_LADDER = (0.25, 0.5, 0.75, 1.25, 1.5, 2.0, 2.5, 3.0)


def build_shape(shape: str):
    if shape == "baseline":
        cfg = GeneratorConfig.baseline()
    elif shape == "large-scale":
        cfg = GeneratorConfig.large_scale(preemption=False)
        cfg.nominal_quota = 200
    elif shape == "small":
        cfg = GeneratorConfig.large_scale(preemption=False)
        cfg.n_cohorts, cfg.cqs_per_cohort = 2, 3
        for wc in cfg.classes:
            wc.count = max(2, wc.count // 8)
    else:
        raise SystemExit(f"unknown shape {shape!r}")
    store, schedule = generate(cfg)
    return store, schedule


def build_specs(args) -> list[ScenarioSpec]:
    factors = ([float(f) for f in args.factors.split(",")]
               if args.factors else None)
    if args.sweep == "quota":
        return quota_sweep(factors or _QUOTA_LADDER, target=args.target,
                           seed=args.seed)
    if args.sweep == "arrival":
        return arrival_sweep(factors or _ARRIVAL_LADDER, seed=args.seed)
    # grid: quota x arrival, truncated to --scenarios
    q = quota_sweep(factors or _QUOTA_LADDER, target=args.target,
                    seed=args.seed)
    a = arrival_sweep(_ARRIVAL_LADDER, seed=args.seed)
    specs = cross(q, a)
    if args.scenarios:
        if len(specs) < args.scenarios:
            raise SystemExit(
                f"grid yields only {len(specs)} scenarios; pass more "
                f"--factors to reach {args.scenarios}")
        specs = specs[:args.scenarios]
    return specs


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    p = argparse.ArgumentParser(
        prog="simulate.py",
        description="Batched what-if simulation & capacity planning.")
    p.add_argument("--shape", default="small",
                   choices=["small", "baseline", "large-scale"],
                   help="generated cluster/backlog shape")
    p.add_argument("--scenarios", type=int, default=0,
                   help="grid size (quota x arrival factors, truncated)")
    p.add_argument("--sweep", default="grid",
                   choices=["grid", "quota", "arrival"])
    p.add_argument("--factors", default="",
                   help="comma-separated factors for the sweep")
    p.add_argument("--target", default="*",
                   help="node-name glob the quota factors apply to "
                        "(CQ or cohort; a cohort scales its subtree)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--parity", type=int, default=None,
                   help="scenarios to cross-check bit-identically "
                        "against the sequential oracle (default: "
                        "simulator config)")
    p.add_argument("--journal",
                   help="flight-recorder journal to anchor the report "
                        "(adds baseline KPIs + replay fidelity)")
    p.add_argument("--trace", action="store_true",
                   help="run ONE virtual-time trace simulation of the "
                        "first PERTURBED scenario (the one after the "
                        "'base' anchor; the report names which) "
                        "instead of the batched sweep")
    p.add_argument("--flap-at", type=float, action="append", default=[],
                   help="trace mode: flap nodes down at this virtual ms")
    p.add_argument("--flap-count", type=int, default=1)
    p.add_argument("--out", help="write the JSON report here instead "
                                 "of stdout")
    p.add_argument("--no-timing", action="store_true",
                   help="omit wall-clock timing (byte-identical reruns)")
    p.add_argument("--compact", action="store_true",
                   help="single-line JSON")
    args = p.parse_args(argv)

    specs = build_specs(args)
    store, schedule = build_shape(args.shape)

    if args.trace:
        spec = specs[1] if len(specs) > 1 else specs[0]
        spec.node_flaps = [
            FlapEvent(at_ms=ms, down=True, count=args.flap_count)
            for ms in args.flap_at]
        result = {"mode": "trace", "trace": simulate_trace(
            store, schedule, spec)}
    else:
        for g in schedule:
            store.add_workload(g.workload)
        cfg = SimulatorConfig(max_scenarios=max(1024, len(specs)))
        engine = WhatIfEngine(store, config=cfg)
        report = engine.run(specs, parity=args.parity)
        result = {"mode": "batched",
                  **report.to_dict(include_timing=not args.no_timing)}

    if args.journal:
        events = load_events(args.journal)
        result["journal"] = journal_baseline(events)
        # replay fidelity: the virtual-time replay must reproduce the
        # recorded decision kinds per cycle, exactly
        replayed = replay(events)
        result["journal"]["replay_faithful"] = (
            kind_counts_per_cycle(events)
            == kind_counts_per_cycle(replayed.events()))

    text = (json.dumps(result, sort_keys=True,
                       separators=(",", ":"))
            if args.compact else
            json.dumps(result, sort_keys=True, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"report written to {args.out}", file=sys.stderr)
    else:
        print(text, file=out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
