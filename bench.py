#!/usr/bin/env python
"""Benchmark: admission throughput vs the reference's own protocol.

PRIMARY metric (the honest headline): the reference's BASELINE
benchmark reproduced end-to-end — 5 cohorts x 6 CQs x 500 workloads =
15k with the generator's arrival schedule, workloads run and finish
freeing capacity, real wall-clock measured until done
(test/performance/scheduler; configs/baseline/rangespec.yaml:
351.1s mean => ~43 admissions/s). Same shape, same churn semantics,
apples-to-apples vs_baseline ratio.

Also reported (extra JSON fields):
- the contended LARGE-SCALE shape (1000 CQs, 50k pending, preemption
  enabled) drained one-shot by the preemption-capable full kernel
  (solve_backlog_full): admissions/s, DECISIONS/s (every workload
  admitted-or-parked), rounds, wall;
- per-cycle p50/p99 latency from a stepped per-round run;
- victim-plan parity vs the host scheduler on a 1/10-scale contended
  preemption shape (admitted-set + victim-set agreement);
- the uncontended fit-only drain (lean kernel) and the 640-node TAS
  sequential placement drain;
- per-scenario platform labels; a dead TPU tunnel is probed up front
  and falls back to the host backend with platform=cpu_fallback.

Measurement protocol: programs are AOT-compiled (lower().compile())
outside the timing window; the FIRST execution is timed (tunneled TPU
platforms can serve repeat executions from a result cache).

Prints exactly ONE JSON line on stdout; diagnostics go to stderr.
"""

import json
import os
import subprocess
import sys
import time

# Persistent XLA compilation cache (a production deployment runs with
# this on): scenario subprocesses inherit it, so the ladder compiles
# each program shape once per machine, not once per subprocess.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/kueue_oss_tpu_xla_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

if os.environ.get("BENCH_CPU") == "1":
    # force the host platform BEFORE jax initializes (the ambient TPU
    # PJRT plugin otherwise overrides JAX_PLATFORMS and blocks on the
    # tunneled device)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

#: reference implied admission throughput (BASELINE.md: 15k wl / 351.1s)
BASELINE_ADMISSIONS_PER_SEC = 42.7

#: stepped-cycle scenario lane count (serve-loop LATENCY config); the
#: production drain path sizes lanes to the CQ count (engine.h_max_cap)
CYCLE_LANES_DEFAULT = "64"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _build(preemption: bool, small: bool):
    from kueue_oss_tpu.core.queue_manager import QueueManager
    from kueue_oss_tpu.perf.generator import GeneratorConfig, generate
    from kueue_oss_tpu.solver.engine import SolverEngine

    config = GeneratorConfig.large_scale(preemption=preemption)
    if not preemption:
        config.nominal_quota = 200  # >= per-CQ demand: everything fits
    if small:
        config.n_cohorts, config.cqs_per_cohort = 2, 10
    if os.environ.get("BENCH_COHORTS"):
        config.n_cohorts = int(os.environ["BENCH_COHORTS"])
    if os.environ.get("BENCH_CQS"):
        config.cqs_per_cohort = int(os.environ["BENCH_CQS"])
    store, schedule = generate(config)
    for g in schedule:
        store.add_workload(g.workload)
    queues = QueueManager(store)
    return store, queues, SolverEngine(store, queues)


def _tunnel_rtt_ms() -> float:
    """Median dispatch+scalar-fetch round trip for a trivial program —
    the per-invocation floor a tunneled device adds (a locally-attached
    TPU pays microseconds). Reported so drain walls can be read net of
    test-rig transport."""
    import jax
    import jax.numpy as jnp

    s = jnp.int32(1)
    add = jax.jit(lambda a: a + 1).lower(s).compile()
    times = []
    for _ in range(5):
        t0 = time.monotonic()
        int(add(s))
        times.append((time.monotonic() - t0) * 1000)
    times.sort()
    return round(times[len(times) // 2], 2)


def _warm_solver_programs(config) -> None:
    """AOT-compile the drain programs outside the timing window.

    Measurement-protocol parity with every other scenario (which
    lower().compile() before timing): a twin store with the full
    schedule pre-loaded is drained once, compiling the solver programs
    for the same padded shape and caps the timed run will use. The twin
    store is discarded; the persistent XLA cache and the in-process
    executable cache carry the programs into the timed Simulator run.
    """
    import time as _time

    from kueue_oss_tpu.core.queue_manager import QueueManager
    from kueue_oss_tpu.perf.generator import generate
    from kueue_oss_tpu.solver.engine import SolverEngine

    t0 = _time.monotonic()
    store, schedule = generate(config)
    for g in schedule:
        store.add_workload(g.workload)
    queues = QueueManager(store)
    engine = SolverEngine(store, queues)
    engine.pad_to = len(schedule)
    try:
        engine.drain(now=0.0, verify=True)
    except Exception as e:  # warm-up must never fail the scenario
        log(f"[warmup] drain failed (continuing cold): {e}")
    log(f"[warmup] solver programs compiled in "
        f"{_time.monotonic() - t0:.1f}s")


def _degradation_counts() -> dict:
    """Solver-backend degradation events recorded by this process
    (scenario subprocesses start with a clean registry, so these are
    per-scenario counts)."""
    from kueue_oss_tpu import metrics as kmetrics

    return {
        "solver_fallback_count": int(
            kmetrics.solver_fallback_total.total()),
        "breaker_trips": int(
            kmetrics.solver_breaker_trips_total.total()),
    }


def run_scenario(scenario: str) -> dict:
    """Executed inside a fresh subprocess: one timed drain."""
    import numpy as np
    import jax

    from kueue_oss_tpu.util import xla_cache

    xla_cache.enable()
    small = os.environ.get("BENCH_SMALL") == "1"

    if scenario == "lean":
        from kueue_oss_tpu.solver.kernels import solve_backlog, to_device

        store, queues, engine = _build(preemption=False, small=small)
        problem, _ = engine.export()
        tensors = to_device(problem)
        jax.block_until_ready(tensors)
        compiled = solve_backlog.lower(tensors).compile()
        t0 = time.monotonic()
        out = compiled(tensors)
        admitted, opt, admit_round, parked, rounds, usage = out
        n_admitted = int(np.asarray(admitted).sum())   # fetch in-window
        n_rounds = int(rounds)
        elapsed = time.monotonic() - t0
        return {
            "scenario": scenario,
            "workloads": problem.n_workloads,
            "cluster_queues": problem.n_cqs,
            "admitted": n_admitted,
            "rounds": n_rounds,
            "seconds": elapsed,
            "tunnel_rtt_ms": _tunnel_rtt_ms(),
        }

    if scenario == "preempt":
        from kueue_oss_tpu.solver.full_kernels import (
            make_full_solver,
            to_device_full,
        )
        from kueue_oss_tpu.solver.tensors import export_problem

        store, queues, engine = _build(preemption=True, small=small)
        pending = engine.pending_backlog()
        problem = export_problem(store, pending, include_admitted=True)
        g_max = int(problem.cq_ngroups.max())
        h_max, p_max = engine._size_caps(problem)
        if os.environ.get("BENCH_HMAX"):
            h_max = int(os.environ["BENCH_HMAX"])
        if os.environ.get("BENCH_PMAX"):
            p_max = int(os.environ["BENCH_PMAX"])
        round_cap = int(os.environ.get("BENCH_ROUND_CAP", "2048"))
        log(f"[preempt] W={problem.n_workloads} C={problem.n_cqs} "
            f"g_max={g_max} h_max={h_max} p_max={p_max} cap={round_cap}")
        tensors = to_device_full(problem)
        jax.block_until_ready(tensors)
        solver = make_full_solver(g_max, h_max, p_max,
                                  round_cap=round_cap)
        compiled = solver.lower(tensors).compile()
        t0 = time.monotonic()
        out = compiled(tensors)
        # the timing window ENDS at a host-side scalar fetch: on the
        # tunneled TPU platform block_until_ready returns before remote
        # execution completes (round-5 probe: a 49-round drain "took"
        # 1.69ms, less than one tunnel RTT), so only a materialized
        # result bounds the wall honestly
        (admitted, opt, admit_round, parked, rounds, usage, wl_usage,
         _reason) = out
        n_admitted = int(np.asarray(admitted).sum())
        n_rounds = int(rounds)
        elapsed = time.monotonic() - t0
        return {
            "scenario": scenario,
            "workloads": problem.n_workloads,
            "cluster_queues": problem.n_cqs,
            "admitted": n_admitted,
            "rounds": n_rounds,
            "seconds": elapsed,
            "tunnel_rtt_ms": _tunnel_rtt_ms(),
        }

    if scenario == "hetero":
        # heterogeneous contended drain: 2 fungible flavors x (cpu,
        # memory) + an accelerator resource group + pod-group workloads,
        # preemption enabled — exercises the option-group axis, the
        # flavor walk, and per-group flavor decode at perf scale
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.perf.generator import GeneratorConfig, generate
        from kueue_oss_tpu.solver.engine import SolverEngine
        from kueue_oss_tpu.solver.full_kernels import (
            make_full_solver,
            to_device_full,
        )
        from kueue_oss_tpu.solver.tensors import export_problem

        n_cohorts = int(os.environ.get("BENCH_COHORTS", "10"))
        cqs = int(os.environ.get("BENCH_CQS", "50"))
        store, schedule = generate(
            GeneratorConfig.heterogeneous(n_cohorts, cqs))
        for g in schedule:
            store.add_workload(g.workload)
        queues = QueueManager(store)
        engine = SolverEngine(store, queues)
        pending = engine.pending_backlog()
        problem = export_problem(store, pending, include_admitted=True)
        g_max = int(problem.cq_ngroups.max())
        h_max, p_max = engine._size_caps(problem)
        log(f"[hetero] W={problem.n_workloads} C={problem.n_cqs} "
            f"g_max={g_max} h_max={h_max} p_max={p_max}")
        tensors = to_device_full(problem)
        jax.block_until_ready(tensors)
        solver = make_full_solver(g_max, h_max, p_max, round_cap=2048)
        compiled = solver.lower(tensors).compile()
        t0 = time.monotonic()
        out = compiled(tensors)
        n_admitted = int(np.asarray(out[0]).sum())     # fetch in-window
        n_rounds = int(out[4])
        elapsed = time.monotonic() - t0
        return {
            "scenario": scenario,
            "workloads": problem.n_workloads,
            "cluster_queues": problem.n_cqs,
            "flavor_options": int(problem.cq_nflavors.max()),
            "resource_groups": g_max,
            "admitted": n_admitted,
            "rounds": n_rounds,
            "seconds": elapsed,
        }

    if scenario == "cycles":
        # per-cycle latency: dispatch round_body one round at a time.
        # Lanes default to the serve-loop's LATENCY config (64) — the
        # production drain path sizes lanes to the CQ count for
        # throughput (engine.h_max_cap), which trades per-round latency
        # for ~10x fewer rounds; preempt_drain_* reports that config.
        import jax.numpy as jnp

        from kueue_oss_tpu.solver.full_kernels import (
            _init_state,
            potential_available_all,
            round_body,
            to_device_full,
        )
        from kueue_oss_tpu.solver.tensors import export_problem

        store, queues, engine = _build(preemption=True, small=small)
        pending = engine.pending_backlog()
        problem = export_problem(store, pending, include_admitted=True)
        g_max = int(problem.cq_ngroups.max())
        _h_ignored, p_max = engine._size_caps(problem)
        h_max = int(os.environ.get("BENCH_HMAX", CYCLE_LANES_DEFAULT))
        log(f"[cycles] W={problem.n_workloads} C={problem.n_cqs} "
            f"h_max={h_max} p_max={p_max}")
        t = to_device_full(problem)
        pot = potential_available_all(t)
        step = jax.jit(lambda tt, st: round_body(tt, st, pot, g_max,
                                                 h_max, p_max)[0])
        state = _init_state(t, g_max)
        state = step(t, state)                         # compile + round 0
        bool(state["progress"])
        times = []
        max_rounds = int(os.environ.get("BENCH_CYCLES", "40"))
        for _ in range(max_rounds):
            t0 = time.monotonic()
            state = step(t, state)
            progress = bool(state["progress"])         # fetch in-window
            times.append(time.monotonic() - t0)
            if not progress:
                break
        import numpy as np

        times_ms = np.asarray(times) * 1000
        return {
            "scenario": scenario,
            "rounds_timed": len(times),
            "cycle_ms_p50": float(np.percentile(times_ms, 50)),
            "cycle_ms_p99": float(np.percentile(times_ms, 99)),
            "cycle_ms_mean": float(times_ms.mean()),
            "tunnel_rtt_ms": _tunnel_rtt_ms(),
        }

    if scenario == "tas":
        # the reference's TAS perf shape: 640 nodes (1 block x 10 racks
        # x 64 hosts, 96 CPU each), 15k sequential placements with the
        # generator's small/medium/large required/preferred/balanced mix
        # (configs/tas/generator.yaml), drained ON DEVICE by the
        # sequential placer (one lax.scan step per workload). Baseline:
        # 15k wl / 401.5s mean wall => ~37 adm/s
        # (configs/tas/rangespec.yaml cmd.maxWallMs).
        import random as _random

        import jax.numpy as jnp

        from kueue_oss_tpu.api.types import Node
        from kueue_oss_tpu.solver.tas_kernels import (
            build_levels,
            make_sequential_placer,
        )
        from kueue_oss_tpu.tas.snapshot import build_tas_flavor_snapshot

        HOSTL = "kubernetes.io/hostname"
        BLOCK = "cloud.provider.com/topology-block"
        RACK = "cloud.provider.com/topology-rack"
        levels_names = [BLOCK, RACK, HOSTL]
        nodes = []
        for r in range(10):
            for h in range(64):
                nodes.append(Node(
                    name=f"n-{r}-{h}",
                    labels={BLOCK: "b0", RACK: f"r{r}"},
                    allocatable={"cpu": 96_000}))
        snap = build_tas_flavor_snapshot("default", levels_names, nodes)
        levels = build_levels(snap)
        rng = _random.Random(640)
        M = int(os.environ.get("BENCH_TAS_WL", "15000"))
        mix = [(2, 500), (5, 2000), (20, 5000)]
        modes = ["required", "preferred", "unconstrained"]
        R = len(levels.resources)
        per_pod = np.zeros((M, R), dtype=np.int32)
        count = np.zeros((M,), dtype=np.int32)
        level = np.zeros((M,), dtype=np.int32)
        required = np.zeros((M,), dtype=bool)
        unconstrained = np.zeros((M,), dtype=bool)
        cpu_col = levels.resources.index("cpu")
        rack_idx = levels_names.index(RACK)
        for i in range(M):
            pods, cpu = mix[rng.randrange(3)]
            mode = modes[rng.randrange(3)]
            per_pod[i, cpu_col] = cpu
            count[i] = pods
            required[i] = mode == "required"
            unconstrained[i] = mode == "unconstrained"
            level[i] = (len(levels_names) - 1 if mode == "unconstrained"
                        else rack_idx)
        least_free = unconstrained & snap.profile_mixed
        place_all = make_sequential_placer(levels.parents)
        args = (jnp.asarray(levels.leaf_capacity), jnp.asarray(per_pod),
                jnp.asarray(count), jnp.asarray(level),
                jnp.asarray(required), jnp.asarray(unconstrained),
                jnp.asarray(least_free))
        jax.block_until_ready(args)
        compiled = place_all.lower(*args).compile()
        t0 = time.monotonic()
        sels, oks, _cap = compiled(*args)
        placed = int(np.asarray(oks).sum())            # fetch in-window
        elapsed = time.monotonic() - t0

        # slice + leader mix through the extended placer (the feature
        # matrix the plain 15k mix avoids): ring slices bound to racks,
        # driver+workers groups with a leader pod
        from kueue_oss_tpu.solver.tas_kernels import (
            make_sequential_placer_ext,
        )

        M2 = int(os.environ.get("BENCH_TAS_EXT_WL", "3000"))
        per_pod2 = np.zeros((M2, R), dtype=np.int32)
        count2 = np.zeros((M2,), dtype=np.int32)
        level2 = np.zeros((M2,), dtype=np.int32)
        required2 = np.zeros((M2,), dtype=bool)
        sl_size = np.ones((M2,), dtype=np.int32)
        sl_level = np.full((M2,), len(levels_names) - 1, dtype=np.int32)
        leader2 = np.zeros((M2, R), dtype=np.int32)
        for i in range(M2):
            kind = rng.randrange(3)
            per_pod2[i, cpu_col] = 4
            required2[i] = True
            if kind == 0:            # 2 rack-bound slices of 4
                count2[i], sl_size[i] = 8, 4
                sl_level[i] = rack_idx
                level2[i] = 0
            elif kind == 1:          # 4 host-bound slices of 2
                count2[i], sl_size[i] = 8, 2
                sl_level[i] = len(levels_names) - 1
                level2[i] = rack_idx
            else:                    # leader + 6 workers in a rack
                count2[i] = 6
                level2[i] = rack_idx
                leader2[i, cpu_col] = 8
        place_ext = make_sequential_placer_ext(levels.parents)
        args2 = (jnp.asarray(levels.leaf_capacity),
                 jnp.asarray(per_pod2), jnp.asarray(count2),
                 jnp.asarray(level2), jnp.asarray(required2),
                 jnp.zeros((M2,), dtype=bool),
                 jnp.zeros((M2,), dtype=bool),
                 jnp.asarray(sl_size), jnp.asarray(sl_level),
                 jnp.asarray(leader2),
                 jnp.asarray((leader2 > 0).any(axis=1)))
        jax.block_until_ready(args2)
        compiled2 = place_ext.lower(*args2).compile()
        t0 = time.monotonic()
        _sels2, _leads2, oks2, _cap2 = compiled2(*args2)
        ext_placed = int(np.asarray(oks2).sum())       # fetch in-window
        ext_elapsed = time.monotonic() - t0
        return {
            "scenario": scenario,
            "workloads": M,
            "nodes": len(nodes),
            "placed": placed,
            "seconds": elapsed,
            "ext_workloads": M2,
            "ext_placed": ext_placed,
            "ext_seconds": ext_elapsed,
        }

    if scenario == "tas_drain":
        # PRODUCTION TAS path: the same 640-node / 15k-workload TAS
        # shape, but through SolverEngine.drain — quota via the kernel,
        # placement via the sequential device placer, commits applied to
        # the store (round-5: device TAS is no longer bench-only). The
        # wall includes export, solve, placement, and plan application.
        import random as _random

        from kueue_oss_tpu.api.types import (
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            Node,
            PodSet,
            PodSetTopologyRequest,
            ResourceFlavor,
            ResourceGroup,
            ResourceQuota,
            Topology,
            Workload,
        )
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.core.store import Store
        from kueue_oss_tpu.solver.engine import SolverEngine

        from kueue_oss_tpu.api.types import Cohort

        HOSTL = "kubernetes.io/hostname"
        BLOCK = "cloud.provider.com/topology-block"
        RACK = "cloud.provider.com/topology-rack"
        store = Store()
        store.upsert_topology(Topology(name="default",
                                       levels=[BLOCK, RACK, HOSTL]))
        store.upsert_resource_flavor(ResourceFlavor(
            name="tas", topology_name="default"))
        for r in range(10):
            for h in range(64):
                store.upsert_node(Node(
                    name=f"n-{r}-{h}", labels={BLOCK: "b0", RACK: f"r{r}"},
                    allocatable={"cpu": 96}))
        # the reference's TAS shape: baseline's 5 cohorts x 6 CQs over
        # the one topology (configs/tas/generator.yaml), nominal 20 +
        # borrowing
        n_cq = 0
        for c in range(5):
            store.upsert_cohort(Cohort(name=f"co{c}"))
            for qi in range(6):
                name = f"cq-{c}-{qi}"
                store.upsert_cluster_queue(ClusterQueue(
                    name=name, cohort=f"co{c}",
                    resource_groups=[ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[FlavorQuotas(name="tas", resources=[
                            ResourceQuota(name="cpu", nominal=20,
                                          borrowing_limit=100)])])]))
                store.upsert_local_queue(LocalQueue(
                    name=f"lq-{c}-{qi}", cluster_queue=name))
                n_cq += 1
        rng = _random.Random(640)
        M = int(os.environ.get("BENCH_TAS_WL", "15000"))
        mix = [1, 5, 20]
        for i in range(M):
            cpu = mix[rng.randrange(3)]
            mode = rng.randrange(3)
            tr = (PodSetTopologyRequest(required=RACK) if mode == 0
                  else PodSetTopologyRequest(preferred=RACK) if mode == 1
                  else PodSetTopologyRequest(unconstrained=True))
            c, qi = rng.randrange(5), rng.randrange(6)
            store.add_workload(Workload(
                name=f"w{i}", queue_name=f"lq-{c}-{qi}", uid=i + 1,
                creation_time=float(i),
                podsets=[PodSet(name="main", count=1,
                                requests={"cpu": cpu},
                                topology_request=tr)]))
        queues = QueueManager(store)
        engine = SolverEngine(store, queues)
        t0 = time.monotonic()
        result = engine.drain(now=0.0)
        elapsed = time.monotonic() - t0
        placed = sum(
            1 for wl in store.workloads.values()
            if wl.is_quota_reserved and wl.status.admission
            .podset_assignments[0].topology_assignment is not None)
        return {
            "scenario": scenario,
            "workloads": M,
            "nodes": 640,
            "admitted": result.admitted,
            "placed_with_topology": placed,
            "rounds": result.rounds,
            "solver_seconds": result.solver_time_s,
            "apply_seconds": result.apply_time_s,
            "seconds": elapsed,
        }

    if scenario == "sim_baseline":
        # the reference's OWN benchmark protocol (minimalkueue +
        # test/performance/scheduler runner): submit the baseline shape
        # (5 cohorts x 6 CQs x 500 workloads = 15k with arrival
        # schedule; workloads run and finish, freeing capacity) and
        # measure real wall until done. Reference: 15k / 351.1s mean =>
        # ~43 admissions/s (configs/baseline/rangespec.yaml).
        # BENCH_SOLVER=1 routes every backlog drain through the TPU
        # solver engine (Scheduler(solver="auto"), verify-then-assume);
        # otherwise the host control plane runs alone.
        from kueue_oss_tpu.perf.generator import GeneratorConfig, generate
        from kueue_oss_tpu.perf.runner import Simulator

        solver = "auto" if os.environ.get("BENCH_SOLVER") == "1" else None
        if solver is not None:
            _warm_solver_programs(GeneratorConfig.baseline())
        store, schedule = generate(GeneratorConfig.baseline())
        stats = Simulator(store, schedule, solver=solver).run()
        return {
            "scenario": scenario,
            "workloads": stats.total_workloads,
            "admitted": stats.admitted,
            "seconds": stats.real_seconds,
            "sim_wall_ms": stats.sim_wall_ms,
            "cycles": stats.cycles,
            "adm_per_s": stats.admissions_per_real_second,
            **_degradation_counts(),
        }

    if scenario == "sim_large":
        # the reference's LARGE-SCALE config (1000 CQs, 50k workloads)
        # through the same churned Simulator protocol as sim_baseline —
        # arrivals + finishes freeing capacity, real wall-clock.
        # Reference target: maxWallMs 1,200,000 for 50k => ~41.7 adm/s
        # (configs/large-scale/rangespec.yaml placeholder).
        from kueue_oss_tpu.perf.generator import GeneratorConfig, generate
        from kueue_oss_tpu.perf.runner import Simulator

        solver = "auto" if os.environ.get("BENCH_SOLVER") == "1" else None
        if solver is not None:
            _warm_solver_programs(
                GeneratorConfig.large_scale(preemption=True))
        store, schedule = generate(
            GeneratorConfig.large_scale(preemption=True))
        stats = Simulator(store, schedule, solver=solver).run()
        return {
            "scenario": scenario,
            "workloads": stats.total_workloads,
            "admitted": stats.admitted,
            "seconds": stats.real_seconds,
            "cycles": stats.cycles,
            "adm_per_s": stats.admissions_per_real_second,
            **_degradation_counts(),
        }

    if scenario == "chaos":
        # seeded fault storm (kueue_oss_tpu/chaos) through the full
        # scheduler routing: the sidecar crashes, garbles frames, and
        # returns corrupt plans on a seeded schedule; the run must
        # finish with full capacity admitted via retries + host-cycle
        # fallback, and the JSON tail records the degradation events
        # (docs/ROBUSTNESS.md).
        import tempfile

        from kueue_oss_tpu.api.types import (
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            PodSet,
            ResourceFlavor,
            ResourceGroup,
            ResourceQuota,
            Workload,
        )
        from kueue_oss_tpu.chaos import (
            CORRUPT_PLAN,
            CRASH,
            GARBLE,
            OK,
            TRUNCATE,
            ChaosSolverServer,
            FaultInjector,
        )
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.core.store import Store
        from kueue_oss_tpu.scheduler.scheduler import Scheduler
        from kueue_oss_tpu.solver.engine import SolverEngine
        from kueue_oss_tpu.solver.service import SolverClient

        n_cqs = int(os.environ.get("BENCH_CHAOS_CQS", "8"))
        quota = int(os.environ.get("BENCH_CHAOS_QUOTA", "32"))
        n_wl = int(os.environ.get("BENCH_CHAOS_WL", "1024"))
        store = Store()
        store.upsert_resource_flavor(ResourceFlavor(name="f"))
        for i in range(n_cqs):
            store.upsert_cluster_queue(ClusterQueue(
                name=f"cq{i}", resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="f", resources=[
                        ResourceQuota(name="cpu", nominal=quota)])])]))
            store.upsert_local_queue(LocalQueue(
                name=f"lq{i}", cluster_queue=f"cq{i}"))
        for i in range(n_wl):
            store.add_workload(Workload(
                name=f"w{i}", queue_name=f"lq{i % n_cqs}", uid=i + 1,
                creation_time=float(i),
                podsets=[PodSet(name="main", count=1,
                                requests={"cpu": 1})]))
        queues = QueueManager(store)
        path = os.path.join(tempfile.mkdtemp(), "solver.sock")
        # deterministic fault prefix (a small backlog may need only a
        # couple of solver calls — the storm must still be exercised),
        # then the seeded weighted mix
        injector = FaultInjector(
            schedule=[CRASH, GARBLE, CORRUPT_PLAN],
            weights={CRASH: 2, GARBLE: 1, TRUNCATE: 1,
                     CORRUPT_PLAN: 1, OK: 3},
            seed=int(os.environ.get("BENCH_CHAOS_SEED", "42")))
        srv = ChaosSolverServer(path, injector)
        srv.serve_in_background()
        try:
            sched = Scheduler(store, queues, solver_min_backlog=64)
            engine = SolverEngine(
                store, queues, scheduler=sched,
                remote=SolverClient(path, timeout_s=30.0, max_retries=1,
                                    backoff_base_s=0.01))
            sched.solver = engine
            t0 = time.monotonic()
            cycles = sched.run_until_quiet(now=0.0, tick=1.0)
            elapsed = time.monotonic() - t0
        finally:
            srv.shutdown()
            srv.server_close()
        admitted = sum(1 for w in store.workloads.values()
                       if w.is_quota_reserved)
        return {
            "scenario": scenario,
            "workloads": n_wl,
            "capacity": n_cqs * quota,
            "admitted": admitted,
            "cycles": cycles,
            "seconds": elapsed,
            "faults_injected": injector.faults_injected(),
            "faults_by_kind": injector.injected,
            **_degradation_counts(),
        }

    if scenario == "chaoscampaign":
        # composed-fault chaos campaigns with the convergence oracle
        # (kueue_oss_tpu/chaos/campaign.py, docs/ROBUSTNESS.md "Chaos
        # campaigns"): every profile storms one subsystem's degradation
        # ladder against a live plane, then must converge back to the
        # fault-free twin's exact bytes within the bound.
        import tempfile

        from kueue_oss_tpu.chaos.campaign import PROFILES, run_campaign

        seed = int(os.environ.get("BENCH_CAMPAIGN_SEED", "42"))
        results = []
        profiles = {}
        t0 = time.monotonic()
        for profile in PROFILES:
            kw = {}
            if profile == "kill-storm":
                kw["persistence_dir"] = tempfile.mkdtemp()
            r = run_campaign(profile, seed=seed, **kw)
            results.append(r)
            profiles[profile] = r.to_dict()
            log(f"[campaign:{profile}] ok={r.ok} "
                f"conv={r.convergence_cycles} "
                f"lvl={r.max_degradation_level} "
                f"avail={r.availability:.2f}")
        return {
            "scenario": scenario,
            "seed": seed,
            "seconds": time.monotonic() - t0,
            "profiles": profiles,
            # aggregate oracle verdicts: worst case across profiles
            "converged_all": all(r.ok for r in results),
            "recovered_identical": all(r.recovered_identical
                                       for r in results),
            "convergence_cycles": max(r.convergence_cycles
                                      for r in results),
            "max_degradation_level": max(r.max_degradation_level
                                         for r in results),
            "availability": min(r.availability for r in results),
            "unavailable_wall_ms": round(sum(r.unavailable_wall_ms
                                             for r in results), 3),
            "invariant_violations": sum(r.invariant_violations
                                        for r in results),
            "faults_injected": sum(r.faults_injected for r in results),
            **_degradation_counts(),
        }

    if scenario == "delta":
        # delta-sync steady state on the 50k x 1k churn shape
        # (docs/SOLVER_PROTOCOL.md): a real sidecar on a unix socket,
        # engine sessions on. Cycle 0 ships the full SYNC; each churn
        # cycle then finishes ~0.5% of the admitted set, submits the
        # same number of new arrivals, and drains — steady-state cycles
        # must ship DELTA frames. Reports wire bytes per cycle vs the
        # full frame, the resync count, and the steady-state solve wall
        # p50 (the engine's solve window ends at host-side scalar
        # fetches, per the round-5 timing discipline).
        import tempfile

        import numpy as np

        from kueue_oss_tpu import metrics as kmetrics
        from kueue_oss_tpu.api.types import PodSet, Workload
        from kueue_oss_tpu.scheduler.scheduler import Scheduler
        from kueue_oss_tpu.solver.service import SolverClient, SolverServer

        store, queues, engine = _build(preemption=True, small=small)
        sched = Scheduler(store, queues)
        engine.scheduler = sched
        path = os.path.join(tempfile.mkdtemp(), "solver.sock")
        srv = SolverServer(path)
        srv.serve_in_background()
        n_wl = len(store.workloads)
        churn = int(os.environ.get("BENCH_DELTA_CHURN",
                                   str(max(1, n_wl // 200))))
        n_cycles = int(os.environ.get("BENCH_DELTA_CYCLES", "8"))
        warm_cycles = 2
        # keep ONE padded capacity across the run: churned arrivals must
        # not cross a power-of-two boundary and force resyncs
        engine.pad_to = n_wl + churn * (n_cycles + warm_cycles) + 1
        try:
            engine.remote = SolverClient(path)
            resync0 = kmetrics.solver_resync_total.total()
            engine.drain(now=0.0, verify=True)
            full_frame = engine.remote.last_frame
            lqs = sorted({w.queue_name for w in store.workloads.values()})
            proto = next(iter(store.workloads.values()))
            req = dict(proto.podsets[0].requests)
            uid = max(w.uid for w in store.workloads.values()) + 1
            t_base = max(w.creation_time
                         for w in store.workloads.values()) + 1.0

            def churn_cycle(cyc):
                admitted = [k for k, w in store.workloads.items()
                            if w.is_quota_reserved and not w.is_finished]
                for k in admitted[:churn]:
                    sched.finish_workload(k, now=float(cyc))
                for j in range(churn):
                    i = uid + cyc * churn + j
                    store.add_workload(Workload(
                        name=f"churn-{cyc}-{j}",
                        queue_name=lqs[i % len(lqs)], uid=i,
                        creation_time=t_base + cyc * churn + j,
                        podsets=[PodSet(name="main", count=1,
                                        requests=dict(req))]))
                result = engine.drain(now=float(cyc), verify=True)
                return result, engine.remote.last_frame

            for c in range(1, warm_cycles + 1):  # churn settles in
                churn_cycle(c)
            frames, solve_walls = [], []
            for c in range(warm_cycles + 1, warm_cycles + 1 + n_cycles):
                result, frame = churn_cycle(c)
                frames.append(frame)
                solve_walls.append(result.solver_time_s)
            resyncs = int(kmetrics.solver_resync_total.total() - resync0)
        finally:
            srv.shutdown()
            srv.server_close()
        delta_frames = [n for kind, n in frames if kind == "delta"]
        delta_bytes = (float(np.median(delta_frames))
                       if delta_frames else 0.0)
        walls_ms = np.asarray(solve_walls) * 1000
        return {
            "scenario": scenario,
            "workloads": n_wl,
            "churn_per_cycle": churn,
            "cycles": n_cycles,
            "full_frame_bytes": int(full_frame[1]),
            "delta_bytes_per_cycle": delta_bytes,
            "bytes_ratio": (round(full_frame[1] / delta_bytes, 1)
                            if delta_bytes else None),
            "delta_frames": len(delta_frames),
            "nondelta_frames": len(frames) - len(delta_frames),
            "resync_count": resyncs,
            "frames_by_kind": engine.remote.frames_by_kind,
            "cycle_ms_p50": float(np.percentile(walls_ms, 50)),
            "cycle_ms_p99": float(np.percentile(walls_ms, 99)),
        }

    if scenario == "multichip":
        # PRODUCTION multi-chip path — no dry-run entry point left: the
        # engine + delta-session stack drains the large fit-only shape
        # on the mesh arm (sharded resident state, donated row
        # scatters, compact plans), with churn cycles measuring the
        # steady state and a single-chip twin proving the plans stay
        # identical. Runs on a virtual host mesh when no multi-chip
        # accelerator is attached (honest mesh_devices/platform labels;
        # the virtual mesh exercises the same XLA partitioner).
        import numpy as np

        from kueue_oss_tpu import metrics as kmetrics
        from kueue_oss_tpu.api.types import PodSet, Workload
        from kueue_oss_tpu.scheduler.scheduler import Scheduler
        from kueue_oss_tpu.solver import meshutil

        mesh = meshutil.detect_mesh()
        n_dev = meshutil.mesh_devices(mesh)
        if n_dev < 2:
            return {"scenario": scenario, "skipped": True,
                    "reason": "single device; no mesh to measure"}

        def build_env():
            store, queues, engine = _build(preemption=False, small=small)
            if len(store.workloads) % n_dev == 0:
                # force the uneven-shard padding path (W % n_dev != 0)
                proto = next(iter(store.workloads.values()))
                store.add_workload(Workload(
                    name="uneven-extra", queue_name=proto.queue_name,
                    uid=10_000_000, creation_time=0.5,
                    podsets=[PodSet(name="main", count=1,
                                    requests=dict(
                                        proto.podsets[0].requests))]))
            sched = Scheduler(store, queues)
            engine.scheduler = sched
            return store, queues, sched, engine

        store, queues, sched, engine = build_env()
        n_wl = len(store.workloads)
        churn = int(os.environ.get("BENCH_MC_CHURN",
                                   str(max(1, n_wl // 200))))
        n_cycles = int(os.environ.get("BENCH_MC_CYCLES", "6"))
        warm = 2
        lqs = sorted({w.queue_name for w in store.workloads.values()})
        proto = next(iter(store.workloads.values()))
        req = dict(proto.podsets[0].requests)
        uid0 = max(w.uid for w in store.workloads.values()) + 1
        t_base = max(w.creation_time
                     for w in store.workloads.values()) + 1.0

        def run_trace(engine, store, sched, tag):
            engine.pad_to = n_wl + churn * (n_cycles + warm) + 1
            t0 = time.monotonic()
            engine.drain(now=0.0, verify=True)
            first_wall = time.monotonic() - t0
            walls = []
            for cyc in range(1, warm + n_cycles + 1):
                admitted = [k for k, w in store.workloads.items()
                            if w.is_quota_reserved and not w.is_finished]
                for k in admitted[:churn]:
                    sched.finish_workload(k, now=float(cyc))
                for j in range(churn):
                    i = uid0 + cyc * churn + j
                    store.add_workload(Workload(
                        name=f"churn-{tag}-{cyc}-{j}",
                        queue_name=lqs[i % len(lqs)], uid=i,
                        creation_time=t_base + cyc * churn + j,
                        podsets=[PodSet(name="main", count=1,
                                        requests=dict(req))]))
                result = engine.drain(now=float(cyc), verify=True)
                if cyc > warm:
                    walls.append(result.solver_time_s)
            return first_wall, walls

        engine.mesh_force = True
        engine.mesh_min_workloads = 0
        first_wall, walls = run_trace(engine, store, sched, "m")
        assert engine.last_drain_arm == "mesh", engine.last_drain_arm
        mesh_admitted = {k for k, w in store.workloads.items()
                        if w.is_quota_reserved}

        # single-chip twin over the byte-identical churn trace
        store2, queues2, sched2, engine2 = build_env()
        engine2.mesh_mode = "off"
        _fw2, walls2 = run_trace(engine2, store2, sched2, "m")
        single_admitted = {k for k, w in store2.workloads.items()
                          if w.is_quota_reserved}

        dev = engine._device_states.get("lean-mesh")
        sess = engine._delta_sessions.get("lean")
        imb = kmetrics.solver_shard_imbalance
        walls_ms = np.asarray(walls) * 1000
        walls2_ms = np.asarray(walls2) * 1000

        # preemption drain (full kernel, lane-sharded) through the
        # production engine at the 1/10 contended shape
        store_p, queues_p, engine_p = _build(preemption=True, small=True)
        engine_p.scheduler = Scheduler(store_p, queues_p)
        engine_p.mesh_force = True
        engine_p.mesh_min_workloads = 0
        t0 = time.monotonic()
        rp = engine_p.drain(now=0.0, verify=True)
        preempt_wall = time.monotonic() - t0

        return {
            "scenario": scenario,
            "workloads": n_wl,
            "mesh_devices": n_dev,
            "uneven_shards": n_wl % n_dev != 0,
            "churn_per_cycle": churn,
            "cycles": n_cycles,
            "first_drain_seconds": round(first_wall, 3),
            "mesh_drain_ms_p50": float(np.percentile(walls_ms, 50)),
            "single_drain_ms_p50": float(np.percentile(walls2_ms, 50)),
            "shard_imbalance_mean": round(
                imb.sum() / max(imb.count(), 1), 4),
            "plans_identical": mesh_admitted == single_admitted,
            "donated_update_bytes_per_cycle": (
                dev.donated_update_bytes // max(dev.delta_updates, 1)
                if dev else 0),
            "avoided_copy_bytes_per_cycle": (
                dev.avoided_copy_bytes // max(dev.delta_updates, 1)
                if dev else 0),
            "full_upload_bytes": (
                dev.full_upload_bytes // max(dev.full_uploads, 1)
                if dev else 0),
            "delta_epochs": dev.delta_updates if dev else 0,
            "full_uploads": dev.full_uploads if dev else 0,
            "session_delta_syncs": sess.delta_syncs if sess else 0,
            "session_full_syncs": sess.full_syncs if sess else 0,
            "preempt_mesh_admitted": rp.admitted,
            "preempt_mesh_rounds": rp.rounds,
            "preempt_mesh_seconds": round(preempt_wall, 3),
            "preempt_mesh_arm": engine_p.last_drain_arm,
            **_degradation_counts(),
        }

    if scenario == "podscale":
        # Pod-scale solver (docs/SOLVER_PROTOCOL.md "Pod-scale
        # sessions") on the virtual host mesh — no ICI, so the numbers
        # bound correctness and steady-state wall, not TPU throughput.
        # Three measurements: the workload-row-sharded FULL
        # (preemption) drain p50 with a byte-identity twin against the
        # single-chip kernel (uneven shard count forced), churned-
        # session shard imbalance under the classic smallest-slot
        # policy vs round-robin interleaving over the SAME trace, and
        # the epoch-migration resync count (bounded: one per twin).
        import numpy as np

        from kueue_oss_tpu.api.types import (
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            PodSet,
            PreemptionPolicy,
            ResourceFlavor,
            ResourceGroup,
            ResourceQuota,
            Workload,
        )
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.core.store import Store
        from kueue_oss_tpu.scheduler.scheduler import Scheduler
        from kueue_oss_tpu.solver import meshutil
        from kueue_oss_tpu.solver.delta import HostDeltaSession
        from kueue_oss_tpu.solver.engine import SolverEngine
        from kueue_oss_tpu.solver.full_kernels import (
            solve_backlog_full,
            to_device_full,
        )
        from kueue_oss_tpu.solver.sharded import solve_backlog_full_sharded
        from kueue_oss_tpu.solver.tensors import export_problem

        mesh = meshutil.detect_mesh()
        n_dev = meshutil.mesh_devices(mesh)
        if n_dev < 2:
            return {"scenario": scenario, "skipped": True,
                    "reason": "single device; no mesh to measure"}

        # --- row-sharded FULL drain: p50 + byte-identity twin -------
        store, queues, engine = _build(preemption=True, small=True)
        if (len(store.workloads) + 1) % n_dev == 0:
            # force the uneven path: W+1 % n_dev != 0 pads-and-unpads
            proto = next(iter(store.workloads.values()))
            store.add_workload(Workload(
                name="uneven-extra", queue_name=proto.queue_name,
                uid=10_000_000, creation_time=0.5,
                podsets=[PodSet(name="main", count=1,
                                requests=dict(
                                    proto.podsets[0].requests))]))
        pending = engine.pending_backlog()
        problem = export_problem(store, pending, include_admitted=True)
        g_max = int(problem.cq_ngroups.max())
        h_max, p_max = engine._size_caps(problem)
        log(f"[podscale] W={problem.n_workloads} C={problem.n_cqs} "
            f"mesh={n_dev} g_max={g_max} h_max={h_max} p_max={p_max}")
        reps = int(os.environ.get("BENCH_POD_REPS", "5"))
        walls, sharded_out = [], None
        for _ in range(reps + 1):  # rep 0 pays compilation
            t0 = time.monotonic()
            sharded_out = solve_backlog_full_sharded(
                problem, mesh, g_max=g_max, h_max=h_max, p_max=p_max)
            np.asarray(sharded_out[0])  # host-materialized window end
            walls.append(time.monotonic() - t0)
        single = solve_backlog_full(to_device_full(problem),
                                    g_max=g_max, h_max=h_max,
                                    p_max=p_max)
        plans_identical = all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(single, sharded_out))
        full_ms = np.asarray(walls[1:]) * 1000

        # --- churned-session imbalance: classic vs interleaved ------
        # small quotas pin a standing PARKED backlog (admitted rows
        # fold into usage and leave the export); churn admits the
        # oldest parked rows as finishes free quota while new arrivals
        # take the freed slots — the classic smallest-slot policy
        # packs the backlog into the low block shards
        def build_twin(classic: bool):
            tstore = Store()
            tstore.upsert_resource_flavor(ResourceFlavor(name="f"))
            for i in range(4):
                tstore.upsert_cluster_queue(ClusterQueue(
                    name=f"cq{i}", preemption=PreemptionPolicy(),
                    resource_groups=[ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[FlavorQuotas(name="f", resources=[
                            ResourceQuota(name="cpu", nominal=4)])])]))
                tstore.upsert_local_queue(LocalQueue(
                    name=f"lq{i}", cluster_queue=f"cq{i}"))
            tqueues = QueueManager(tstore)
            tsched = Scheduler(tstore, tqueues)
            teng = SolverEngine(tstore, tqueues, scheduler=tsched,
                                mesh_mode="auto")
            teng.mesh_min_workloads = 0
            teng.mesh_force = True
            teng.pad_to = 64  # pinned capacity: no shape-change syncs
            if classic:
                sess = HostDeltaSession(cache=teng.export_cache)
                sess.set_interleave = lambda n: None
                teng._delta_sessions["lean"] = sess
            return teng, tstore, tsched

        def churn_twin(teng, tstore, tsched):
            uid = 0

            def add(n):
                nonlocal uid
                for _ in range(n):
                    tstore.add_workload(Workload(
                        name=f"w{uid}", queue_name=f"lq{uid % 4}",
                        uid=uid + 1, creation_time=float(uid),
                        podsets=[PodSet(name="main", count=1,
                                        requests={"cpu": 1})]))
                    uid += 1

            add(56)  # 16 admit (4 CQs x quota 4), 40 park
            teng.drain(now=0.0)
            for cyc in range(16):
                admitted = sorted(
                    (w.creation_time, k)
                    for k, w in tstore.workloads.items()
                    if w.is_quota_reserved and not w.is_finished)
                for _, k in admitted[:2]:
                    tsched.finish_workload(k, now=float(cyc))
                add(2)
                teng.drain(now=float(cyc + 1))
            assert teng.last_drain_arm == "mesh", teng.last_drain_arm
            sess = teng._delta_sessions["lean"]
            wl_cqid = np.asarray(sess._last[0]["wl_cqid"])
            return meshutil.shard_imbalance(wl_cqid, 4, mesh)

        imb_interleaved = churn_twin(*build_twin(classic=False))
        imb_classic = churn_twin(*build_twin(classic=True))

        # epoch-migration cost: a live session whose interleave width
        # changes without a capacity change (the production case — a
        # sidecar advertises a mesh narrower than the local device
        # count; local width changes re-align the pad and ride a
        # shape-change sync instead) re-lays its slots out in exactly
        # ONE counted full RESYNC, then returns to deltas
        from kueue_oss_tpu.solver.tensors import pad_workloads

        w1 = problem.wl_cqid.shape[0]
        mprob = pad_workloads(problem, w1 - 1 + (-w1) % n_dev)
        msess = HostDeltaSession()
        msess.advance(mprob)  # first_sync seeds the session
        msess.set_interleave(n_dev)
        _, mframe = msess.advance(mprob)
        migration_resyncs = int(
            mframe.full_reason == "interleave_migration")
        _, mframe2 = msess.advance(mprob)
        migration_resyncs += int(mframe2.full_reason is not None)
        session_migrations = msess.migrations

        return {
            "scenario": scenario,
            "workloads": problem.n_workloads,
            "mesh_devices": n_dev,
            "uneven_shards": problem.wl_cqid.shape[0] % n_dev != 0,
            "full_shard_drain_ms_p50": float(np.percentile(full_ms, 50)),
            "full_shard_first_drain_seconds": round(walls[0], 3),
            "plans_identical": plans_identical,
            "shard_imbalance_classic": round(imb_classic, 4),
            "shard_imbalance_interleaved": round(imb_interleaved, 4),
            "session_migrations": session_migrations,
            "migration_resyncs": migration_resyncs,
            **_degradation_counts(),
        }

    if scenario == "recorder":
        # flight-recorder overhead on the 50k x 1k host cycle-latency
        # shape: identical twin stores run the same N host cycles with
        # the recorder off, then on; the JSON tail reports the relative
        # overhead (<2% acceptance bar, docs/OBSERVABILITY.md) plus the
        # decision-event volume and per-reason skip counts the enabled
        # run produced.
        from kueue_oss_tpu import metrics as kmetrics
        from kueue_oss_tpu import obs
        from kueue_oss_tpu.scheduler.scheduler import Scheduler

        n_cycles = int(os.environ.get("BENCH_RECORDER_CYCLES", "10"))

        def timed_cycles(enabled: bool) -> tuple[float, int]:
            store, queues, _ = _build(preemption=True, small=small)
            sched = Scheduler(store, queues)
            obs.recorder.clear()
            obs.recorder.enabled = enabled
            t0 = time.monotonic()
            for c in range(n_cycles):
                sched.schedule(now=float(c))
            return time.monotonic() - t0, len(store.workloads)

        reps = int(os.environ.get("BENCH_RECORDER_REPS", "3"))
        _, n_wl = timed_cycles(False)       # warm-up (imports, caches)
        t_offs, t_ons = [], []
        events = skips = None
        for _ in range(reps):               # alternate; min beats noise
            t_offs.append(timed_cycles(False)[0])
            ev0 = kmetrics.decision_events_total.total()
            sk0 = kmetrics.decision_skips_total.collect()
            t_ons.append(timed_cycles(True)[0])
            if events is None:              # one enabled run's counts
                events = int(
                    kmetrics.decision_events_total.total() - ev0)
                skips = {
                    k[0]: int(v - sk0.get(k, 0)) for k, v in
                    kmetrics.decision_skips_total.collect().items()
                    if v - sk0.get(k, 0)}
        obs.recorder.enabled = True
        t_off, t_on = min(t_offs), min(t_ons)
        overhead = (t_on - t_off) / t_off * 100 if t_off > 0 else 0.0
        return {
            "scenario": scenario,
            "workloads": n_wl,
            "cycles": n_cycles,
            "seconds_recorder_off": round(t_off, 3),
            "seconds_recorder_on": round(t_on, 3),
            "recorder_overhead_pct": round(overhead, 2),
            "decision_events_total": events,
            "skips_by_reason": skips,
        }

    if scenario == "slo_arm":
        # internal helper for the "slo" twin: ONE arm of the cluster
        # health layer, run in its own interpreter. The parent spawns
        # each arm via measure() with PYTHONHASHSEED pinned, so every
        # arm executes the identical build + warm-up + churn cycle
        # sequence modulo the flags under test — whole-run twins inside
        # one process carry several percent of allocator/RSS drift,
        # far above the <2% bar this measurement must resolve.
        from kueue_oss_tpu import metrics as kmetrics
        from kueue_oss_tpu import obs
        from kueue_oss_tpu.api.types import PodSet, Workload
        from kueue_oss_tpu.scheduler.scheduler import Scheduler

        import gc
        from itertools import islice

        arm = os.environ.get("SLO_ARM", "off")
        ledger, slo_on, exem = {
            "off": (False, False, False), "led": (True, False, False),
            "ex": (False, False, True), "all": (True, True, True)}[arm]
        n_cycles = int(os.environ.get("BENCH_SLO_CYCLES", "10"))
        warm_cycles = 5

        store, queues, _ = _build(preemption=True, small=small)
        sched = Scheduler(store, queues)
        obs.cycle_ledger.enabled = ledger
        obs.slo_engine.enabled = slo_on
        kmetrics.exemplars_enabled = exem
        for c in range(warm_cycles):  # admit the initial backlog
            sched.schedule(now=float(c))
        n_wl = len(store.workloads)
        churn = max(1, n_wl // 200)
        lqs = sorted({w.queue_name for w in store.workloads.values()})
        proto = next(iter(store.workloads.values()))
        req = dict(proto.podsets[0].requests)
        uid = max(w.uid for w in store.workloads.values()) + 1
        t_base = max(w.creation_time
                     for w in store.workloads.values()) + 1.0

        def churn_cycle(cyc: int) -> None:
            # steady state: finish `churn` admitted workloads, submit
            # `churn` arrivals, schedule — every cycle nominates,
            # admits, and records real work
            now = float(cyc)
            for k in list(islice(store._admitted, churn)):
                sched.finish_workload(k, now=now)
            for j in range(churn):
                i = uid + cyc * churn + j
                store.add_workload(Workload(
                    name=f"churn-{cyc}-{j}",
                    queue_name=lqs[i % len(lqs)], uid=i,
                    creation_time=t_base + cyc * churn + j,
                    podsets=[PodSet(name="main", count=1,
                                    requests=dict(req))]))
            sched.schedule(now=now)

        for c in range(warm_cycles, warm_cycles + 2):  # churn settles
            churn_cycle(c)
        # a GC pass over the 50k-object store mid-window is multiple
        # percent of the wall; keep the collector out of the timed
        # region (refcounting still frees the churned objects)
        gc.collect()
        gc.disable()
        try:
            t0 = time.monotonic()
            for c in range(warm_cycles + 2, warm_cycles + 2 + n_cycles):
                churn_cycle(c)
            wall = time.monotonic() - t0
        finally:
            gc.enable()
        out = {"scenario": scenario, "arm": arm,
               "wall": round(wall, 4), "workloads": n_wl,
               "cycles": n_cycles}
        if arm == "all":
            out["ledger_rows"] = len(obs.cycle_ledger.rows())
            t0 = time.monotonic()
            report = obs.slo_engine.evaluate(queues=queues)
            out["slo_eval_ms"] = round((time.monotonic() - t0) * 1000, 2)
            out["slo_keys"] = len(report["slis"])
            out["alerts_firing"] = len(report["alerts"])
        return out

    if scenario == "slo":
        # cluster health layer overhead on the 50k x 1k CHURN shape
        # (docs/OBSERVABILITY.md "Cluster health & SLOs"): identical
        # twin runs of the slo_arm steady-state churn loop with the
        # ledger + SLO feed + exemplars off, then each layer on, each
        # arm in its own hash-seed-pinned subprocess so all four
        # execute the same cycle sequence on the same address-space
        # trajectory. The JSON tail reports the per-layer and combined
        # relative overheads (<2% combined acceptance bar) plus the
        # wall of one SLO evaluation over the populated engine. The
        # flight recorder stays ON in every arm: its cost is the
        # recorder scenario's measurement, not this one's.
        reps = int(os.environ.get("BENCH_SLO_REPS", "3"))
        arm_names = ("off", "led", "ex", "all")
        walls: dict[str, list[float]] = {k: [] for k in arm_names}
        all_res = None
        for _ in range(reps):            # alternate; min beats noise
            for name in arm_names:
                res = measure("slo_arm",
                              extra_env={"SLO_ARM": name,
                                         "PYTHONHASHSEED": "0"},
                              timeout=600)
                walls[name].append(res["wall"])
                if name == "all":
                    all_res = res
        off = min(walls["off"])

        def pct(on: float) -> float:
            return round((on - off) / off * 100, 2) if off > 0 else 0.0

        return {
            "scenario": scenario,
            "workloads": all_res["workloads"],
            "cycles": all_res["cycles"],
            "seconds_health_off": round(off, 3),
            "seconds_health_on": round(min(walls["all"]), 3),
            "ledger_overhead_pct": pct(min(walls["led"])),
            "exemplar_overhead_pct": pct(min(walls["ex"])),
            "slo_combined_overhead_pct": pct(min(walls["all"])),
            "slo_eval_ms": all_res["slo_eval_ms"],
            "ledger_rows": all_res["ledger_rows"],
            "slo_keys": all_res["slo_keys"],
            "alerts_firing": all_res["alerts_firing"],
        }

    if scenario == "durability":
        # durable control plane on the 50k x 1k churn shape
        # (docs/DURABILITY.md): identical twin stores run the same N
        # host cycles with persistence off, then on (group-commit WAL
        # into a scratch dir) — wal_overhead_pct is the relative cost
        # (<5% acceptance bar). Then the 50k-workload store is
        # checkpointed atomically (checkpoint_ms) and recovered from
        # checkpoint + WAL suffix (recovery_ms_50k), with the recovered
        # canonical dump byte-compared against the live store and the
        # invariant auditor run over it.
        import shutil
        import tempfile

        from kueue_oss_tpu.persist import (
            InvariantAuditor,
            PersistenceManager,
            canonical_dump,
        )
        from kueue_oss_tpu.scheduler.scheduler import Scheduler

        n_cycles = int(os.environ.get("BENCH_DURABILITY_CYCLES", "10"))
        reps = int(os.environ.get("BENCH_DURABILITY_REPS", "3"))

        def timed_cycles(persist_dir):
            store, queues, _ = _build(preemption=True, small=small)
            mgr = None
            if persist_dir is not None:
                # attach after the backlog seeding: the measurement is
                # the steady-state churn cost (decision intents +
                # admission/eviction events), not the one-time import.
                # Checkpoint triggers are disabled inside the timed
                # window — checkpoint cost is measured separately as
                # checkpoint_ms, and a cadence-tripped full-store
                # serialization would masquerade as WAL overhead.
                mgr = PersistenceManager(
                    persist_dir, fsync="batch",
                    checkpoint_interval_records=1 << 62,
                    checkpoint_interval_seconds=0.0)
                mgr.attach(store)
            sched = Scheduler(store, queues)
            t0 = time.monotonic()
            for c in range(n_cycles):
                sched.schedule(now=float(c))
            wall = time.monotonic() - t0
            return wall, store, mgr

        _w, n_store, _m = timed_cycles(None)  # warm-up
        n_wl = len(n_store.workloads)
        t_offs, t_ons = [], []
        keep = None
        for r in range(reps):  # alternate; min beats noise
            t_offs.append(timed_cycles(None)[0])
            d = tempfile.mkdtemp(prefix="kueue-bench-dur-")
            wall, store, mgr = timed_cycles(d)
            t_ons.append(wall)
            if keep is not None:
                keep[1].close()
                shutil.rmtree(keep[2], ignore_errors=True)
            keep = (store, mgr, d)
        store, mgr, d = keep
        t_off, t_on = min(t_offs), min(t_ons)
        overhead = (t_on - t_off) / t_off * 100 if t_off > 0 else 0.0
        wal_bytes = mgr.wal.bytes_appended
        wal_records = mgr.wal.records_appended

        t0 = time.monotonic()
        mgr.checkpoint()
        checkpoint_ms = (time.monotonic() - t0) * 1000
        # churn a WAL suffix past the checkpoint so recovery replays a
        # real tail: finish a slice of admitted workloads (events +
        # freed capacity) and let two cycles readmit into the gap
        from kueue_oss_tpu.core.queue_manager import QueueManager as _QM

        sched_tail = Scheduler(store, _QM(store))
        for key in list(store._admitted)[:100]:
            sched_tail.finish_workload(key, now=float(n_cycles))
        for c in range(2):
            sched_tail.schedule(now=float(n_cycles + c))
        mgr.flush()
        mgr.close()

        t0 = time.monotonic()
        rec_mgr = PersistenceManager(d, fsync="off")
        rr = rec_mgr.recover()
        recovery_ms = (time.monotonic() - t0) * 1000
        rec_mgr.close()
        identical = canonical_dump(rr.store) == canonical_dump(store)
        violations = InvariantAuditor(rr.store).audit()
        shutil.rmtree(d, ignore_errors=True)
        return {
            "scenario": scenario,
            "workloads": n_wl,
            "cycles": n_cycles,
            "seconds_persist_off": round(t_off, 3),
            "seconds_persist_on": round(t_on, 3),
            "wal_overhead_pct": round(overhead, 2),
            "wal_bytes_per_cycle": int(wal_bytes / max(1, n_cycles)),
            "wal_records": int(wal_records),
            "checkpoint_ms": round(checkpoint_ms, 1),
            "recovery_ms_50k": round(recovery_ms, 1),
            "recovery_replayed": rr.replayed_events,
            "recovered_identical": identical,
            "audit_violations": len(violations),
        }

    if scenario == "whatif":
        # TPU-batched counterfactual planning (docs/SIMULATOR.md): S
        # scenario variants of the padded admission problem vmapped
        # into ONE dispatch, vs the same S scenarios solved
        # sequentially through the single-problem kernel (the parity
        # oracle). Measurement protocol: both programs execute once to
        # compile OUTSIDE the timing windows; plans must stay
        # bit-identical between the two paths.
        import numpy as np

        from kueue_oss_tpu.sim import (
            arrival_sweep,
            check_parity,
            cross,
            pending_backlog,
            quota_sweep,
            solve_scenarios,
            solve_scenarios_sequential,
        )
        from kueue_oss_tpu.sim.batch import pow2
        from kueue_oss_tpu.solver.tensors import (
            ExportCache,
            export_problem,
            pad_workloads,
        )

        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.perf.generator import (
            GeneratorConfig,
            generate,
        )

        # the planning sweet spot: MANY scenarios over a contended
        # moderate backlog. (A 50k-row contended drain batches poorly
        # on one CPU core — vmapped while_loop lanes all run to the
        # batch's max round count, so round-skew eats the win; the
        # scenario axis is the dimension the TPU VPU parallelizes.)
        n_scen = int(os.environ.get("BENCH_WHATIF_S", "128"))
        config = GeneratorConfig.large_scale(preemption=False)
        config.n_cohorts = int(os.environ.get("BENCH_WHATIF_COHORTS", "2"))
        config.cqs_per_cohort = int(os.environ.get("BENCH_WHATIF_CQS", "4"))
        for wc, n in zip(config.classes, (14, 4, 2)):
            wc.count = n
        store, schedule = generate(config)
        for g in schedule:
            store.add_workload(g.workload)
        queues = QueueManager(store)
        pending = pending_backlog(store, queues)
        problem = export_problem(
            store, pending, cache=ExportCache(store, subscribe=False))
        W = problem.n_workloads
        problem = pad_workloads(problem, pow2(W))
        specs = cross(quota_sweep((0.25, 0.5, 0.75, 1.25, 1.5, 2.0, 3.0)),
                      arrival_sweep((0.5, 0.75, 1.25, 1.5, 2.0, 2.5, 3.0)))
        if len(specs) < n_scen:  # tile the grid to the requested width
            specs = (specs * (n_scen // len(specs) + 1))
        specs = specs[:n_scen]
        overlays = [s.overlay(problem, replicas=1) for s in specs]
        # NOTE replicas=1: the bench sweep masks arrivals only downward
        # (no clone materialization), keeping one export for both paths
        log(f"[whatif] {len(specs)} scenarios x {W} workloads "
            f"(padded {problem.n_workloads})")
        solve_scenarios(problem, overlays)          # compile (vmapped)
        batch = solve_scenarios(problem, overlays)  # timed inside
        solve_scenarios_sequential(problem, overlays[:1])  # compile
        seq = solve_scenarios_sequential(problem, overlays)
        pr = check_parity(batch, seq, range(len(specs)))
        vs = batch.solve_seconds
        ss = seq.solve_seconds
        return {
            "scenario": scenario,
            "scenarios": len(specs),
            "workloads": W,
            "padded_workloads": problem.n_workloads,
            "cluster_queues": problem.n_cqs,
            "batch_width": batch.batch_width,
            "vmapped_wall_s": round(vs, 6),
            "sequential_wall_s": round(ss, 6),
            "scenarios_per_sec": round(len(specs) / vs, 2) if vs else 0.0,
            "vmapped_speedup": round(ss / vs, 2) if vs else 0.0,
            "plans_identical": pr.identical,
            "rounds_max": int(np.asarray(batch.rounds).max()),
            "admitted_base": int(np.asarray(
                batch.admitted[0]).sum()),
        }

    if scenario == "fullsweep":
        # FULL-kernel what-if sweeps (docs/SIMULATOR.md "FULL-kernel
        # sweeps, lane budgets & resident state"): S preemption-aware
        # scenario solves over a production-shaped Philly trace with
        # admitted incumbents, dispatched in lane-budgeted pow2 chunks
        # of jit(vmap(solve_backlog_full)) vs the sequential FULL
        # oracle. Protocol: every program compiles OUTSIDE the timing
        # windows, walls are best-of-3, and the chunked plans must be
        # bit-identical to the oracle. Also measured: the resident
        # device-state win (ResidentSweep reuse vs a fresh upload per
        # sweep) and the relax-tier mega-sweep throughput.
        import time as _time

        import numpy as np

        from kueue_oss_tpu.api.types import (
            Admission,
            PodSetAssignment,
            WorkloadConditionType,
        )
        from kueue_oss_tpu.sim import batch as simbatch
        from kueue_oss_tpu.sim import traces as simtraces
        from kueue_oss_tpu.sim.batch import pow2
        from kueue_oss_tpu.sim.engine import pending_backlog
        from kueue_oss_tpu.sim.resident import ResidentSweep
        from kueue_oss_tpu.sim.scenario import (
            arrival_sweep,
            cross,
            quota_sweep,
        )
        from kueue_oss_tpu.solver.full_kernels import to_device_full
        from kueue_oss_tpu.solver.tensors import (
            ExportCache,
            export_problem,
            pad_workloads,
        )

        # the planning sweet spot, like whatif: MANY scenarios over a
        # small contended trace — the scenario axis is what batching
        # amortizes (per-scenario dispatch overhead dominates the
        # sequential oracle); W scales up via env on real hardware
        n_jobs = int(os.environ.get("BENCH_FULLSWEEP_JOBS", "7"))
        n_scen = int(os.environ.get("BENCH_FULLSWEEP_S", "64"))
        chunk = int(os.environ.get("BENCH_FULLSWEEP_CHUNK", "0"))
        n_relax = int(os.environ.get("BENCH_FULLSWEEP_RELAX", "256"))

        jobs = simtraces.philly_trace(n_jobs, seed=11)
        store = simtraces.store_from_trace(jobs, capacity_frac=0.25)
        # admit the earliest ~40% so quota cuts have preemption targets
        for j in sorted(jobs, key=lambda j: j.submit_s)[
                : int(n_jobs * 0.4)]:
            wl = store.workloads[f"default/{j.job_id}"]
            wl.status.admission = Admission(
                cluster_queue=j.vc,
                podset_assignments=[PodSetAssignment(
                    name="main", flavors={"gpu": "gpu"},
                    resource_usage=dict(wl.podsets[0].total_requests()),
                    count=1)])
            wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True,
                             reason="QuotaReserved", now=j.submit_s)
            store.update_workload(wl)
        problem = export_problem(store, pending_backlog(store),
                                 cache=ExportCache(store,
                                                   subscribe=False),
                                 include_admitted=True)
        W = problem.n_workloads
        problem = pad_workloads(problem, pow2(W))
        caps = simbatch.full_caps(problem)
        grid = cross(quota_sweep((0.25, 0.4, 0.5, 0.75, 1.5, 2.0)),
                     arrival_sweep((0.5, 0.75, 1.25, 1.5, 2.0, 2.5,
                                    3.0)))
        specs = (grid * (n_scen // len(grid) + 1))[:n_scen]
        overlays = [s.overlay(problem) for s in specs]
        order = simbatch.sweep_order(specs)
        tensors = to_device_full(problem)
        log(f"[fullsweep] {n_scen} scenarios x {W} workloads "
            f"(padded {problem.n_workloads}) caps={caps} chunk={chunk}")

        def best3(fn):
            walls = []
            for _ in range(3):
                t0 = _time.perf_counter()
                fn()
                walls.append(_time.perf_counter() - t0)
            return min(walls)

        # chunked FULL vs the sequential FULL oracle
        simbatch.solve_scenarios_full(problem, overlays, *caps,
                                      tensors=tensors, chunk=chunk,
                                      order=order)
        simbatch.solve_scenarios_sequential_full(
            problem, overlays[:1], *caps, tensors=tensors)
        t_chunked = best3(lambda: simbatch.solve_scenarios_full(
            problem, overlays, *caps, tensors=tensors, chunk=chunk,
            order=order))
        t_seq = best3(
            lambda: simbatch.solve_scenarios_sequential_full(
                problem, overlays, *caps, tensors=tensors))
        full = simbatch.solve_scenarios_full(
            problem, overlays, *caps, tensors=tensors, chunk=chunk,
            order=order)
        seq = simbatch.solve_scenarios_sequential_full(
            problem, overlays, *caps, tensors=tensors)
        pr = simbatch.check_parity_full(full, seq, range(n_scen))
        preempt = int((np.asarray(seq.victim_reason)[:, :W] > 0).sum())

        # resident device state vs a fresh upload per sweep
        rs = ResidentSweep(store)
        rp, rdev = rs.refresh()
        rovl = [s.overlay(rp) for s in specs]
        simbatch.solve_scenarios_full(rp, rovl, *caps, tensors=rdev,
                                      chunk=chunk)

        def resident_sweep():
            p, dev = rs.refresh()
            simbatch.solve_scenarios_full(p, rovl, *caps, tensors=dev,
                                          chunk=chunk)

        def reupload_sweep():
            dev = to_device_full(rp)
            simbatch.solve_scenarios_full(rp, rovl, *caps, tensors=dev,
                                          chunk=chunk)

        resident_sweep(), reupload_sweep()  # warm both arms
        t_res = best3(resident_sweep)
        t_re = best3(reupload_sweep)

        # relax approximate tier: mega-sweep throughput
        mega = (grid * (n_relax // len(grid) + 1))[:n_relax]
        movl = [s.overlay(problem) for s in mega]
        simbatch.solve_scenarios_relax(problem, movl[:8])
        t_rx = best3(
            lambda: simbatch.solve_scenarios_relax(problem, movl))

        return {
            "scenario": scenario,
            "scenarios": n_scen,
            "workloads": W,
            "padded_workloads": problem.n_workloads,
            "chunk_width": chunk,
            "chunks": len(full.chunks),
            "chunked_wall_s": round(t_chunked, 6),
            "sequential_wall_s": round(t_seq, 6),
            "full_speedup": round(t_seq / t_chunked, 2)
            if t_chunked else 0.0,
            "plans_identical": pr.identical,
            "preemptions_total": preempt,
            "resident_sweep_s": round(t_res, 6),
            "reupload_sweep_s": round(t_re, 6),
            "resident_win": round(t_re / t_res, 2) if t_res else 0.0,
            "resident_reuses": rs.reuses,
            "resident_full_uploads": rs.full_uploads,
            "relax_scenarios": n_relax,
            "relax_scenarios_per_sec": round(n_relax / t_rx, 1)
            if t_rx else 0.0,
        }

    if scenario == "federation":
        # federated control planes (docs/FEDERATION.md). Phase 1: four
        # tenants x two control-plane instances each share ONE solver
        # sidecar through the weighted-DRR farm; a deadline-bound
        # contended churn (every member re-drains as fast as its grants
        # come back, so demand exceeds the single solve slot) measures
        # whether per-tenant solver WALL-TIME shares track the 2:2:1:1
        # weights. Plans must stay bit-identical to dedicated-sidecar
        # host twins replaying the same churn, and every resident
        # session's state checksum must match its own tenant only.
        # Phase 2: the WhatIf dispatcher priced against Incremental on
        # a heterogeneous 4-worker fleet where the three constrained
        # workers list first — an unpriced strategy races them for a
        # full round before reaching the roomy one, a priced one goes
        # straight there; time-to-admit is counted in simulated seconds.
        import tempfile
        import threading

        from kueue_oss_tpu import metrics as kmetrics
        from kueue_oss_tpu.api.types import (
            AdmissionCheck,
            CheckState,
            ClusterQueue,
            FlavorQuotas,
            LocalQueue,
            PodSet,
            PreemptionPolicy,
            ResourceFlavor,
            ResourceGroup,
            ResourceQuota,
            Workload,
        )
        from kueue_oss_tpu.controllers import WorkloadReconciler
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.core.store import Store
        from kueue_oss_tpu.federation import (
            attach_farm,
            build_member,
            plan_fingerprint,
        )
        from kueue_oss_tpu.multikueue import (
            MULTIKUEUE_CONTROLLER_NAME,
            IncrementalDispatcher,
            MultiKueueCluster,
            MultiKueueController,
            WhatIfDispatcher,
            WorkerEnvironment,
        )
        from kueue_oss_tpu.scheduler.scheduler import Scheduler
        from kueue_oss_tpu.solver.delta import state_checksum
        from kueue_oss_tpu.solver.service import SolverServer

        def seed_cluster(store, n_cqs=4, quota=8):
            store.upsert_resource_flavor(ResourceFlavor(name="f"))
            for i in range(n_cqs):
                store.upsert_cluster_queue(ClusterQueue(
                    name=f"cq{i}", preemption=PreemptionPolicy(),
                    resource_groups=[ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[FlavorQuotas(name="f", resources=[
                            ResourceQuota(name="cpu", nominal=quota)])])]))
                store.upsert_local_queue(LocalQueue(
                    name=f"lq{i}", cluster_queue=f"cq{i}"))

        def fed_wl(i, cpu=1):
            return Workload(
                name=f"w{i}", queue_name=f"lq{i % 4}", uid=i + 1,
                creation_time=float(i),
                podsets=[PodSet(name="main", count=1,
                                requests={"cpu": cpu})])

        def churn(member, cycles, uid0, t0):
            uid = uid0
            for cyc in range(t0, t0 + cycles):
                admitted = sorted(
                    k for k, w in member.store.workloads.items()
                    if w.is_quota_reserved and not w.is_finished)
                for k in admitted[:2]:
                    member.scheduler.finish_workload(k, now=float(cyc))
                for _ in range(2):
                    member.store.add_workload(fed_wl(uid))
                    uid += 1
                member.drain(now=float(cyc))
            return uid

        weights = {"cp-a": 2.0, "cp-b": 2.0, "cp-c": 1.0, "cp-d": 1.0}
        sock = os.path.join(tempfile.mkdtemp(), "farm.sock")
        srv = SolverServer(sock, max_sessions=16)
        farm = attach_farm(srv, weights=weights, quantum_s=0.002)
        srv.serve_in_background()
        members = {}
        for tname in weights:
            for j in range(2):
                members[f"{tname}/{j}"] = build_member(
                    tname, socket_path=sock,
                    seed=lambda s: seed_cluster(s), pad_to=64)
        offsets = {n: 10000 * i for i, n in enumerate(members)}
        # warm sequentially (initial SYNC + kernel compile) so compile
        # wall never lands on one tenant's bill
        uids = {}
        for name, m in members.items():
            for i in range(24):
                m.store.add_workload(fed_wl(i + offsets[name]))
            m.drain(now=0.0)
            uids[name] = churn(m, 2, offsets[name] + 100, t0=1)
        base_wall = dict(farm.wall_by_tenant)
        base_served = dict(farm.served)

        secs = float(os.environ.get("BENCH_FED_SECS", "5.0"))
        barrier = threading.Barrier(len(members))
        cycles_run = {}

        def contend(name, m):
            barrier.wait()
            deadline = time.monotonic() + secs
            cyc = 3
            while time.monotonic() < deadline:
                uids[name] = churn(m, 1, uids[name], t0=cyc)
                cyc += 1
            cycles_run[name] = cyc - 3

        threads = [threading.Thread(target=contend, args=(n, m))
                   for n, m in members.items()]
        t0 = time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        contended_s = time.monotonic() - t0
        shares = {t: farm.wall_by_tenant.get(t, 0.0) - base_wall.get(t, 0.0)
                  for t in weights}
        solves = sum(farm.served.get(t, 0) - base_served.get(t, 0)
                     for t in weights)
        norm = {t: shares[t] / weights[t] for t in weights}
        spread = (max(norm.values()) / min(norm.values())
                  if min(norm.values()) > 0 else float("inf"))
        log(f"[federation] contended {contended_s:.1f}s, "
            f"{solves} solves, wall shares {shares}, spread "
            f"{spread:.2f}, throttled {dict(farm.throttled)}")

        # zero cross-tenant: every resident session's checksum matches
        # one of its OWN tenant's control planes and no other tenant's
        host_sums = {}
        for name, m in members.items():
            sess = next(iter(m.engine._delta_sessions.values()))
            kwargs, meta = sess._last
            host_sums[name] = state_checksum(kwargs, meta)
        with srv._sessions_lock:
            side_sums = {k: state_checksum(s.kwargs, s.meta)
                         for k, s in srv.sessions.items()}
        zero_cross = bool(side_sums)
        for (tenant, _sid), chk in side_sums.items():
            own = {host_sums[n] for n in host_sums
                   if n.split("/")[0] == tenant}
            other = {host_sums[n] for n in host_sums
                     if n.split("/")[0] != tenant}
            if chk not in own or chk in other:
                zero_cross = False
        # farm-vs-dedicated bit-identity: a host twin of each member
        # replaying the same churn lands the exact same plan
        identical = True
        for name, m in members.items():
            twin = build_member(f"{name}-twin", pad_to=64,
                                seed=lambda s: seed_cluster(s))
            twin.engine.use_sessions = False
            for i in range(24):
                twin.store.add_workload(fed_wl(i + offsets[name]))
            twin.drain(now=0.0)
            uid = churn(twin, 2, offsets[name] + 100, t0=1)
            churn(twin, cycles_run[name], uid, t0=3)
            if (plan_fingerprint(twin.store, twin.queues)
                    != plan_fingerprint(m.store, m.queues)):
                identical = False
                log(f"[federation] PLAN MISMATCH vs twin: {name}")
        srv.shutdown()
        srv.server_close()

        # -- phase 2: what-if-scored dispatch vs Incremental ----------
        def worker_env(name, quota, background_cpu=()):
            env = WorkerEnvironment(name)
            store = env.store
            store.upsert_resource_flavor(ResourceFlavor(name="f0"))
            store.upsert_cluster_queue(ClusterQueue(
                name="wcq", preemption=PreemptionPolicy(),
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(name="f0", resources=[
                        ResourceQuota(name="cpu", nominal=quota)])])]))
            store.upsert_local_queue(LocalQueue(
                name="lq", cluster_queue="wcq"))
            for i, cpu in enumerate(background_cpu):
                store.add_workload(Workload(
                    name=f"bg{i}", queue_name="lq",
                    creation_time=float(i),
                    podsets=[PodSet(count=1, requests={"cpu": cpu})]))
            env.run_cycle(5.0)
            return env

        def make_workers():
            return [
                worker_env("tight-a", 2000, background_cpu=(1500,)),
                worker_env("tight-b", 2500, background_cpu=(2000,)),
                worker_env("tight-c", 2000, background_cpu=(1600,)),
                worker_env("roomy", 8000, background_cpu=(1000,)),
            ]

        class Hub:
            def __init__(self, workers, dispatcher):
                self.store = Store()
                self.store.upsert_resource_flavor(
                    ResourceFlavor(name="f0"))
                self.store.upsert_cluster_queue(ClusterQueue(
                    name="hubcq", admission_checks=["multikueue"],
                    resource_groups=[ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[FlavorQuotas(name="f0", resources=[
                            ResourceQuota(name="cpu",
                                          nominal=16000)])])]))
                self.store.upsert_local_queue(LocalQueue(
                    name="lq", cluster_queue="hubcq"))
                self.store.upsert_admission_check(AdmissionCheck(
                    name="multikueue",
                    controller_name=MULTIKUEUE_CONTROLLER_NAME))
                self.queues = QueueManager(self.store)
                self.scheduler = Scheduler(self.store, self.queues)
                self.wr = WorkloadReconciler(self.store, self.scheduler)
                self.clusters = [
                    MultiKueueCluster(name=e.name, environment=e)
                    for e in workers]
                self.dispatcher = dispatcher
                self.mk = MultiKueueController(
                    self.store, self.scheduler, self.clusters,
                    dispatcher=dispatcher)
                self.t = 10.0

            def submit(self, cpu):
                self.t += 1.0
                self.store.add_workload(Workload(
                    name="wl", queue_name="lq", creation_time=self.t,
                    podsets=[PodSet(count=1, requests={"cpu": cpu})]))

            def tick(self):
                self.t += 1.0
                self.scheduler.schedule(self.t)
                self.mk.reconcile_all(self.t)
                for c in self.clusters:
                    if c.active:
                        c.environment.run_cycle(self.t)
                self.mk.reconcile_all(self.t)
                self.wr.reconcile_all(self.t)

        round_timeout = 15.0
        sizes = (300, 3000, 1000, 300, 2500, 1500)

        def dispatch_once(dispatcher, cpu):
            hub = Hub(make_workers(), dispatcher)
            hub.submit(cpu)
            t_submit = hub.t
            for _ in range(60):
                hub.tick()
                wl = hub.store.workloads["default/wl"]
                st = wl.status.admission_checks.get("multikueue")
                if st is not None and st.state == CheckState.READY:
                    return hub.t - t_submit, hub
            raise RuntimeError(f"dispatch never admitted (cpu={cpu})")

        # compile the pricer programs outside the measured stream
        dispatch_once(WhatIfDispatcher(round_timeout_s=round_timeout,
                                       check_oracle=True), 1000)
        _, score_sum0, score_n0 = (
            kmetrics.multikueue_dispatch_score_ms._values[()])
        ttas = {}
        agree = scored = 0
        for label in ("whatif", "incremental"):
            ttas[label] = []
            for cpu in sizes:
                dispatcher = (
                    WhatIfDispatcher(round_timeout_s=round_timeout,
                                     check_oracle=True)
                    if label == "whatif" else
                    IncrementalDispatcher(round_timeout_s=round_timeout))
                tta, hub = dispatch_once(dispatcher, cpu)
                ttas[label].append(tta)
                if label == "whatif":
                    rep = dispatcher.last_reports.get("default/wl")
                    if rep is not None:
                        scored += 1
                        if (rep.best == rep.oracle_best
                                and rep.oracle_identical):
                            agree += 1
        _, score_sum1, score_n1 = (
            kmetrics.multikueue_dispatch_score_ms._values[()])
        tta_whatif = sum(ttas["whatif"]) / len(sizes)
        tta_inc = sum(ttas["incremental"]) / len(sizes)
        score_ms = ((score_sum1 - score_sum0)
                    / max(1, score_n1 - score_n0))
        log(f"[federation] whatif tta {ttas['whatif']} vs incremental "
            f"{ttas['incremental']} (sim s); oracle {agree}/{scored}; "
            f"score {score_ms:.2f} ms")
        return {
            "scenario": scenario,
            "tenants": len(weights),
            "members": len(members),
            "contended_seconds": round(contended_s, 2),
            "farm_solves": int(solves),
            "farm_throttled": int(sum(farm.throttled.values())),
            "tenant_wall_share_spread": round(spread, 3),
            "zero_cross_tenant": zero_cross,
            "plans_identical_dedicated": identical,
            "whatif_dispatches": len(sizes),
            "whatif_oracle_agreement": round(agree / max(1, scored), 4),
            "dispatch_score_ms_mean": round(score_ms, 3),
            "whatif_time_to_admit_s": round(tta_whatif, 2),
            "incremental_time_to_admit_s": round(tta_inc, 2),
            "whatif_admit_speedup": round(
                tta_inc / max(1e-9, tta_whatif), 2),
        }

    if scenario == "relax_arm":
        # internal helper for the "relax" twin: ONE solver arm (exact
        # lean kernel vs the convex-relaxation fast path) timed in its
        # own hash-seed-pinned interpreter on the 50k x 1k CONTENDED
        # fit-only shape (docs/SOLVER_PROTOCOL.md "Relaxed fast-path
        # arm"). The parent alternates arms via measure(), so both
        # execute the identical build + export + warm sequence.
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.perf.generator import GeneratorConfig, generate
        from kueue_oss_tpu.solver import relax
        from kueue_oss_tpu.solver.engine import SolverEngine
        from kueue_oss_tpu.solver.kernels import solve_backlog, to_device
        from kueue_oss_tpu.solver.tensors import pad_workloads, pow2

        arm = os.environ.get("RELAX_ARM", "exact")
        reps = int(os.environ.get("BENCH_RELAX_REPS", "5"))
        config = GeneratorConfig.large_scale(preemption=False)
        if small:
            config.n_cohorts, config.cqs_per_cohort = 2, 10
        if os.environ.get("BENCH_COHORTS"):
            config.n_cohorts = int(os.environ["BENCH_COHORTS"])
        if os.environ.get("BENCH_CQS"):
            config.cqs_per_cohort = int(os.environ["BENCH_CQS"])
        store, schedule = generate(config)
        for g in schedule:
            store.add_workload(g.workload)
        queues = QueueManager(store)
        engine = SolverEngine(store, queues)
        problem, _ = engine.export()
        n_live = problem.n_workloads
        problem = pad_workloads(problem, pow2(problem.n_workloads))
        out = {"scenario": scenario, "arm": arm, "workloads": n_live,
               "cluster_queues": problem.n_cqs}

        if arm == "relax":
            _w, warm_stats = relax.solve_relaxed(problem)  # compile
            pad_to = warm_stats.support_padded
            walls, last = [], None
            for _ in range(reps):
                t0 = time.monotonic()
                plan, stats = relax.solve_relaxed(problem,
                                                  pad_to=pad_to)
                walls.append(time.monotonic() - t0)
                last = (plan, stats)
            plan, stats = last
            exact = tuple(np.asarray(a)
                          for a in solve_backlog(to_device(problem)))
            fault = SolverEngine._plan_fault(
                problem, plan[0], plan[1], plan[2], plan[3], None,
                plan[4], False)
            out.update({
                "support": stats.support,
                "support_fraction": round(stats.support
                                          / max(1, stats.live), 4),
                "lp_iters": stats.iters,
                "repair_rounds": stats.repair_rounds,
                "plan_feasible": fault is None,
                "plans_agree_one_shot": relax.plans_agree(
                    plan, exact, problem.n_workloads),
            })
            # disagreement RATE through the production router: audited
            # relax drains over steady-state churn cycles
            from kueue_oss_tpu import metrics as kmetrics
            from kueue_oss_tpu.api.types import PodSet, Workload
            from kueue_oss_tpu.scheduler.scheduler import Scheduler

            sched = Scheduler(store, queues)
            engine.scheduler = sched
            engine.relax_force = True
            engine.relax_audit_every = 1
            engine.pad_to = len(schedule) + 512
            rejected0 = kmetrics.solver_plan_fallbacks_total.total()
            engine.drain(now=0.0, verify=True)
            n_cycles = int(os.environ.get("BENCH_RELAX_CYCLES", "4"))
            lqs = sorted({w.queue_name
                          for w in store.workloads.values()})
            uid = max(w.uid for w in store.workloads.values()) + 1
            for c in range(1, n_cycles + 1):
                admitted = [k for k, w in store.workloads.items()
                            if w.is_quota_reserved
                            and not w.is_finished]
                for k in admitted[:32]:
                    sched.finish_workload(k, now=float(c))
                for j in range(32):
                    i = uid + c * 32 + j
                    store.add_workload(Workload(
                        name=f"churn-{c}-{j}",
                        queue_name=lqs[i % len(lqs)], uid=i,
                        creation_time=1e6 + c * 32 + j,
                        podsets=[PodSet(name="main", count=1,
                                        requests={"cpu": 1})]))
                engine.drain(now=float(c), verify=True)
            audits = kmetrics.solver_relax_drains_total.collect()
            match = audits.get(("audit_match",), 0)
            diverged = audits.get(("audit_diverged",), 0)
            out.update({
                "audit_match": int(match),
                "audit_diverged": int(diverged),
                "disagreement_rate": round(
                    diverged / max(1, match + diverged), 4),
                "oracle_rejections": int(
                    kmetrics.solver_plan_fallbacks_total.total()
                    - rejected0),
            })
        else:
            tensors = to_device(problem)
            plan = tuple(a for a in solve_backlog(tensors))  # compile
            walls = []
            for _ in range(reps):
                t0 = time.monotonic()
                plan = solve_backlog(tensors)
                plan[0].block_until_ready()
                int(np.asarray(plan[4]))
                walls.append(time.monotonic() - t0)
            out["rounds"] = int(np.asarray(plan[4]))
        walls.sort()
        out["solve_wall_min"] = round(walls[0], 4)
        out["solve_wall_p50"] = round(walls[len(walls) // 2], 4)
        return out

    if scenario == "relax":
        # convex-relaxation fast path vs the exact lean kernel on the
        # 50k x 1k contended backlog: per-arm hash-seed-pinned
        # subprocess twins (the bench methodology — whole-run twins in
        # one process carry percent-level allocator drift), alternated,
        # min-of-reps. Acceptance: relax_speedup >= 2x with every plan
        # exactly feasible; the disagreement rate is the audited
        # divergence frequency through the production 4-arm router.
        reps = int(os.environ.get("BENCH_RELAX_TWIN_REPS", "2"))
        walls = {"exact": [], "relax": []}
        relax_res = None
        for _ in range(reps):
            for armname in ("exact", "relax"):
                res = measure("relax_arm",
                              extra_env={"RELAX_ARM": armname,
                                         "PYTHONHASHSEED": "0"},
                              timeout=1500)
                walls[armname].append(res["solve_wall_min"])
                if armname == "relax":
                    relax_res = res
        exact_w = min(walls["exact"])
        relax_w = min(walls["relax"])
        return {
            "scenario": scenario,
            "workloads": relax_res["workloads"],
            "cluster_queues": relax_res["cluster_queues"],
            "exact_solve_wall": round(exact_w, 4),
            "relax_solve_wall": round(relax_w, 4),
            "relax_speedup": round(exact_w / relax_w, 2)
            if relax_w > 0 else None,
            "relax_support_fraction": relax_res["support_fraction"],
            "relax_repair_rounds": relax_res["repair_rounds"],
            "relax_disagreement_rate": relax_res["disagreement_rate"],
            "plans_feasible": bool(
                relax_res["plan_feasible"]
                and relax_res["oracle_rejections"] == 0),
            "plans_agree_one_shot": relax_res["plans_agree_one_shot"],
            "audit_match": relax_res["audit_match"],
            "audit_diverged": relax_res["audit_diverged"],
        }

    if scenario == "streaming_arm":
        # internal helper for the "streaming" twin: ONE admission
        # model (stream = micro-drain per tick + full solve per
        # cadence; batch = full solve per cadence only) over an
        # identical sustained-arrival schedule on a virtual clock.
        # Time-to-admit is virtual (creation -> QuotaReserved
        # transition), so the comparison measures the MODEL's latency
        # floor, not host speed; the wall is reported for overhead.
        from kueue_oss_tpu.api.types import (
            ClusterQueue as _CQ,
            Cohort as _Cohort,
            FlavorQuotas as _FQ,
            LocalQueue as _LQ,
            PodSet as _PS,
            ResourceFlavor as _RF,
            ResourceGroup as _RG,
            ResourceQuota as _RQ,
            Workload as _WL,
            WorkloadConditionType as _WCT,
        )
        from kueue_oss_tpu.core.store import Store as _Store
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.scheduler.scheduler import Scheduler
        from kueue_oss_tpu import metrics as _kmetrics

        arm = os.environ.get("STREAM_ARM", "batch")
        profile = os.environ.get("BENCH_STREAM_PROFILE", "single")
        n_cqs = int(os.environ.get("BENCH_STREAM_CQS", "32"))
        ticks = int(os.environ.get("BENCH_STREAM_TICKS", "400"))
        per_tick = int(os.environ.get("BENCH_STREAM_ARRIVALS", "16"))
        tick_s = 0.01                 # 10 ms virtual tick
        solve_every = 100             # full solve each 1 s virtual

        store = _Store()
        for f in ("default", "small", "large"):
            store.upsert_resource_flavor(_RF(name=f))
        if profile == "wide":
            # the fleet the structural fences streamed ~0 on: every CQ
            # is multi-flavor or a borrow-capable cohort member, so
            # sub-cycle admission rides entirely on the flavor-pick
            # witness and the reserved-headroom budget
            for c in range(0, n_cqs, 8):
                store.upsert_cohort(_Cohort(name=f"co{c // 8}"))
            for c in range(n_cqs):
                if c % 2 == 0:
                    rg = _RG(covered_resources=["cpu"], flavors=[
                        _FQ(name="small", resources=[
                            _RQ(name="cpu", nominal=10_000_000)]),
                        _FQ(name="large", resources=[
                            _RQ(name="cpu", nominal=10_000_000)])])
                    store.upsert_cluster_queue(_CQ(
                        name=f"cq{c}", resource_groups=[rg]))
                else:
                    store.upsert_cluster_queue(_CQ(
                        name=f"cq{c}", cohort=f"co{c // 8}",
                        resource_groups=[_RG(
                            covered_resources=["cpu"],
                            flavors=[_FQ(name="default", resources=[
                                _RQ(name="cpu",
                                    nominal=10_000_000)])])]))
                store.upsert_local_queue(
                    _LQ(name=f"lq{c}", cluster_queue=f"cq{c}"))
        else:
            for c in range(n_cqs):
                store.upsert_cluster_queue(_CQ(
                    name=f"cq{c}",
                    resource_groups=[_RG(
                        covered_resources=["cpu"],
                        flavors=[_FQ(name="default", resources=[
                            _RQ(name="cpu", nominal=10_000_000)])])]))
                store.upsert_local_queue(
                    _LQ(name=f"lq{c}", cluster_queue=f"cq{c}"))
        queues = QueueManager(store)
        sched = Scheduler(store, queues, solver="auto",
                          solver_min_backlog=0,
                          streaming=(arm == "stream"))
        eng = sched._solver_engine()
        eng.drain(now=0.0, verify=True)  # warm + arm the fences

        uid = 1
        t0 = time.monotonic()
        for tick in range(1, ticks + 1):
            now = tick * tick_s
            if arm == "stream":
                # the micro-batch at tick start picks up the PREVIOUS
                # tick's arrivals: one tick of honest pickup latency,
                # never a same-instant admit
                sched.micro_drain(now)
            for j in range(per_tick):
                c = (tick * per_tick + j) % n_cqs
                store.add_workload(_WL(
                    name=f"w{uid}", queue_name=f"lq{c}", uid=uid,
                    creation_time=now,
                    podsets=[_PS(count=1, requests={"cpu": 100})]))
                uid += 1
            if tick % solve_every == 0:
                eng.drain(now=now, verify=True)
        wall = time.monotonic() - t0

        waits = []
        for wl in store.workloads.values():
            cond = wl.status.conditions.get(_WCT.QUOTA_RESERVED)
            if cond is not None and cond.status:
                waits.append(
                    cond.last_transition_time - wl.creation_time)
        waits.sort()

        def pct(p):
            return (round(waits[int(p * (len(waits) - 1))] * 1000, 3)
                    if waits else None)

        return {
            "scenario": scenario, "arm": arm, "profile": profile,
            "workloads": uid - 1, "admitted": len(waits),
            "cluster_queues": n_cqs,
            "solve_cadence_ms": round(solve_every * tick_s * 1000, 1),
            "tta_ms_p50": pct(0.50), "tta_ms_p95": pct(0.95),
            "wall": round(wall, 3),
            "stream_admitted": int(
                _kmetrics.stream_admitted_total.total()),
            "stream_eligible_fraction": round(
                _kmetrics.stream_eligible_fraction.value(), 4),
        }

    if scenario == "streaming":
        # streaming control plane (docs/ARCHITECTURE.md "Streaming
        # dataflow"): p50/p95 time-to-admit for uncontended CQs under
        # sustained arrivals, streaming vs the cycle-batch twin at the
        # SAME full-solve cadence — per-arm hash-seed-pinned
        # subprocesses (bench methodology). Acceptance: stream p50
        # decoupled from the solve cadence (>= 5x below the batch
        # twin). Plus the durability side: incremental vs full
        # checkpoint wall on the 50k-workload store at <5% dirty keys
        # (acceptance < 20%), and shipped bytes per churn cycle with
        # WAL log shipping on.
        import shutil
        import tempfile

        from kueue_oss_tpu.persist import PersistenceManager

        arms = {}
        for armname in ("batch", "stream"):
            for prof in ("single", "wide"):
                arms[(armname, prof)] = measure(
                    "streaming_arm",
                    extra_env={"STREAM_ARM": armname,
                               "BENCH_STREAM_PROFILE": prof,
                               "PYTHONHASHSEED": "0", "BENCH_CPU": "1"},
                    timeout=1500)
        p50_s, p50_b = arms[("stream", "single")]["tta_ms_p50"], \
            arms[("batch", "single")]["tta_ms_p50"]
        wp50_s, wp50_b = arms[("stream", "wide")]["tta_ms_p50"], \
            arms[("batch", "wide")]["tta_ms_p50"]

        # -- watch-driven vs tick-driven drain latency ---------------
        # real-time (not virtual-clock): arrivals either wake the
        # watch worker directly (event-bound) or wait for the next
        # fixed-cadence micro-drain tick (tick-bound, the pre-watch
        # model). Measures wall latency from add_workload to
        # QuotaReserved over a quiet single-CQ store.
        import threading as _threading

        from kueue_oss_tpu.api.types import (
            ClusterQueue as _CQ,
            FlavorQuotas as _FQ,
            LocalQueue as _LQ,
            PodSet as _PS,
            ResourceFlavor as _RF,
            ResourceGroup as _RG,
            ResourceQuota as _RQ,
            Workload as _WL,
        )
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.core.store import Store as _Store
        from kueue_oss_tpu.scheduler.scheduler import Scheduler

        def _drain_latency(watch, n=40, tick=0.02):
            st = _Store()
            st.upsert_resource_flavor(_RF(name="default"))
            st.upsert_cluster_queue(_CQ(
                name="cq", resource_groups=[_RG(
                    covered_resources=["cpu"],
                    flavors=[_FQ(name="default", resources=[
                        _RQ(name="cpu", nominal=10_000_000)])])]))
            st.upsert_local_queue(_LQ(name="lq", cluster_queue="cq"))
            qs = QueueManager(st)
            sc = Scheduler(st, qs, solver="auto", solver_min_backlog=0,
                           streaming=True)
            sc._solver_engine().drain(now=0.0, verify=True)
            sa = sc._streaming_admitter()
            stop = _threading.Event()
            if watch:
                wake = _threading.Event()
                sa.set_arrival_notifier(wake.set)
                worker = _threading.Thread(
                    target=sc._watch_drain_loop,
                    args=(sa, wake, stop, time.monotonic), daemon=True)
            else:
                def _tick_loop():
                    while not stop.is_set():
                        sc.micro_drain(time.monotonic())
                        stop.wait(tick)
                wake = None
                worker = _threading.Thread(target=_tick_loop,
                                           daemon=True)
            worker.start()
            lat = []
            try:
                for i in range(n):
                    t0 = time.monotonic()
                    st.add_workload(_WL(
                        name=f"lw{i}", queue_name="lq", uid=i + 1,
                        creation_time=t0,
                        podsets=[_PS(count=1,
                                     requests={"cpu": 100})]))
                    while not st.workloads[
                            f"default/lw{i}"].is_quota_reserved:
                        if time.monotonic() - t0 > 5.0:
                            break
                        time.sleep(0.0002)
                    lat.append(time.monotonic() - t0)
                    time.sleep(0.005)
            finally:
                stop.set()
                if wake is not None:
                    wake.set()
                worker.join(timeout=5.0)
            lat.sort()
            return round(lat[len(lat) // 2] * 1000, 3)

        watch_p50 = _drain_latency(watch=True)
        tick_p50 = _drain_latency(watch=False)

        # -- incremental vs full checkpoint on the 50k store ---------
        store, _queues, _eng = _build(preemption=True, small=small)
        n_wl = len(store.workloads)
        d = tempfile.mkdtemp(prefix="kueue-bench-stream-")
        ship = tempfile.mkdtemp(prefix="kueue-bench-ship-")
        mgr = PersistenceManager(
            d, fsync="off", incremental=True,
            full_checkpoint_every=1 << 30, ship_to=ship,
            checkpoint_interval_records=1 << 62,
            checkpoint_interval_seconds=0.0)
        mgr.attach(store)
        t0 = time.monotonic()
        mgr.checkpoint(force_full=True)
        full_ms = (time.monotonic() - t0) * 1000
        dirty_n = max(1, n_wl // 50)  # 2% dirty keys
        keys = list(store.workloads)[:dirty_n]
        for k in keys:
            store.update_workload(store.workloads[k])
        mgr.flush()
        t0 = time.monotonic()
        mgr.checkpoint()
        incr_ms = (time.monotonic() - t0) * 1000
        # -- shipped bytes per churn cycle ---------------------------
        base = mgr.shipper.shipped_bytes
        churn_cycles = 5
        for c in range(churn_cycles):
            for k in keys[:200]:
                store.update_workload(store.workloads[k])
            mgr.flush()
        shipped_per_cycle = (mgr.shipper.shipped_bytes
                             - base) // churn_cycles
        mgr.close()
        shutil.rmtree(d, ignore_errors=True)
        shutil.rmtree(ship, ignore_errors=True)
        s1 = arms[("stream", "single")]
        b1 = arms[("batch", "single")]
        sw = arms[("stream", "wide")]
        return {
            "scenario": scenario,
            "workloads": s1["workloads"],
            "cluster_queues": s1["cluster_queues"],
            "solve_cadence_ms": s1["solve_cadence_ms"],
            "stream_tta_ms_p50": p50_s,
            "stream_tta_ms_p95": s1["tta_ms_p95"],
            "batch_tta_ms_p50": p50_b,
            "batch_tta_ms_p95": b1["tta_ms_p95"],
            "tta_p50_speedup": (round(p50_b / p50_s, 1)
                                if p50_s else None),
            "stream_admitted_subcycle": s1["stream_admitted"],
            "stream_wall": s1["wall"],
            "batch_wall": b1["wall"],
            "wide_stream_tta_ms_p50": wp50_s,
            "wide_batch_tta_ms_p50": wp50_b,
            "wide_tta_p50_speedup": (round(wp50_b / wp50_s, 1)
                                     if wp50_s else None),
            "wide_stream_admitted_subcycle": sw["stream_admitted"],
            "wide_stream_eligible_fraction": sw[
                "stream_eligible_fraction"],
            "watch_tta_ms_p50": watch_p50,
            "tick_tta_ms_p50": tick_p50,
            "watch_vs_tick_delta_ms": round(tick_p50 - watch_p50, 3),
            "ckpt_workloads": n_wl,
            "checkpoint_full_ms": round(full_ms, 1),
            "checkpoint_incremental_ms": round(incr_ms, 1),
            "checkpoint_incremental_pct": round(
                incr_ms / full_ms * 100, 1) if full_ms else None,
            "dirty_fraction_pct": round(dirty_n / n_wl * 100, 2),
            "shipped_bytes_per_cycle": int(shipped_per_cycle),
        }

    if scenario == "megascale":
        # million-workload control plane (docs/ARCHITECTURE.md
        # "Columnar export path"): the export/delta/micro-drain
        # pipeline at BENCH_MEGA_WLS x BENCH_MEGA_CQS (default 1M x
        # 10k). Three stories, each with its own budget line in the
        # JSON tail:
        #   1. columnar export — the unchanged-store re-export
        #      (incrementally-maintained columns, O(dirty) refresh)
        #      vs the classic O(W) per-row dict walk, plus the
        #      churned-store scatter re-export with dirty-row counts;
        #   2. delta encode — the hint-driven DELTA frame straight
        #      from the dirty columns after a clustered churn;
        #   3. streamed burst — a coalesced arrival burst through the
        #      device micro-solve vs the per-entry host walk. The
        #      engine commit (store writes, metrics, recorder) is
        #      bit-identical work in both arms — parity requires it —
        #      so the decision-phase rates subtract it on the host
        #      side and time the kernel solve on the device side;
        #      end-to-end walls for both arms ride along unsubtracted.
        import gc

        from kueue_oss_tpu.api.types import (
            ClusterQueue as _CQ,
            FlavorQuotas as _FQ,
            LocalQueue as _LQ,
            Node as _Node,
            PodSet as _PS,
            ResourceFlavor as _RF,
            ResourceGroup as _RG,
            ResourceQuota as _RQ,
            Workload as _WL,
        )
        from kueue_oss_tpu.core.queue_manager import QueueManager
        from kueue_oss_tpu.core.store import Store as _Store
        from kueue_oss_tpu.solver.delta import HostDeltaSession
        from kueue_oss_tpu.solver.engine import SolverEngine
        from kueue_oss_tpu.solver.tensors import export_problem

        W = int(os.environ.get("BENCH_MEGA_WLS", "1000000"))
        C = int(os.environ.get("BENCH_MEGA_CQS", "10000"))
        churn_n = min(int(os.environ.get("BENCH_MEGA_CHURN", "4096")),
                      W // 2)
        burst = int(os.environ.get("BENCH_MEGA_BURST", "8192"))
        per_cq = max(1, W // C)

        def _flat_cq(name, nominal):
            return _CQ(name=name, resource_groups=[_RG(
                covered_resources=["cpu"],
                flavors=[_FQ(name="default", resources=[
                    _RQ(name="cpu", nominal=nominal)])])])

        store = _Store()
        store.upsert_resource_flavor(_RF(name="default"))
        store.upsert_node(_Node(name="n1",
                                allocatable={"cpu": 10 ** 12}))
        for c in range(C):
            store.upsert_cluster_queue(
                _flat_cq(f"cq{c:05d}", 10_000_000))
            store.upsert_local_queue(
                _LQ(name=f"lq{c:05d}", cluster_queue=f"cq{c:05d}"))
        log(f"[megascale] {C} CQs up; adding {W} workloads")
        # block assignment (workload i -> CQ i // per_cq) keeps the
        # churn slice below clustered in a few hot CQs, the realistic
        # dirty-set shape for the scatter re-export
        for i in range(W):
            c = min(i // per_cq, C - 1)
            store.add_workload(_WL(
                name=f"w{i}", queue_name=f"lq{c:05d}", uid=i + 1,
                creation_time=float(i) * 1e-3,
                podsets=[_PS(count=1,
                             requests={"cpu": 100 + (i % 5) * 50})]))
        queues = QueueManager(store)
        engine = SolverEngine(store, queues)
        cache = engine.export_cache
        pending = engine.pending_backlog()
        n_pend = sum(len(v) for v in pending.values())
        log(f"[megascale] backlog built: {n_pend} pending")

        # -- 1. export: classic walk vs columnar ---------------------
        t0 = time.monotonic()
        p_cold = export_problem(store, pending, now=1.0, cache=cache,
                                columnar=False)
        export_cold_s = time.monotonic() - t0
        t0 = time.monotonic()
        p_walk = export_problem(store, pending, now=1.0, cache=cache,
                                columnar=False)
        export_walk_s = time.monotonic() - t0
        t0 = time.monotonic()
        export_problem(store, pending, now=1.0, cache=cache)
        export_build_s = time.monotonic() - t0
        t0 = time.monotonic()
        p_cached = export_problem(store, pending, now=1.0, cache=cache)
        export_unchanged_s = time.monotonic() - t0
        stats = dict(cache.columnar.last_stats) \
            if cache.columnar is not None else {}
        log(f"[megascale] export: cold {export_cold_s:.2f}s, warm walk "
            f"{export_walk_s:.2f}s, columnar build {export_build_s:.2f}s, "
            f"unchanged {export_unchanged_s * 1000:.1f}ms "
            f"({stats.get('mode')})")
        identical = (
            p_cached.n_workloads == p_walk.n_workloads
            and p_cached.wl_keys == p_walk.wl_keys
            and p_cached.cq_names == p_walk.cq_names
            and all(np.array_equal(getattr(p_cached, f),
                                   getattr(p_walk, f))
                    for f in ("wl_cqid", "wl_rank", "wl_prio", "wl_ts",
                              "wl_uid", "wl_req", "wl_valid",
                              "nominal", "usage0")))

        # -- 2. clustered churn: scatter re-export + DELTA encode ----
        sess = HostDeltaSession(cache=cache)
        sess.cheap_checksum = True
        sess.advance(p_cached,
                     hint=getattr(p_cached, "_columnar_hint", None))
        for i in range(churn_n):
            wl = store.workloads[f"default/w{i}"]
            wl.podsets[0].requests["cpu"] += 50
            store.update_workload(wl)
        pending2 = engine.pending_backlog()
        t0 = time.monotonic()
        p_churn = export_problem(store, pending2, now=1.0, cache=cache)
        export_churn_s = time.monotonic() - t0
        churn_stats = dict(cache.columnar.last_stats) \
            if cache.columnar is not None else {}
        t0 = time.monotonic()
        _slotted, frame = sess.advance(
            p_churn, hint=getattr(p_churn, "_columnar_hint", None))
        delta_encode_s = time.monotonic() - t0
        frame_kind = ("delta" if frame.delta is not None
                      else (frame.full_reason or "full"))
        log(f"[megascale] churn {churn_n}: re-export "
            f"{export_churn_s * 1000:.1f}ms ({churn_stats.get('mode')}, "
            f"{churn_stats.get('dirty_rows')} dirty), encode "
            f"{delta_encode_s * 1000:.1f}ms ({frame_kind})")

        del (store, queues, engine, cache, pending, pending2, p_cold,
             p_walk, p_cached, p_churn, sess, frame)
        gc.collect()

        # -- 3. streamed burst: device micro-solve vs host walk ------
        from kueue_oss_tpu.scheduler.scheduler import Scheduler

        burst_cqs = min(256, C)

        def _burst_arm(micro):
            st = _Store()
            st.upsert_resource_flavor(_RF(name="default"))
            st.upsert_node(_Node(name="n1",
                                 allocatable={"cpu": 10 ** 12}))
            for c in range(burst_cqs):
                st.upsert_cluster_queue(
                    _flat_cq(f"bq{c}", 10 ** 9))
                st.upsert_local_queue(
                    _LQ(name=f"blq{c}", cluster_queue=f"bq{c}"))
            qs = QueueManager(st)
            sc = Scheduler(st, qs, solver="auto",
                           solver_min_backlog=0, streaming=True)
            sc._solver_engine().drain(now=0.0, verify=True)
            sa = sc._streaming_admitter()
            sa.micro_solve = micro
            sa.micro_solve_min = 1
            sa.max_batch = burst + 64

            def _arrivals(uid0, now):
                for j in range(burst):
                    st.add_workload(_WL(
                        name=f"bw{uid0 + j}",
                        queue_name=f"blq{j % burst_cqs}",
                        uid=uid0 + j, creation_time=now,
                        podsets=[_PS(count=1,
                                     requests={"cpu": 100})]))

            _arrivals(1, 1.0)
            r = sc.micro_drain(1.5)  # warm (compiles the micro kernel)
            assert r.admitted == burst, (micro, r.admitted)
            _arrivals(10_000_000, 2.0)
            t0 = time.monotonic()
            r = sc.micro_drain(2.5)
            wall = time.monotonic() - t0
            assert r.admitted == burst, (micro, r.admitted)
            assert r.micro_batch == (burst if micro else 0)
            return wall, r

        wall_h, r_h = _burst_arm(False)
        wall_m, r_m = _burst_arm(True)
        host_decision_s = max(wall_h - r_h.commit_s, 1e-9)
        log(f"[megascale] burst {burst} x {burst_cqs} CQs: host "
            f"{wall_h * 1000:.0f}ms (commit {r_h.commit_s * 1000:.0f}ms)"
            f", micro {wall_m * 1000:.0f}ms (export "
            f"{r_m.micro_export_s * 1000:.0f}ms solve "
            f"{r_m.micro_solve_s * 1000:.0f}ms commit "
            f"{r_m.commit_s * 1000:.0f}ms)")

        return {
            "scenario": scenario,
            "workloads": W,
            "cqs": C,
            "pending": n_pend,
            "export_ms": round(export_cold_s * 1000, 1),
            "export_walk_warm_ms": round(export_walk_s * 1000, 1),
            "export_columnar_build_ms": round(export_build_s * 1000, 1),
            "export_ms_unchanged": round(export_unchanged_s * 1000, 3),
            "export_speedup": round(
                export_cold_s / max(export_unchanged_s, 1e-9), 1),
            "export_speedup_warm": round(
                export_walk_s / max(export_unchanged_s, 1e-9), 1),
            "export_mode_unchanged": stats.get("mode"),
            "columnar_identical": bool(identical),
            "churn_rows": churn_n,
            "export_churn_ms": round(export_churn_s * 1000, 1),
            "export_churn_mode": churn_stats.get("mode"),
            "export_churn_dirty_rows": churn_stats.get("dirty_rows"),
            "delta_encode_ms": round(delta_encode_s * 1000, 2),
            "delta_frame": frame_kind,
            "burst": burst,
            "burst_cqs": burst_cqs,
            "micro_solve_ms": round(r_m.micro_solve_s * 1000, 2),
            "micro_export_ms": round(r_m.micro_export_s * 1000, 2),
            "stream_commit_ms_host": round(r_h.commit_s * 1000, 1),
            "stream_commit_ms_micro": round(r_m.commit_s * 1000, 1),
            "stream_e2e_ms_host": round(wall_h * 1000, 1),
            "stream_e2e_ms_micro": round(wall_m * 1000, 1),
            # decision-phase rates: host = per-entry walk net of the
            # shared commit; device = the coalesced kernel solve
            "arrivals_per_sec": round(burst / max(r_m.micro_solve_s,
                                                  1e-9), 1),
            "arrivals_per_sec_host": round(burst / host_decision_s, 1),
            "arrivals_speedup": round(
                host_decision_s / max(r_m.micro_solve_s, 1e-9), 1),
        }

    if scenario == "parity":
        # 1/10-scale contended preemption drain: kernel vs host
        store_h, queues_h, _ = _build(preemption=True, small=True)
        from kueue_oss_tpu.scheduler.scheduler import Scheduler

        sched = Scheduler(store_h, queues_h)
        t0 = time.monotonic()
        sched.run_until_quiet(now=0.0, max_cycles=20000, tick=1.0)
        host_s = time.monotonic() - t0
        admitted_h = {k for k, w in store_h.workloads.items()
                      if w.is_quota_reserved}

        store_k, queues_k, engine = _build(preemption=True, small=True)
        t0 = time.monotonic()
        engine.drain(now=0.0)
        kernel_s = time.monotonic() - t0
        admitted_k = {k for k, w in store_k.workloads.items()
                      if w.is_quota_reserved}
        agree = len(admitted_h & admitted_k)
        union = len(admitted_h | admitted_k) or 1
        return {
            "scenario": scenario,
            "host_admitted": len(admitted_h),
            "kernel_admitted": len(admitted_k),
            "plan_agreement": agree / union,
            "host_seconds": host_s,
            "kernel_seconds": kernel_s,
        }

    if scenario == "telemetry_arm":
        # internal helper for the "telemetry" twin: one PAIRED run of
        # the devtel collector off/on. Whole-run subprocess twins (the
        # slo_arm protocol) cannot resolve this measurement — the
        # per-drain wall is solver-execution dominated and swings
        # +/-15% BETWEEN interpreters, far above the <=2% bar — so the
        # two arms instead alternate per cycle inside ONE process on
        # one shared store trajectory: even churn cycles run with the
        # collector off, odd cycles with everything on (compile
        # accounting, transfer ledger, HBM watermarks, armed capture,
        # fabric tracer), and the medians of each parity are compared.
        import gc
        import tempfile

        from kueue_oss_tpu import metrics as kmetrics
        from kueue_oss_tpu import obs
        from kueue_oss_tpu.api.types import PodSet, Workload
        from kueue_oss_tpu.debugger.profiling import Tracer
        from kueue_oss_tpu.federation import attach_farm
        from kueue_oss_tpu.obs import devtel
        from kueue_oss_tpu.scheduler.scheduler import Scheduler
        from kueue_oss_tpu.solver.service import SolverClient, SolverServer

        # 32 cycles PER PARITY: the per-cycle wall carries multi-ms
        # solver-execution noise, and the parity medians need enough
        # samples to resolve a sub-percent delta
        n_cycles = int(os.environ.get("BENCH_DEVTEL_CYCLES", "32"))
        warm_cycles = 2

        store, queues, engine = _build(preemption=True, small=small)
        sched = Scheduler(store, queues)
        engine.scheduler = sched
        obs.cycle_ledger.enabled = True  # constant across both arms
        col = devtel.collector
        col.compile_enabled = True
        col.transfer_enabled = True
        col.hbm_enabled = True
        col.capture_enabled = True
        tracer = Tracer()
        col.tracer = tracer

        def set_devtel(on: bool) -> None:
            col.enabled = on
            engine.tracer = tracer if on else None

        set_devtel(True)  # warm-up runs the full collector path
        path = os.path.join(tempfile.mkdtemp(), "solver.sock")
        srv = SolverServer(path)
        attach_farm(srv, weights={"bench": 1.0})
        srv.serve_in_background()
        n_wl = len(store.workloads)
        churn = max(1, n_wl // 200)
        # one padded capacity across the run (no pow2-boundary resyncs)
        engine.pad_to = n_wl + churn * (2 * n_cycles + warm_cycles) + 1
        try:
            # cycle 0 drains IN-PROCESS: the engine's own arm router
            # times the solve, so the compile probe sees the fresh XLA
            # compiles (the sidecar's solves are outside the host
            # router); the churn cycles then run through the sidecar
            engine.drain(now=0.0, verify=True)
            engine.remote = SolverClient(path, tenant="bench")
            lqs = sorted({w.queue_name for w in store.workloads.values()})
            proto = next(iter(store.workloads.values()))
            req = dict(proto.podsets[0].requests)
            uid = max(w.uid for w in store.workloads.values()) + 1
            t_base = max(w.creation_time
                         for w in store.workloads.values()) + 1.0

            def churn_cycle(cyc):
                admitted = [k for k, w in store.workloads.items()
                            if w.is_quota_reserved and not w.is_finished]
                for k in admitted[:churn]:
                    sched.finish_workload(k, now=float(cyc))
                for j in range(churn):
                    i = uid + cyc * churn + j
                    store.add_workload(Workload(
                        name=f"churn-{cyc}-{j}",
                        queue_name=lqs[i % len(lqs)], uid=i,
                        creation_time=t_base + cyc * churn + j,
                        podsets=[PodSet(name="main", count=1,
                                        requests=dict(req))]))
                engine.drain(now=float(cyc), verify=True)

            for c in range(1, warm_cycles + 1):  # churn settles in
                churn_cycle(c)
            # keep the collector out of the timed window (slo_arm
            # discipline): a GC pass over the 50k-object store is
            # multiple percent of the wall
            gc.collect()
            gc.disable()
            walls: dict[bool, list[float]] = {False: [], True: []}
            try:
                for i, c in enumerate(range(
                        warm_cycles + 1,
                        warm_cycles + 1 + 2 * n_cycles)):
                    # ABBA assignment (off,on,on,off,...): churn
                    # cycles carry an intrinsic even/odd rhythm, so a
                    # plain alternation would conflate that parity
                    # with the collector under test
                    on = bool(i % 2) ^ bool((i // 2) % 2)
                    set_devtel(on)
                    t0 = time.monotonic()
                    churn_cycle(c)
                    walls[on].append(time.monotonic() - t0)
            finally:
                gc.enable()
                set_devtel(True)
        finally:
            srv.shutdown()
            srv.server_close()
        out = {"scenario": scenario, "workloads": n_wl,
               "cycles": n_cycles,
               # median-of-cycles x n beats the window sum: one
               # straggler cycle (an XLA recompile, a socket hiccup)
               # is several percent of a window — far above the delta
               # under measurement
               "wall_off": round(
                   float(np.median(walls[False])) * n_cycles, 4),
               "wall_on": round(
                   float(np.median(walls[True])) * n_cycles, 4)}
        # evidence OUTSIDE the timed window: the acceptance bar wants
        # non-zero compile events + transfer bytes, a grant-wait p50
        # out of the ledger rows, the synthetic track count of the
        # merged timeline, and a deterministic virtual-clock capture
        # drill
        out["compiles_detected"] = int(
            kmetrics.solver_compiles_total.total())
        out["transfer_bytes_total"] = int(
            kmetrics.solver_transfer_bytes_total.total())
        waits = [r.grant_wait_ms for r in obs.cycle_ledger.rows()
                 if r.kind != "host"]
        out["grant_wait_ms_p50"] = (
            round(float(np.percentile(waits, 50)), 4)
            if waits else 0.0)
        doc = json.loads(tracer.chrome_trace())
        out["trace_tracks"] = len({
            e.get("tid") for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "thread_name"})
        cap = col.capture
        cap.reset()  # clear any phase-regression cooldown stamp
        vt = [0.0]
        cap.clock = lambda: vt[0]
        cap.dir = tempfile.mkdtemp()
        cap.max_seconds = 0.5
        started = cap.trigger("manual", {"source": "bench_drill"})
        vt[0] = 1.0
        finished = cap.poll()
        marker = bool(cap.history and cap.history[-1].get("path")
                      and os.path.exists(os.path.join(
                          cap.history[-1]["path"], "capture.json")))
        out["capture_trigger_works"] = bool(
            started and finished and marker)
        return out

    if scenario == "telemetry":
        # device-telemetry overhead twin on the 50k x 1k churn shape
        # (docs/OBSERVABILITY.md "Device telemetry & fabric tracing"):
        # one sidecar+farm churn loop whose cycles alternate the
        # devtel collector off and fully on (compile accounting +
        # transfer ledger + HBM watermarks + armed capture + fabric
        # tracer) inside each hash-seed-pinned subprocess, repeated
        # reps times. The overhead is computed PER REP (the pairing
        # lives inside one process; min-reducing the parities
        # independently would re-introduce the between-process noise)
        # and median-reduced across reps. The JSON
        # tail reports the relative overhead (<=2% acceptance bar,
        # enforced by tools/benchcheck.py --strict) plus the on-arm
        # evidence: compile events detected, unified transfer bytes,
        # the grant-wait p50 out of the ledger, the merged timeline's
        # synthetic track count, and the capture trigger drill.
        import statistics

        reps = int(os.environ.get("BENCH_DEVTEL_REPS", "3"))
        pcts, offs, ons = [], [], []
        res = None
        for _ in range(reps):
            res = measure("telemetry_arm",
                          extra_env={"PYTHONHASHSEED": "0"},
                          timeout=600)
            offs.append(res["wall_off"])
            ons.append(res["wall_on"])
            if res["wall_off"] > 0:
                pcts.append((res["wall_on"] - res["wall_off"])
                            / res["wall_off"] * 100)
        return {
            "scenario": scenario,
            "workloads": res["workloads"],
            "cycles": res["cycles"],
            "seconds_devtel_off": round(min(offs), 3),
            "seconds_devtel_on": round(min(ons), 3),
            "devtel_overhead_pct": (round(statistics.median(pcts), 2)
                                    if pcts else 0.0),
            "compiles_detected": res["compiles_detected"],
            "transfer_bytes_total": res["transfer_bytes_total"],
            "grant_wait_ms_p50": res["grant_wait_ms_p50"],
            "trace_tracks": res["trace_tracks"],
            "capture_trigger_works": res["capture_trigger_works"],
        }

    raise SystemExit(f"unknown scenario {scenario}")


def measure(scenario: str, extra_env: dict | None = None,
            timeout: int = 1800) -> dict:
    """Run one scenario in a fresh subprocess (AOT compile inside)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--scenario", scenario]
    env = dict(os.environ)
    env.update(extra_env or {})
    t0 = time.monotonic()
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          env=env, timeout=timeout)
    if proc.returncode != 0:
        log(proc.stderr[-3000:])
        raise RuntimeError(f"scenario {scenario} failed")
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    log(f"[{scenario}] {result} (subprocess total "
        f"{time.monotonic() - t0:.1f}s)")
    return result


#: preempt-scenario scale ladder: (label, env, subprocess timeout). The
#: tunneled TPU stalls on device programs beyond ~100 CQs / 5k workloads
#: (remote compile/execution never returns); the bench reports the
#: largest scale that completes and says so.
SCALES = [
    ("50k_wl_1000_cqs", {}, 2400),
    ("25k_wl_500_cqs", {"BENCH_COHORTS": "10", "BENCH_CQS": "50"}, 1500),
    ("10k_wl_200_cqs", {"BENCH_COHORTS": "4", "BENCH_CQS": "50"}, 1200),
    ("5k_wl_100_cqs", {"BENCH_COHORTS": "4", "BENCH_CQS": "25"}, 900),
]


def main() -> None:
    if "--scenario" in sys.argv:
        scenario = sys.argv[sys.argv.index("--scenario") + 1]
        print(json.dumps(run_scenario(scenario)), flush=True)
        return

    t_start = time.monotonic()
    preempt = None
    scale_label = None
    platform = "tpu"
    # a wedged tunnel HANGS jax init rather than erroring; probe it with
    # a short-lived subprocess so a dead device costs 120s, not the
    # whole scale ladder's timeouts
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=120)
        device_ok = probe.returncode == 0 and "ok" in probe.stdout
    except subprocess.TimeoutExpired:
        device_ok = False
    if not device_ok:
        log("[probe] TPU backend unreachable; skipping the TPU ladder")
        platform = "cpu_fallback"
    for label, env, tmo in (SCALES if device_ok else []):
        try:
            preempt = measure("preempt", extra_env=env, timeout=tmo)
            scale_label = label
            break
        except Exception as e:  # timeout / device stall: try smaller
            log(f"[preempt@{label}] did not complete: {e}")
    if preempt is None:
        # the tunneled TPU can go UNAVAILABLE entirely; an honest
        # CPU-backend number beats recording nothing (labeled below)
        platform = "cpu_fallback"
        log("[preempt] TPU unavailable at every scale; "
            "falling back to the host backend")
        for label, env, tmo in SCALES:
            try:
                preempt = measure("preempt",
                                  extra_env={**env, "BENCH_CPU": "1"},
                                  timeout=tmo)
                scale_label = label
                break
            except Exception as e:
                log(f"[preempt@{label} cpu] did not complete: {e}")
    if preempt is None:
        raise RuntimeError("preempt scenario failed at every scale")

    dev_env = {"BENCH_CPU": "1"} if platform == "cpu_fallback" else {}
    # per-cycle latency at the full 50k x 1k shape — THE north-star
    # metric (<200 ms/cycle on device); falls back to the host backend
    # with an honest label
    cycles_platform = "cpu" if dev_env else "tpu"
    try:
        cycles = measure("cycles", extra_env={
            **dev_env, "BENCH_CYCLES": "20"}, timeout=1800)
    except Exception as e:
        log(f"[cycles] did not complete, retrying on cpu: {e}")
        cycles_platform = "cpu"
        cycles = measure("cycles", extra_env={
            "BENCH_CPU": "1", "BENCH_CYCLES": "20"}, timeout=1800)
    scenario_platform = {}

    def measure_with_fallback(name, timeout):
        """Per-scenario CPU retry with an HONEST per-scenario label."""
        scenario_platform[name] = ("cpu" if dev_env else "tpu")
        try:
            return measure(name, extra_env=dev_env, timeout=timeout)
        except Exception as e:
            log(f"[{name}] did not complete, retrying on cpu: {e}")
            scenario_platform[name] = "cpu"
            return measure(name, extra_env={"BENCH_CPU": "1"},
                           timeout=timeout)

    parity = measure_with_fallback("parity", 1800)
    lean = measure_with_fallback("lean", 1800)
    try:
        hetero = measure_with_fallback("hetero", 1800)
    except Exception as e:
        log(f"[hetero] did not complete: {e}")
        hetero = None
    try:
        tas = measure_with_fallback("tas", 1200)
    except Exception as e:
        log(f"[tas cpu] did not complete: {e}")
        tas = None
    # the reference's own benchmark protocol: once through the host
    # control plane alone, once with every backlog drain routed through
    # the solver engine (the TPU-native headline; device-backed when the
    # tunnel is up)
    try:
        tas_drain = measure_with_fallback("tas_drain", 1800)
    except Exception as e:
        log(f"[tas_drain] did not complete: {e}")
        tas_drain = None
    try:
        sim = measure("sim_baseline", extra_env={"BENCH_CPU": "1"},
                      timeout=1800)
    except Exception as e:
        # the headline scenario must not discard the completed ones
        log(f"[sim_baseline] did not complete: {e}")
        sim = None
    # the solver-backed reference protocol on BOTH backends: the XLA:CPU
    # run shows the control-plane + kernel cost without tunnel dispatch
    # latency; the device run is the end-to-end TPU number. The better
    # one is eligible for the headline (labeled).
    try:
        sim_solver_cpu = measure(
            "sim_baseline",
            extra_env={"BENCH_CPU": "1", "BENCH_SOLVER": "1"},
            timeout=1800)
    except Exception as e:
        log(f"[sim_baseline solver cpu] did not complete: {e}")
        sim_solver_cpu = None
    sim_solver_dev = None
    if not dev_env:
        try:
            sim_solver_dev = measure(
                "sim_baseline", extra_env={"BENCH_SOLVER": "1"},
                timeout=1800)
        except Exception as e:
            log(f"[sim_baseline solver tpu] did not complete: {e}")
    if sim_solver_dev is not None and (
            sim_solver_cpu is None
            or sim_solver_dev["adm_per_s"] >= sim_solver_cpu["adm_per_s"]):
        sim_solver, solver_platform = sim_solver_dev, "tpu"
    else:
        sim_solver, solver_platform = sim_solver_cpu, "cpu"
    # the large-scale config (1000 CQs / 50k wl) through the same
    # churned protocol; reference target ~41.7 adm/s (1200s wall)
    try:
        sim_large = measure("sim_large", extra_env={"BENCH_CPU": "1"},
                            timeout=1800)
    except Exception as e:
        log(f"[sim_large] did not complete: {e}")
        sim_large = None
    # seeded fault storm through the chaos harness (host backend; the
    # scenario's point is the control plane surviving, not kernel speed)
    try:
        chaos = measure("chaos", extra_env={"BENCH_CPU": "1"},
                        timeout=900)
    except Exception as e:
        log(f"[chaos] did not complete: {e}")
        chaos = None
    # composed-fault campaigns + convergence oracle (host backend:
    # the measurement is recovery discipline, not kernel speed)
    try:
        campaign = measure("chaoscampaign",
                           extra_env={"BENCH_CPU": "1"}, timeout=1200)
    except Exception as e:
        log(f"[chaoscampaign] did not complete: {e}")
        campaign = None
    # flight-recorder overhead on the 50k x 1k host cycle shape (host
    # backend: the recorder instruments the host path)
    try:
        recorder = measure("recorder", extra_env={"BENCH_CPU": "1"},
                           timeout=1800)
    except Exception as e:
        log(f"[recorder] did not complete: {e}")
        recorder = None
    # cluster health layer (ledger + SLO + exemplars) on the same host
    # cycle shape (docs/OBSERVABILITY.md acceptance: combined < 2%)
    try:
        slo = measure("slo", extra_env={"BENCH_CPU": "1"},
                      timeout=1800)
    except Exception as e:
        log(f"[slo] did not complete: {e}")
        slo = None
    # device telemetry collector (compile accounting + transfer
    # ledger + HBM watermarks + capture + fabric tracer) on the same
    # churn shape (docs/OBSERVABILITY.md "Device telemetry & fabric
    # tracing" acceptance: <= 2%)
    try:
        telemetry = measure("telemetry", extra_env={"BENCH_CPU": "1"},
                            timeout=1800)
    except Exception as e:
        log(f"[telemetry] did not complete: {e}")
        telemetry = None
    # durable control plane on the 50k x 1k churn shape (host backend:
    # the WAL instruments the host write path; docs/DURABILITY.md
    # acceptance: wal_overhead_pct under ~5%)
    try:
        durability = measure("durability", extra_env={"BENCH_CPU": "1"},
                             timeout=1800)
    except Exception as e:
        log(f"[durability] did not complete: {e}")
        durability = None
    # delta-sync steady state on the 50k x 1k churn shape: wire bytes
    # per cycle vs the full sync frame + resync count
    # (docs/SOLVER_PROTOCOL.md acceptance: steady-state deltas ship
    # >= 50x fewer payload bytes than a full-sync cycle)
    try:
        delta = measure_with_fallback("delta", 2400)
    except Exception as e:
        log(f"[delta] did not complete: {e}")
        delta = None
    # the production multi-chip path (mesh-resident sessions, donated
    # row scatters, sharded drain) on a virtual 8-device host mesh —
    # same XLA partitioner as real multi-chip; labeled honestly
    try:
        multichip = measure("multichip", extra_env={
            "BENCH_CPU": "1",
            "XLA_FLAGS": ("--xla_force_host_platform_device_count=8 "
                          "--xla_cpu_parallel_codegen_split_count=1 "
                          "--xla_cpu_max_isa=AVX")}, timeout=2400)
    except Exception as e:
        log(f"[multichip] did not complete: {e}")
        multichip = None
    # pod-scale solver: row-sharded FULL drain + byte-identity twin,
    # churned-session shard imbalance classic vs interleaved, and the
    # epoch-migration resync count (docs/SOLVER_PROTOCOL.md "Pod-scale
    # sessions"); virtual host mesh, same XLA partitioner, no ICI
    try:
        podscale = measure("podscale", extra_env={
            "BENCH_CPU": "1",
            "XLA_FLAGS": ("--xla_force_host_platform_device_count=8 "
                          "--xla_cpu_parallel_codegen_split_count=1 "
                          "--xla_cpu_max_isa=AVX")}, timeout=2400)
    except Exception as e:
        log(f"[podscale] did not complete: {e}")
        podscale = None
    # batched what-if planning: S counterfactual scenarios in one
    # vmapped dispatch vs the sequential oracle (docs/SIMULATOR.md);
    # host backend — the measurement is batching leverage, not device
    # speed, and must run everywhere the planning surfaces do
    try:
        whatif = measure("whatif", extra_env={"BENCH_CPU": "1"},
                         timeout=1200)
    except Exception as e:
        log(f"[whatif] did not complete: {e}")
        whatif = None
    # FULL-kernel what-if sweeps: lane-budgeted chunked batching vs
    # the sequential FULL oracle over a Philly-shaped trace, plus the
    # resident-state and relax-tier measurements (docs/SIMULATOR.md;
    # host backend for the same reason as whatif)
    try:
        fullsweep = measure("fullsweep", extra_env={"BENCH_CPU": "1"},
                            timeout=1200)
    except Exception as e:
        log(f"[fullsweep] did not complete: {e}")
        fullsweep = None
    # federated control planes: multi-tenant solver-farm DRR fairness
    # under contended churn + the what-if-scored dispatcher vs
    # Incremental (docs/FEDERATION.md; host backend — the measurement
    # is arbitration and dispatch quality, not kernel speed)
    try:
        federation = measure("federation",
                             extra_env={"BENCH_CPU": "1"}, timeout=1200)
    except Exception as e:
        log(f"[federation] did not complete: {e}")
        federation = None
    # streaming control plane: p50/p95 time-to-admit streaming vs the
    # cycle-batch twin at the same full-solve cadence, incremental vs
    # full checkpoint wall, shipped bytes per cycle (host backend:
    # the fast path is host-side; the twin is the model comparison)
    try:
        # outer cap covers the two nested streaming_arm subprocesses
        # (1500s inner cap each) plus the 50k checkpoint section
        streaming_res = measure("streaming", extra_env={
            "BENCH_CPU": "1"}, timeout=4200)
    except Exception as e:
        log(f"[streaming] did not complete: {e}")
        streaming_res = None
    # convex-relaxation fast-path arm vs the exact lean kernel on the
    # contended 50k x 1k shape (docs/SOLVER_PROTOCOL.md "Relaxed
    # fast-path arm"; acceptance: >= 2x solve-wall speedup, every plan
    # exactly feasible). Host backend: per-arm subprocess twins.
    try:
        # the twin spawns up to 2 reps x 2 arms of nested relax_arm
        # subprocesses (1500s inner cap each); the outer cap must
        # cover the whole ladder or a slow host silently drops the
        # headline result while every inner arm is within budget
        relax_res = measure("relax", extra_env={"BENCH_CPU": "1"},
                            timeout=6600)
    except Exception as e:
        log(f"[relax] did not complete: {e}")
        relax_res = None
    # million-workload control plane: columnar/delta export budget plus
    # the device micro-drain burst twin (host backend: the export and
    # encode phases are host-side by construction). The full 1M x 10k
    # shape runs only with BENCH_MEGA=1; the default ladder keeps the
    # 50k x 1k smoke shape so the bench wall stays bounded.
    mega_env = {"BENCH_CPU": "1"}
    if os.environ.get("BENCH_MEGA") != "1":
        mega_env.update({"BENCH_MEGA_WLS": "50000",
                         "BENCH_MEGA_CQS": "1000"})
    try:
        mega = measure("megascale", extra_env=mega_env, timeout=3600)
    except Exception as e:
        log(f"[megascale] did not complete: {e}")
        mega = None
    log(f"total bench time {time.monotonic() - t_start:.1f}s")

    # HEADLINE: the reference's own protocol — same shape, same
    # submit/run/finish churn, real wall-clock — so vs_baseline is an
    # apples-to-apples ratio against 351.1s / ~43 adm/s. If the
    # simulator scenario failed, the contended drain's decision rate
    # stands in (labeled by the metric name).
    drain_value = preempt["admitted"] / preempt["seconds"]
    drain_decisions = preempt["workloads"] / preempt["seconds"]
    lean_value = lean["admitted"] / lean["seconds"]
    extra = {}
    if sim is not None:
        extra["baseline_host_adm_per_s"] = round(sim["adm_per_s"], 1)
        extra["baseline_host_wall_s"] = round(sim["seconds"], 1)
        extra["baseline_admitted"] = sim["admitted"]
    if sim_solver is not None:
        extra["baseline_solver_adm_per_s"] = round(
            sim_solver["adm_per_s"], 1)
        extra["baseline_solver_wall_s"] = round(sim_solver["seconds"], 1)
        extra["baseline_solver_admitted"] = sim_solver["admitted"]
        extra["baseline_solver_platform"] = solver_platform
    if sim_solver_cpu is not None and sim_solver is not sim_solver_cpu:
        extra["baseline_solver_cpu_adm_per_s"] = round(
            sim_solver_cpu["adm_per_s"], 1)
    if sim_solver_dev is not None and sim_solver is not sim_solver_dev:
        extra["baseline_solver_tpu_adm_per_s"] = round(
            sim_solver_dev["adm_per_s"], 1)
    if sim_large is not None:
        extra["large_scale_churn_adm_per_s"] = round(
            sim_large["adm_per_s"], 1)
        extra["large_scale_churn_wall_s"] = round(sim_large["seconds"], 1)
        extra["large_scale_churn_admitted"] = sim_large["admitted"]
        # reference placeholder target: 50k / 1200s
        extra["large_scale_churn_vs_target"] = round(
            sim_large["adm_per_s"] / 41.7, 1)
    if tas_drain is not None:
        extra["tas_engine_drain_decisions_per_s"] = round(
            tas_drain["workloads"] / tas_drain["seconds"], 1)
        extra["tas_engine_drain_admitted"] = tas_drain["admitted"]
        extra["tas_engine_drain_placed"] = tas_drain[
            "placed_with_topology"]
        extra["tas_engine_drain_seconds"] = round(
            tas_drain["seconds"], 3)
    # HEADLINE: the better of the two reference-protocol runs, named
    # for the config that produced it. The solver=auto config routes
    # backlog FLOODS to the device and trickles to host cycles
    # (Scheduler.solver_min_backlog); on the 15k baseline's
    # trickle-churn arrival schedule the per-drain host-side export
    # cost keeps the hybrid below the pure host loop on this protocol —
    # the batched path's win is the contended 50k x 1k drain
    # (preempt_drain_* / cycle_ms_* fields).
    if sim_solver is not None and (
            sim is None or sim_solver["adm_per_s"] >= sim["adm_per_s"]):
        metric_name = "baseline_15k_admissions_per_s_solver"
        value = sim_solver["adm_per_s"]
    elif sim is not None:
        metric_name = "baseline_15k_admissions_per_s"
        value = sim["adm_per_s"]
    else:
        metric_name = f"preempt_drain_decisions_{scale_label}"
        value = drain_decisions
    if hetero is not None:
        extra["hetero_decisions_per_s"] = round(
            hetero["workloads"] / hetero["seconds"], 1)
        extra["hetero_workloads"] = hetero["workloads"]
        extra["hetero_admitted"] = hetero["admitted"]
        extra["hetero_rounds"] = hetero["rounds"]
        extra["hetero_seconds"] = round(hetero["seconds"], 3)
    if tas is not None:
        # baseline: 15k wl / 401.5s mean wall => ~37.4 decisions/s
        # (configs/tas/rangespec.yaml). The drain here is one-shot (no
        # workload churn freeing capacity), so `tas_placed` is bounded
        # by the 640-node capacity; the rate counts placement DECISIONS
        # (admit or infeasible), which is what the wall-clock bounds.
        rate = tas["workloads"] / tas["seconds"]
        extra["tas_decisions_per_s_640_nodes"] = round(rate, 1)
        extra["tas_placed"] = tas["placed"]
        extra["tas_vs_baseline"] = round(rate / 37.4, 1)
        if "ext_workloads" in tas:
            extra["tas_slice_leader_decisions_per_s"] = round(
                tas["ext_workloads"] / tas["ext_seconds"], 1)
            extra["tas_slice_leader_placed"] = tas["ext_placed"]
    if chaos is not None:
        extra["chaos_admitted"] = chaos["admitted"]
        extra["chaos_capacity"] = chaos["capacity"]
        extra["chaos_faults_injected"] = chaos["faults_injected"]
        extra["chaos_seconds"] = round(chaos["seconds"], 3)
    if campaign is not None:
        extra["campaign_converged_all"] = campaign["converged_all"]
        extra["campaign_convergence_cycles"] = campaign[
            "convergence_cycles"]
        extra["campaign_max_degradation_level"] = campaign[
            "max_degradation_level"]
        extra["campaign_availability"] = campaign["availability"]
        extra["campaign_unavailable_wall_ms"] = campaign[
            "unavailable_wall_ms"]
        extra["campaign_faults_injected"] = campaign["faults_injected"]
    if recorder is not None:
        # flight-recorder cost + decision volume (docs/OBSERVABILITY.md:
        # the overhead bar is <2% on this shape)
        extra["recorder_overhead_pct"] = recorder[
            "recorder_overhead_pct"]
        extra["decision_events_total"] = recorder[
            "decision_events_total"]
        extra["decision_skips_by_reason"] = recorder["skips_by_reason"]
    if slo is not None:
        # cluster health layer (docs/OBSERVABILITY.md "Cluster health
        # & SLOs"): per-layer and combined off/on twin overheads plus
        # one SLO evaluation's wall over the populated engine
        extra["ledger_overhead_pct"] = slo["ledger_overhead_pct"]
        extra["exemplar_overhead_pct"] = slo["exemplar_overhead_pct"]
        extra["slo_combined_overhead_pct"] = slo[
            "slo_combined_overhead_pct"]
        extra["slo_eval_ms"] = slo["slo_eval_ms"]
        extra["ledger_rows"] = slo["ledger_rows"]
    if telemetry is not None:
        # device telemetry (docs/OBSERVABILITY.md "Device telemetry &
        # fabric tracing"): paired off/on collector overhead plus the
        # compile/transfer/grant-wait/capture evidence bundle
        extra["devtel_overhead_pct"] = telemetry["devtel_overhead_pct"]
        extra["devtel_compiles_detected"] = telemetry[
            "compiles_detected"]
        extra["devtel_transfer_bytes_total"] = telemetry[
            "transfer_bytes_total"]
        extra["devtel_grant_wait_ms_p50"] = telemetry[
            "grant_wait_ms_p50"]
        extra["devtel_capture_trigger_works"] = telemetry[
            "capture_trigger_works"]
    if durability is not None:
        # durable control plane (docs/DURABILITY.md): WAL overhead on
        # the churn shape, atomic checkpoint wall, and recovery
        # (checkpoint + WAL replay) of the 50k-workload store —
        # recovered_identical is the byte-equality bit
        extra["wal_overhead_pct"] = durability["wal_overhead_pct"]
        extra["wal_bytes_per_cycle"] = durability["wal_bytes_per_cycle"]
        extra["checkpoint_ms"] = durability["checkpoint_ms"]
        extra["recovery_ms_50k"] = durability["recovery_ms_50k"]
        extra["recovered_identical"] = durability["recovered_identical"]
        extra["recovery_audit_violations"] = durability[
            "audit_violations"]
    if delta is not None:
        # delta-sync sessions: steady-state wire cost vs the full sync
        # frame, plus the forced-resync count and the steady-state
        # solve wall on the churn shape (docs/SOLVER_PROTOCOL.md)
        extra["delta_bytes_per_cycle"] = delta["delta_bytes_per_cycle"]
        extra["delta_full_frame_bytes"] = delta["full_frame_bytes"]
        extra["delta_bytes_ratio"] = delta["bytes_ratio"]
        extra["resync_count"] = delta["resync_count"]
        extra["delta_cycle_ms_p50_50k_1k"] = round(
            delta["cycle_ms_p50"], 2)
        extra["delta_churn_per_cycle"] = delta["churn_per_cycle"]
    if multichip is not None and not multichip.get("skipped"):
        # production mesh path (docs/SOLVER_PROTOCOL.md "Mesh-resident
        # sessions"): the steady-state drain p50 on the mesh arm, the
        # per-cycle donated scatter bytes vs the full-problem copy a
        # re-upload would ship, shard imbalance, and the parity bit
        extra["mesh_devices"] = multichip["mesh_devices"]
        extra["mesh_drain_ms_p50"] = round(
            multichip["mesh_drain_ms_p50"], 2)
        extra["mesh_single_drain_ms_p50"] = round(
            multichip["single_drain_ms_p50"], 2)
        extra["mesh_shard_imbalance"] = multichip["shard_imbalance_mean"]
        extra["mesh_plans_identical"] = multichip["plans_identical"]
        extra["mesh_donated_update_bytes"] = multichip[
            "donated_update_bytes_per_cycle"]
        extra["mesh_avoided_copy_bytes"] = multichip[
            "avoided_copy_bytes_per_cycle"]
        extra["mesh_uneven_shards"] = multichip["uneven_shards"]
        extra["mesh_preempt_seconds"] = multichip["preempt_mesh_seconds"]
        extra["mesh_platform"] = "cpu_virtual_mesh"
    if podscale is not None and not podscale.get("skipped"):
        # pod-scale solver (docs/SOLVER_PROTOCOL.md "Pod-scale
        # sessions"): the row-sharded FULL drain p50 + parity bit,
        # churned-session imbalance before/after slot interleaving
        # (acceptance: interleaved <= 1.1 while classic drifts), and
        # the bounded epoch-migration resync count
        extra["full_shard_drain_ms_p50"] = round(
            podscale["full_shard_drain_ms_p50"], 2)
        extra["full_shard_plans_identical"] = podscale["plans_identical"]
        extra["full_shard_uneven"] = podscale["uneven_shards"]
        extra["shard_imbalance_classic"] = podscale[
            "shard_imbalance_classic"]
        extra["shard_imbalance_interleaved"] = podscale[
            "shard_imbalance_interleaved"]
        extra["interleave_migration_resyncs"] = podscale[
            "migration_resyncs"]
    if whatif is not None:
        # what-if engine acceptance: >1 vmapped-vs-sequential speedup,
        # plans bit-identical between the two paths
        extra["whatif_scenarios"] = whatif["scenarios"]
        extra["whatif_batch_width"] = whatif["batch_width"]
        extra["whatif_scenarios_per_sec"] = whatif["scenarios_per_sec"]
        extra["whatif_vmapped_speedup"] = whatif["vmapped_speedup"]
        extra["whatif_plans_identical"] = whatif["plans_identical"]
        extra["whatif_workloads"] = whatif["workloads"]
    if fullsweep is not None:
        # FULL-sweep acceptance (docs/SIMULATOR.md): >= 3x chunked-vs-
        # sequential FULL wall, plans bit-identical at the tested lane
        # budget, and a measured resident-state win per sweep
        extra["fullsweep_scenarios"] = fullsweep["scenarios"]
        extra["fullsweep_full_speedup"] = fullsweep["full_speedup"]
        extra["fullsweep_plans_identical"] = fullsweep[
            "plans_identical"]
        extra["fullsweep_resident_win"] = fullsweep["resident_win"]
        extra["fullsweep_relax_scenarios_per_sec"] = fullsweep[
            "relax_scenarios_per_sec"]
        extra["fullsweep_preemptions_total"] = fullsweep[
            "preemptions_total"]
    if federation is not None:
        # federation acceptance (docs/FEDERATION.md): per-tenant solver
        # wall-time shares within 1.5x of the DRR weights, zero
        # cross-tenant session state, farm plans bit-identical to
        # dedicated-sidecar twins, and the what-if dispatcher agreeing
        # with the sequential oracle on >= 95% of scored dispatches
        extra["fed_tenant_wall_share_spread"] = federation[
            "tenant_wall_share_spread"]
        extra["fed_farm_solves"] = federation["farm_solves"]
        extra["fed_zero_cross_tenant"] = federation["zero_cross_tenant"]
        extra["fed_plans_identical_dedicated"] = federation[
            "plans_identical_dedicated"]
        extra["fed_whatif_oracle_agreement"] = federation[
            "whatif_oracle_agreement"]
        extra["fed_dispatch_score_ms_mean"] = federation[
            "dispatch_score_ms_mean"]
        extra["fed_whatif_time_to_admit_s"] = federation[
            "whatif_time_to_admit_s"]
        extra["fed_incremental_time_to_admit_s"] = federation[
            "incremental_time_to_admit_s"]
        extra["fed_whatif_admit_speedup"] = federation[
            "whatif_admit_speedup"]
    if streaming_res is not None:
        # streaming control plane acceptance: p50 time-to-admit
        # decoupled from the full-solve cadence (>= 5x below the
        # batch twin), incremental checkpoint < 20% of the full wall
        # at <5% dirty keys, shipped bytes per churn cycle
        extra["stream_tta_ms_p50"] = streaming_res["stream_tta_ms_p50"]
        extra["stream_tta_ms_p95"] = streaming_res["stream_tta_ms_p95"]
        extra["batch_tta_ms_p50"] = streaming_res["batch_tta_ms_p50"]
        extra["stream_tta_p50_speedup"] = streaming_res[
            "tta_p50_speedup"]
        extra["stream_admitted_subcycle"] = streaming_res[
            "stream_admitted_subcycle"]
        # wide-fence acceptance: the multi-flavor + borrow-capable
        # fleet (which the structural fences streamed ~0 on) streams
        # >= 0.8 of pending CQs at <= 2x the single-flavor p50, and
        # the watch-driven drain beats the fixed-cadence tick
        extra["wide_stream_eligible_fraction"] = streaming_res[
            "wide_stream_eligible_fraction"]
        extra["wide_stream_tta_ms_p50"] = streaming_res[
            "wide_stream_tta_ms_p50"]
        extra["wide_stream_admitted_subcycle"] = streaming_res[
            "wide_stream_admitted_subcycle"]
        extra["wide_tta_p50_speedup"] = streaming_res[
            "wide_tta_p50_speedup"]
        extra["watch_vs_tick_delta_ms"] = streaming_res[
            "watch_vs_tick_delta_ms"]
        extra["checkpoint_full_ms"] = streaming_res[
            "checkpoint_full_ms"]
        extra["checkpoint_incremental_ms"] = streaming_res[
            "checkpoint_incremental_ms"]
        extra["checkpoint_incremental_pct"] = streaming_res[
            "checkpoint_incremental_pct"]
        extra["shipped_bytes_per_cycle"] = streaming_res[
            "shipped_bytes_per_cycle"]
    if mega is not None:
        # million-workload control plane acceptance: unchanged-store
        # columnar re-export >= 20x the from-scratch walk, the DELTA
        # frame encoded straight from dirty columns, and the device
        # micro-drain decision rate >= 10x the host per-entry walk
        extra["mega_workloads"] = mega["workloads"]
        extra["mega_cqs"] = mega["cqs"]
        extra["mega_export_ms"] = mega["export_ms"]
        extra["mega_export_ms_unchanged"] = mega["export_ms_unchanged"]
        extra["mega_export_speedup"] = mega["export_speedup"]
        extra["mega_columnar_identical"] = mega["columnar_identical"]
        extra["mega_delta_encode_ms"] = mega["delta_encode_ms"]
        extra["mega_micro_solve_ms"] = mega["micro_solve_ms"]
        extra["mega_arrivals_per_sec"] = mega["arrivals_per_sec"]
        extra["mega_arrivals_per_sec_host"] = mega[
            "arrivals_per_sec_host"]
        extra["mega_arrivals_speedup"] = mega["arrivals_speedup"]
    if relax_res is not None:
        # relaxed fast-path arm: solve-wall speedup over the exact lean
        # kernel, audited divergence rate through the 4-arm router, and
        # the exact-feasibility bit (plan guard + oracle re-check)
        extra["relax_speedup"] = relax_res["relax_speedup"]
        extra["relax_disagreement_rate"] = relax_res[
            "relax_disagreement_rate"]
        extra["plans_feasible"] = relax_res["plans_feasible"]
        extra["relax_solve_wall"] = relax_res["relax_solve_wall"]
        extra["relax_exact_solve_wall"] = relax_res["exact_solve_wall"]
        extra["relax_support_fraction"] = relax_res[
            "relax_support_fraction"]
    # degradation events across every solver-routed scenario, so the
    # perf trajectory records backend faults alongside throughput
    solver_runs = [sim, sim_solver_cpu, sim_solver_dev, sim_large, chaos]
    extra["solver_fallback_count"] = sum(
        r.get("solver_fallback_count", 0) for r in solver_runs if r)
    extra["breaker_trips"] = sum(
        r.get("breaker_trips", 0) for r in solver_runs if r)
    # honest per-scenario backend labels (a scenario that fell back to
    # the CPU must not masquerade as a TPU number)
    for name, plat in scenario_platform.items():
        if plat != "tpu":
            extra[f"{name}_platform"] = plat
    print(json.dumps({
        "metric": metric_name,
        "value": round(value, 1),
        "unit": "admissions/s",
        "vs_baseline": round(value / BASELINE_ADMISSIONS_PER_SEC, 1),
        # the contended 50k x 1k preemption drain through the full
        # kernel (one-shot, no churn: admitted bounded by capacity)
        "preempt_drain_scale": scale_label,
        "preempt_drain_admissions_per_s": round(drain_value, 1),
        "preempt_drain_decisions_per_s": round(drain_decisions, 1),
        "preempt_drain_admitted": preempt["admitted"],
        "preempt_drain_workloads": preempt["workloads"],
        "preempt_drain_rounds": preempt["rounds"],
        "preempt_drain_seconds": round(preempt["seconds"], 6),
        "cycle_ms_p50_50k_1k": round(cycles["cycle_ms_p50"], 2),
        "cycle_ms_p99_50k_1k": round(cycles["cycle_ms_p99"], 2),
        "cycle_platform": cycles_platform,
        "cycle_lanes": int(os.environ.get("BENCH_HMAX",
                                          CYCLE_LANES_DEFAULT)),
        "tunnel_rtt_ms": preempt.get("tunnel_rtt_ms"),
        "plan_agreement_small": round(parity["plan_agreement"], 4),
        "lean_admissions_per_s_50k": round(lean_value, 1),
        **extra,
        "platform": platform,
        "note": ("round 5: timing windows now END at a host-side scalar "
                 "fetch (the tunneled TPU's block_until_ready can return "
                 "before remote execution completes — the earlier "
                 "'1.69ms drain' was shorter than one tunnel RTT and is "
                 "disavowed; tunnel_rtt_ms reports the transport floor). "
                 "Production drains size victim-search lanes from a "
                 "per-round work budget (lanes x options x groups; "
                 "backend-aware): the 50k x 1k drain fell from 49 "
                 "park-throttled rounds to 8 and host-cycle parity "
                 "improved (the host defers no heads). solver=auto "
                 "routes adaptively by measured cost EMAs — drains "
                 "engage where their predicted wall beats the host "
                 "cycles they replace — so the solver-backed reference "
                 "protocols converge toward the host numbers on the "
                 "1-core XLA:CPU fallback instead of losing 2-3x; the "
                 "single-core CPU backend cannot show the kernel's "
                 "data-parallel advantage, which is the TPU thesis"),
    }), flush=True)


if __name__ == "__main__":
    main()
