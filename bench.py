#!/usr/bin/env python
"""Benchmark: TPU solver admission throughput on the large-scale shape.

Mirrors the reference's test/performance/scheduler large-scale config
(10 cohorts x 100 CQs = 1000 ClusterQueues, 50 workloads per CQ = 50k
pending workloads; see BASELINE.md). The full backlog is drained by the
jitted TPU solver in one invocation; the headline metric is admissions
per second against the reference's implied ~43 admissions/s baseline
(15k workloads / 351.1s, test/performance/scheduler/configs/baseline).

Measurement protocol: the solver program is AOT-compiled
(lower().compile()) outside the timing window, then the FIRST execution
is timed. Timing the first execution matters because tunneled TPU
platforms can serve repeat executions on identical inputs from a result
cache; excluding compilation matters because a fresh process would
otherwise spend the whole window tracing + XLA-compiling.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
Diagnostics go to stderr.
"""

import json
import os
import subprocess
import sys
import time

#: reference implied admission throughput (BASELINE.md: 15k wl / 351.1s)
BASELINE_ADMISSIONS_PER_SEC = 42.7


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_scenario(scenario: str) -> dict:
    """Executed inside a fresh subprocess: one timed drain."""
    import jax

    from kueue_oss_tpu.core.queue_manager import QueueManager
    from kueue_oss_tpu.perf.generator import GeneratorConfig, generate
    from kueue_oss_tpu.solver.engine import SolverEngine
    from kueue_oss_tpu.solver.kernels import solve_backlog, to_device

    small = os.environ.get("BENCH_SMALL") == "1"
    config = GeneratorConfig.large_scale(preemption=False)
    if scenario == "full":
        config.nominal_quota = 200  # >= per-CQ demand of 170: all admit
    if small:
        config.n_cohorts, config.cqs_per_cohort = 2, 10

    store, schedule = generate(config)
    for g in schedule:
        store.add_workload(g.workload)
    engine = SolverEngine(store, QueueManager(store))
    problem, _ = engine.export()
    tensors = to_device(problem)
    jax.block_until_ready(tensors)
    compiled = solve_backlog.lower(tensors).compile()

    t0 = time.monotonic()
    out = compiled(tensors)
    jax.block_until_ready(out)
    elapsed = time.monotonic() - t0
    admitted, opt, admit_round, parked, rounds, usage = out
    return {
        "scenario": scenario,
        "workloads": problem.n_workloads,
        "cluster_queues": problem.n_cqs,
        "admitted": int(admitted.sum()),
        "rounds": int(rounds),
        "seconds": elapsed,
    }


def measure(scenario: str) -> dict:
    """Run one scenario in a fresh subprocess (AOT compile inside)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--scenario", scenario]
    t0 = time.monotonic()
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          env=dict(os.environ), timeout=1800)
    if proc.returncode != 0:
        log(proc.stderr[-2000:])
        raise RuntimeError(f"scenario {scenario} failed")
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    log(f"[{scenario}] admitted "
        f"{result['admitted']}/{result['workloads']} in "
        f"{result['seconds']:.2f}s over {result['rounds']} rounds "
        f"(subprocess total {time.monotonic() - t0:.1f}s)")
    return result


def main() -> None:
    if "--scenario" in sys.argv:
        scenario = sys.argv[sys.argv.index("--scenario") + 1]
        print(json.dumps(run_scenario(scenario)), flush=True)
        return

    t_start = time.monotonic()
    full = measure("full")
    contended = measure("contended")
    log(f"[contended] {contended['seconds'] * 1000 / max(contended['rounds'], 1):.1f} "
        f"ms per reference-equivalent cycle @ {contended['cluster_queues']} CQs")
    log(f"total bench time {time.monotonic() - t_start:.1f}s")

    value = full["admitted"] / full["seconds"]
    print(json.dumps({
        "metric": "admission_throughput_50k_backlog_1k_cqs",
        "value": round(value, 1),
        "unit": "admissions/s",
        "vs_baseline": round(value / BASELINE_ADMISSIONS_PER_SEC, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
