from kueue_oss_tpu.deploy import main

raise SystemExit(main())
