"""Deploy-manifest tooling: kustomize loader + chart renderer.

Reference parity: config/components/* (kustomize bases the reference
ships) and charts/kueue (its helm chart). The analogs live in
deploy/manifests (base + overlays) and deploy/chart (values.yaml +
templates). Since the toolchain here has no helm binary, the chart is
rendered by this module: `${a.b.c}` tokens substitute from deep-merged
values (scalars inline; mappings/lists splice as YAML), and a template
whose first line carries `enabled: ${flag}` is skipped when the flag
resolves false.

CLI:
    python -m kueue_oss_tpu.deploy render [--values my.yaml] [--set a.b=c]
    python -m kueue_oss_tpu.deploy build deploy/manifests/overlays/dev
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Any, Optional

import yaml

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
CHART_DIR = REPO_ROOT / "deploy" / "chart"
MANIFESTS_DIR = REPO_ROOT / "deploy" / "manifests"

_TOKEN = re.compile(r"\$\{([A-Za-z0-9_.]+)\}")


class DeployError(ValueError):
    pass


def _deep_merge(base: dict, override: dict) -> dict:
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _lookup(values: dict, dotted: str):
    cur: Any = values
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            raise DeployError(f"chart value {dotted!r} is not defined")
        cur = cur[part]
    return cur


def _substitute(text: str, values: dict) -> str:
    """Replace ${a.b.c}. A token that resolves to a mapping or list is
    spliced as flow-style YAML (valid inline in a block document)."""

    def repl(m: re.Match) -> str:
        v = _lookup(values, m.group(1))
        if isinstance(v, (dict, list)):
            return json.dumps(v)  # JSON is a YAML subset
        if isinstance(v, bool):
            return "true" if v else "false"
        return str(v)

    return _TOKEN.sub(repl, text)


def render_chart(chart_dir: Path = CHART_DIR,
                 values_override: Optional[dict] = None) -> dict[str, list]:
    """Render every template with values.yaml deep-merged under the
    override; returns {template_name: [parsed docs]}."""
    values = yaml.safe_load((chart_dir / "values.yaml").read_text()) or {}
    values = _deep_merge(values, values_override or {})
    out: dict[str, list] = {}
    for tpl in sorted((chart_dir / "templates").glob("*.yaml")):
        text = tpl.read_text()
        first = text.splitlines()[0] if text else ""
        m = re.match(r"#\s*enabled:\s*\$\{([A-Za-z0-9_.]+)\}", first)
        if m and not _lookup(values, m.group(1)):
            continue
        rendered = _substitute(text, values)
        docs = [d for d in yaml.safe_load_all(rendered) if d is not None]
        out[tpl.name] = docs
    return out


def _apply_json_patch(doc: dict, ops: list) -> None:
    """The subset of RFC-6902 kustomize patches the overlays use
    (replace/add/remove on dict paths and list indices, `-` append)."""
    for op in ops:
        path = [p for p in op["path"].split("/") if p]
        parent: Any = doc
        for part in path[:-1]:
            parent = (parent[int(part)] if isinstance(parent, list)
                      else parent[part])
        leaf = path[-1]
        kind = op["op"]
        if isinstance(parent, list):
            if kind == "add" and leaf == "-":
                parent.append(op["value"])
            elif kind == "add":
                parent.insert(int(leaf), op["value"])
            elif kind == "replace":
                parent[int(leaf)] = op["value"]
            elif kind == "remove":
                del parent[int(leaf)]
            else:
                raise DeployError(f"unsupported patch op {kind!r}")
        else:
            if kind in ("add", "replace"):
                parent[leaf] = op["value"]
            elif kind == "remove":
                parent.pop(leaf, None)
            else:
                raise DeployError(f"unsupported patch op {kind!r}")


def build_kustomize(directory: Path) -> list[dict]:
    """Resolve a kustomization: recurse into resource dirs, load
    resource files, apply the overlay's JSON patches by target."""
    directory = Path(directory)
    kustomization = yaml.safe_load(
        (directory / "kustomization.yaml").read_text())
    docs: list[dict] = []
    for res in kustomization.get("resources", []):
        path = directory / res
        if path.is_dir():
            docs.extend(build_kustomize(path))
        else:
            docs.extend(d for d in yaml.safe_load_all(path.read_text())
                        if d is not None)
    for patch in kustomization.get("patches", []):
        target = patch.get("target", {})
        ops = yaml.safe_load(patch["patch"])
        matched = False
        for doc in docs:
            if target.get("kind") and doc.get("kind") != target["kind"]:
                continue
            name = doc.get("metadata", {}).get("name")
            if target.get("name") and name != target["name"]:
                continue
            _apply_json_patch(doc, ops)
            matched = True
        if not matched:
            raise DeployError(f"patch target matched nothing: {target}")
    return docs


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(prog="kueue_oss_tpu.deploy")
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("render", help="render the chart to stdout")
    pr.add_argument("--values", help="values override YAML file")
    pr.add_argument("--set", action="append", default=[],
                    metavar="a.b=v", help="inline value override")
    pb = sub.add_parser("build", help="resolve a kustomization dir")
    pb.add_argument("directory")
    args = p.parse_args(argv)
    if args.cmd == "render":
        override: dict = {}
        if args.values:
            override = yaml.safe_load(Path(args.values).read_text()) or {}
        for item in getattr(args, "set"):
            dotted, _, raw = item.partition("=")
            cur = override
            parts = dotted.split(".")
            for part in parts[:-1]:
                cur = cur.setdefault(part, {})
            cur[parts[-1]] = yaml.safe_load(raw)
        rendered = render_chart(values_override=override)
        docs = [d for lst in rendered.values() for d in lst]
    else:
        docs = build_kustomize(Path(args.directory))
    yaml.safe_dump_all(docs, sys.stdout, sort_keys=False)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
