"""kueue-populator (experimental).

Reference parity: cmd/experimental/kueue-populator — automatically
creates a LocalQueue in every namespace whose labels match a
ClusterQueue's namespace selector, so teams don't hand-provision LQs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kueue_oss_tpu.api.types import LocalQueue
from kueue_oss_tpu.core.store import Store


@dataclass
class PopulatorResult:
    created: list[str] = field(default_factory=list)  # "namespace/name"
    skipped: list[str] = field(default_factory=list)


class Populator:
    def __init__(self, store: Store,
                 local_queue_name: str = "default") -> None:
        self.store = store
        self.local_queue_name = local_queue_name

    def _matches(self, selector, labels: dict[str, str]) -> bool:
        if selector is None:
            return False  # populator requires an explicit selector
        return all(labels.get(k) == v for k, v in selector.items())

    def reconcile(self) -> PopulatorResult:
        """Create missing LocalQueues for matching namespaces."""
        res = PopulatorResult()
        for ns, labels in self.store.namespaces.items():
            for cq in self.store.cluster_queues.values():
                if not self._matches(cq.namespace_selector, labels):
                    continue
                key = f"{ns}/{self.local_queue_name}"
                if key in self.store.local_queues:
                    res.skipped.append(key)
                    continue
                self.store.upsert_local_queue(LocalQueue(
                    name=self.local_queue_name, namespace=ns,
                    cluster_queue=cq.name))
                res.created.append(key)
        return res
