"""Core API object model.

These dataclasses are the in-memory analog of the reference's CRDs
(reference: apis/kueue/v1beta2/clusterqueue_types.go, workload_types.go,
cohort_types.go, resourceflavor_types.go, topology_types.go,
admissioncheck_types.go). Field names follow the reference API surface so a
Kueue user can map concepts 1:1; quantities are plain integers in canonical
milli-units (cpu -> millicores, memory -> bytes, devices -> count*1000 is NOT
used — devices are whole counts) to keep the tensor path integer-exact.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

# ---------------------------------------------------------------------------
# Shared scalar types
# ---------------------------------------------------------------------------

#: (flavor_name, resource_name) — the key of every quota/usage map.
#: Reference parity: pkg/resources/resource.go FlavorResource.
FlavorResource = tuple[str, str]


class QueueingStrategy:
    """Reference parity: apis/kueue/v1beta2/clusterqueue_types.go:180."""

    STRICT_FIFO = "StrictFIFO"
    BEST_EFFORT_FIFO = "BestEffortFIFO"


class StopPolicy:
    """Reference parity: clusterqueue_types.go StopPolicy."""

    NONE = "None"
    HOLD = "Hold"
    HOLD_AND_DRAIN = "HoldAndDrain"


class PreemptionPolicyValue:
    NEVER = "Never"
    LOWER_PRIORITY = "LowerPriority"
    LOWER_OR_NEWER_EQUAL_PRIORITY = "LowerOrNewerEqualPriority"
    ANY = "Any"


@dataclass
class BorrowWithinCohort:
    """Reference parity: clusterqueue_types.go BorrowWithinCohort (KEP-1337)."""

    policy: str = PreemptionPolicyValue.NEVER  # Never | LowerPriority
    max_priority_threshold: Optional[int] = None


@dataclass
class PreemptionPolicy:
    """Reference parity: clusterqueue_types.go ClusterQueuePreemption (KEP-83)."""

    within_cluster_queue: str = PreemptionPolicyValue.NEVER
    reclaim_within_cohort: str = PreemptionPolicyValue.NEVER
    borrow_within_cohort: BorrowWithinCohort = field(default_factory=BorrowWithinCohort)

    @property
    def any_enabled(self) -> bool:
        return (
            self.within_cluster_queue != PreemptionPolicyValue.NEVER
            or self.reclaim_within_cohort != PreemptionPolicyValue.NEVER
        )


class FlavorFungibilityPolicy:
    BORROW = "Borrow"
    PREEMPT = "Preempt"
    TRY_NEXT_FLAVOR = "TryNextFlavor"


class FlavorFungibilityPreference:
    BORROWING_OVER_PREEMPTION = "BorrowingOverPreemption"
    PREEMPTION_OVER_BORROWING = "PreemptionOverBorrowing"


@dataclass
class FlavorFungibility:
    """Reference parity: clusterqueue_types.go:432-449 FlavorFungibility."""

    when_can_borrow: str = FlavorFungibilityPolicy.BORROW
    when_can_preempt: str = FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
    preference: Optional[str] = None  # FlavorFungibilityPreference


@dataclass
class FairSharing:
    """Reference parity: fairsharing types; weight scales DRS down."""

    weight: float = 1.0


@dataclass
class AdmissionScope:
    """Reference parity: AdmissionScope for admission fair sharing (KEP-4136)."""

    admission_mode: str = "UsageBasedAdmissionFairSharing"


@dataclass
class AdmissionCheckStrategyRule:
    """Run check `name` only when the workload's flavor assignment uses
    one of `on_flavors` (empty = every flavor). Reference parity:
    clusterqueue_types.go AdmissionCheckStrategyRule."""

    name: str
    on_flavors: list[str] = field(default_factory=list)


@dataclass
class AdmissionChecksStrategy:
    """Reference parity: clusterqueue_types.go AdmissionChecksStrategy."""

    admission_checks: list[AdmissionCheckStrategyRule] = field(
        default_factory=list)


# ---------------------------------------------------------------------------
# ResourceFlavor / Topology
# ---------------------------------------------------------------------------


def format_taint(t) -> str:
    """Canonical `key=value:Effect` rendering shared by kueuectl and
    the dashboard."""
    return f"{t.key}={t.value}:{t.effect}"


@dataclass
class ResourceFlavor:
    """Reference parity: resourceflavor_types.go."""

    name: str
    node_labels: dict[str, str] = field(default_factory=dict)
    node_taints: list[Taint] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    #: Name of a Topology object enabling TAS for this flavor (KEP-2724).
    topology_name: Optional[str] = None


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class Topology:
    """Reference parity: topology_types.go — ordered levels, broadest first
    (e.g. ["cloud.google.com/topology-block", "...-rack", "kubernetes.io/hostname"]).
    """

    name: str
    levels: list[str] = field(default_factory=list)


#: Label key marking the host level of a topology (kubernetes.io/hostname).
HOSTNAME_LABEL = "kubernetes.io/hostname"


@dataclass
class Node:
    """Cluster node feeding TAS capacity (reference parity: corev1.Node as
    consumed by pkg/cache/scheduler/tas_nodes_cache.go)."""

    name: str
    labels: dict[str, str] = field(default_factory=dict)
    #: allocatable capacity in canonical units; "pods" defaults to 110
    allocatable: dict[str, int] = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    ready: bool = True

    def __post_init__(self) -> None:
        self.labels.setdefault(HOSTNAME_LABEL, self.name)
        self.allocatable.setdefault("pods", 110)


# ---------------------------------------------------------------------------
# Quota model
# ---------------------------------------------------------------------------


@dataclass
class ResourceQuota:
    """Per (flavor, resource) quota on a CQ or Cohort.

    Reference parity: clusterqueue_types.go ResourceQuota
    {nominalQuota, borrowingLimit, lendingLimit}.
    """

    name: str  # resource name, e.g. "cpu"
    nominal: int = 0
    borrowing_limit: Optional[int] = None
    lending_limit: Optional[int] = None


@dataclass
class FlavorQuotas:
    name: str  # ResourceFlavor name
    resources: list[ResourceQuota] = field(default_factory=list)


@dataclass
class ResourceGroup:
    """A set of resources admitted together through an ordered flavor list.

    Reference parity: clusterqueue_types.go ResourceGroup — coveredResources
    must match the union of resources across flavors; flavor order is the
    assignment preference order.
    """

    covered_resources: list[str] = field(default_factory=list)
    flavors: list[FlavorQuotas] = field(default_factory=list)


def iter_quotas(resource_groups: list[ResourceGroup]):
    """Yield ((flavor, resource), ResourceQuota) across resource groups."""
    for rg in resource_groups:
        for fq in rg.flavors:
            for rq in fq.resources:
                yield (fq.name, rq.name), rq


def _quota_for(resource_groups: list[ResourceGroup],
               fr: FlavorResource) -> Optional[ResourceQuota]:
    for key, rq in iter_quotas(resource_groups):
        if key == fr:
            return rq
    return None


# ---------------------------------------------------------------------------
# ClusterQueue / Cohort / LocalQueue
# ---------------------------------------------------------------------------


@dataclass
class ClusterQueue:
    name: str
    cohort: Optional[str] = None
    #: object labels (CustomMetricLabels reads configured keys)
    labels: dict[str, str] = field(default_factory=dict)
    resource_groups: list[ResourceGroup] = field(default_factory=list)
    queueing_strategy: str = QueueingStrategy.BEST_EFFORT_FIFO
    preemption: PreemptionPolicy = field(default_factory=PreemptionPolicy)
    flavor_fungibility: FlavorFungibility = field(default_factory=FlavorFungibility)
    fair_sharing: FairSharing = field(default_factory=FairSharing)
    admission_scope: Optional[AdmissionScope] = None
    namespace_selector: Optional[dict[str, str]] = None  # None selects everything
    admission_checks: list[str] = field(default_factory=list)
    admission_checks_strategy: Optional[AdmissionChecksStrategy] = None
    stop_policy: str = StopPolicy.NONE

    def flavor_resources(self) -> list[FlavorResource]:
        """All (flavor, resource) pairs this CQ defines quota for."""
        return [key for key, _ in iter_quotas(self.resource_groups)]

    def checks_for_flavors(self, flavors) -> list[str]:
        """Effective admission checks for an assignment using `flavors`:
        plain admissionChecks always apply; strategy rules apply when
        onFlavors is empty or intersects the assignment. `flavors=None`
        (no admission yet) applies EVERY strategy rule (reference:
        workload.AdmissionChecksForWorkload treats a nil admission as
        all-checks, admissionchecks.go)."""
        names = list(self.admission_checks)
        if self.admission_checks_strategy is not None:
            fset = None if flavors is None else set(flavors)
            for rule in self.admission_checks_strategy.admission_checks:
                if rule.name in names:
                    continue
                if (fset is None or not rule.on_flavors
                        or fset & set(rule.on_flavors)):
                    names.append(rule.name)
        return names

    def quota_for(self, fr: FlavorResource) -> Optional[ResourceQuota]:
        return _quota_for(self.resource_groups, fr)


@dataclass
class Cohort:
    """Reference parity: cohort_types.go (KEP-79 hierarchical cohorts).

    Cohorts form a forest; they may carry their own quotas and fair-sharing
    weight. A ClusterQueue names its (leaf-adjacent) cohort by string.
    """

    name: str
    parent: Optional[str] = None
    resource_groups: list[ResourceGroup] = field(default_factory=list)
    fair_sharing: FairSharing = field(default_factory=FairSharing)

    def quota_for(self, fr: FlavorResource) -> Optional[ResourceQuota]:
        return _quota_for(self.resource_groups, fr)


@dataclass
class LocalQueue:
    name: str
    namespace: str = "default"
    cluster_queue: str = ""
    stop_policy: str = StopPolicy.NONE
    fair_sharing: FairSharing = field(default_factory=FairSharing)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class WorkloadPriorityClass:
    """Reference parity: workloadpriorityclass_types.go."""

    name: str
    value: int = 0


# ---------------------------------------------------------------------------
# Workload
# ---------------------------------------------------------------------------


@dataclass
class PodSetTopologyRequest:
    """Reference parity: workload_types.go PodSetTopologyRequest (KEP-2724)."""

    required: Optional[str] = None  # topology level that must contain the podset
    preferred: Optional[str] = None  # level to try, falling back upward
    unconstrained: bool = False
    podset_group_name: Optional[str] = None
    podset_slice_required_topology: Optional[str] = None
    podset_slice_size: Optional[int] = None
    #: additional nested slice layers below the outermost slice
    #: (KEP multi-layer topology; workload_types.go
    #: PodsetSliceRequiredTopologyConstraints): (topology level, size)
    #: pairs, each layer strictly below and evenly dividing its parent
    podset_slice_constraints: list["PodSetSliceConstraint"] = field(
        default_factory=list)


@dataclass
class PodSetSliceConstraint:
    topology: str = ""
    size: int = 1


@dataclass
class PodSet:
    name: str = "main"
    count: int = 1
    #: per-pod requests in canonical units, e.g. {"cpu": 1000, "memory": 2<<30}
    requests: dict[str, int] = field(default_factory=dict)
    #: minimum acceptable count for partial admission (KEP-420); None disables.
    min_count: Optional[int] = None
    topology_request: Optional[PodSetTopologyRequest] = None
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    #: pod template environment, ordered (name, value) pairs; duplicates
    #: are legal in a spec and deduplicated at Workload creation under
    #: the SanitizePodSets gate (kube_features.go:207-212)
    env: list[tuple[str, str]] = field(default_factory=list)

    def total_requests(self) -> dict[str, int]:
        return {r: q * self.count for r, q in self.requests.items()}


# Condition types on Workload status.
# Reference parity: workload_types.go condition constants.
class WorkloadConditionType:
    QUOTA_RESERVED = "QuotaReserved"
    ADMITTED = "Admitted"
    EVICTED = "Evicted"
    PREEMPTED = "Preempted"
    FINISHED = "Finished"
    REQUEUED = "Requeued"
    PODS_READY = "PodsReady"


@dataclass
class Condition:
    type: str
    status: bool
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


@dataclass
class PodSetAssignment:
    """Reference parity: workload_types.go PodSetAssignment."""

    name: str
    #: resource -> flavor name chosen for it
    flavors: dict[str, str] = field(default_factory=dict)
    #: total usage counted against the quota (resource -> quantity)
    resource_usage: dict[str, int] = field(default_factory=dict)
    count: int = 0
    topology_assignment: Optional[TopologyAssignment] = None
    delayed_topology_request: Optional[str] = None  # "Pending" | "Ready"


@dataclass
class TopologyDomainAssignment:
    values: list[str] = field(default_factory=list)  # node label values per level
    count: int = 0


@dataclass
class TopologyAssignment:
    levels: list[str] = field(default_factory=list)
    domains: list[TopologyDomainAssignment] = field(default_factory=list)


@dataclass
class Admission:
    cluster_queue: str
    podset_assignments: list[PodSetAssignment] = field(default_factory=list)

    def assigned_flavors(self) -> set:
        """Distinct ResourceFlavor names across all podset assignments
        (workload.go flavor extraction; feeds checks_for_flavors)."""
        return {f for psa in self.podset_assignments
                for f in psa.flavors.values()}


class CheckState:
    """Reference parity: workload_types.go CheckState (KEP-993)."""

    PENDING = "Pending"
    READY = "Ready"
    RETRY = "Retry"
    REJECTED = "Rejected"


@dataclass
class PodSetUpdate:
    """Reference parity: workload_types.go PodSetUpdate — per-podset
    scheduling context an admission-check controller injects at Ready
    (node selectors/labels pointing pods at provisioned capacity)."""

    name: str
    node_selector: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    tolerations: list["Toleration"] = field(default_factory=list)


@dataclass
class AdmissionCheckState:
    name: str
    state: str = CheckState.PENDING
    message: str = ""
    #: injected into the job's podsets when the workload starts
    #: (workload_types.go AdmissionCheckState.PodSetUpdates)
    pod_set_updates: list[PodSetUpdate] = field(default_factory=list)
    #: provisioning retry bookkeeping (KEP-3258 RetryCount)
    retry_count: int = 0


@dataclass
class RequeueState:
    """Reference parity: workload_types.go RequeueState — eviction backoff."""

    count: int = 0
    requeue_at: Optional[float] = None


@dataclass
class WorkloadSchedulingStatsEviction:
    reason: str
    underlying_cause: str = ""
    count: int = 0


@dataclass
class WorkloadStatus:
    conditions: dict[str, Condition] = field(default_factory=dict)
    admission: Optional[Admission] = None
    admission_checks: dict[str, AdmissionCheckState] = field(default_factory=dict)
    requeue_state: Optional[RequeueState] = None
    eviction_stats: list[WorkloadSchedulingStatsEviction] = field(default_factory=list)
    #: names of nodes in this workload's topology assignment that became
    #: unhealthy (reference: workload_types.go UnhealthyNodes, KEP TAS
    #: failed-node replacement)
    unhealthy_nodes: list[str] = field(default_factory=list)
    #: MultiKueue dispatch (KEP-693): worker clusters nominated for this
    #: workload, and the one that won the admission race
    #: (workload_types.go:686-706 NominatedClusterNames / ClusterName)
    nominated_cluster_names: list[str] = field(default_factory=list)
    cluster_name: Optional[str] = None
    #: podset name -> pods whose resources are no longer needed (finished
    #: pods of a running workload release their quota share; reference:
    #: workload_types.go ReclaimablePods, JobWithReclaimablePods)
    reclaimable_pods: dict[str, int] = field(default_factory=dict)


_uid_counter = itertools.count(1)


@dataclass
class Workload:
    name: str
    namespace: str = "default"
    queue_name: str = ""  # LocalQueue name
    priority: int = 0
    priority_class: Optional[str] = None
    labels: dict[str, str] = field(default_factory=dict)
    #: object annotations (e.g. kueue.x-k8s.io/priority-boost)
    annotations: dict[str, str] = field(default_factory=dict)
    podsets: list[PodSet] = field(default_factory=list)
    #: spec.active=false deactivates the workload (reference: workload_types.go Active)
    active: bool = True
    creation_time: float = 0.0
    uid: int = 0
    #: maximum execution time in seconds; None = unlimited
    max_execution_time: Optional[float] = None
    #: owning job identity "Kind/namespace/name" (jobframework ownership)
    owner: Optional[str] = None
    #: key of the workload slice this one replaces on scale-up
    #: (reference: kueue.x-k8s.io/workload-slice-replacement-for annotation)
    replacement_for: Optional[str] = None
    #: concurrent admission (KEP-8691): parent marker, the variant's parent
    #: key, and the single ResourceFlavor this variant may assign
    #: (reference: ConcurrentAdmissionParentLabelKey, owner ref,
    #: WorkloadAllowedResourceFlavorAnnotation)
    ca_parent: bool = False
    parent_workload: Optional[str] = None
    allowed_flavor: Optional[str] = None
    #: open preemption gates (KEP-8303 MultiKueue orchestrated preemption):
    #: while non-empty, the scheduler must not issue preemptions for this
    #: workload (workload_types.go:604,899-917; scheduler.go:411-416)
    preemption_gates: list[str] = field(default_factory=list)
    #: optimistic-concurrency token, bumped by every store write; the
    #: merge-patch client path (WorkloadRequestUseMergePatch)
    #: preconditions on it
    resource_version: int = 0
    status: WorkloadStatus = field(default_factory=WorkloadStatus)

    def __post_init__(self) -> None:
        if self.uid == 0:
            self.uid = next(_uid_counter)
        if not self.podsets:
            self.podsets = [PodSet()]

    # -- status helpers (reference parity: pkg/workload/workload.go) --------

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def condition(self, ctype: str) -> Optional[Condition]:
        return self.status.conditions.get(ctype)

    def has_condition(self, ctype: str) -> bool:
        c = self.status.conditions.get(ctype)
        return c is not None and c.status

    def set_condition(self, ctype: str, status: bool, reason: str = "",
                      message: str = "", now: float = 0.0) -> None:
        # last_transition_time only moves when status actually flips
        # (reference parity: apimeta.SetStatusCondition semantics).
        prev = self.status.conditions.get(ctype)
        if prev is not None and prev.status == status:
            now = prev.last_transition_time
        self.status.conditions[ctype] = Condition(
            type=ctype, status=status, reason=reason, message=message,
            last_transition_time=now)

    @property
    def is_quota_reserved(self) -> bool:
        return self.has_condition(WorkloadConditionType.QUOTA_RESERVED)

    @property
    def is_admitted(self) -> bool:
        return self.has_condition(WorkloadConditionType.ADMITTED)

    @property
    def is_finished(self) -> bool:
        return self.has_condition(WorkloadConditionType.FINISHED)

    @property
    def is_evicted(self) -> bool:
        return self.has_condition(WorkloadConditionType.EVICTED)

    def total_requests(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for ps in self.podsets:
            for r, q in ps.total_requests().items():
                out[r] = out.get(r, 0) + q
        return out


# ---------------------------------------------------------------------------
# AdmissionCheck
# ---------------------------------------------------------------------------


@dataclass
class AdmissionCheckStatus:
    active: bool = True


@dataclass
class AdmissionCheck:
    """Reference parity: admissioncheck_types.go (KEP-993)."""

    name: str
    controller_name: str = ""
    parameters: dict[str, str] = field(default_factory=dict)
    status: AdmissionCheckStatus = field(
        default_factory=AdmissionCheckStatus)
