"""Typed client layer over the Store.

Reference parity: client-go/ (~27k generated LoC) — typed clientsets,
listers, and watch interfaces external consumers (kueuectl, kueueviz,
user tooling) use instead of reaching into internals. Here one
hand-written module provides the same surface: per-kind resource
interfaces with get/list/create/update/delete/watch, namespace scoping
for namespaced kinds, and label selection.

Usage:
    cs = Clientset(store)
    cs.cluster_queues().list()
    cs.workloads("team-ns").get("train")
    cs.workloads().watch(lambda ev: ...)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from kueue_oss_tpu.api.types import (
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    LocalQueue,
    Node,
    ResourceFlavor,
    Topology,
    Workload,
    WorkloadPriorityClass,
)
from kueue_oss_tpu.core.store import Store


class NotFound(KeyError):
    pass


class Conflict(ValueError):
    pass


@dataclass
class WatchEvent:
    type: str        # Added | Modified | Deleted
    kind: str
    object: object


_VERB_TO_WATCH = {"add": "Added", "update": "Modified",
                  "delete": "Deleted"}


class _ResourceClient:
    """One kind's typed interface (clientset.Interface analog)."""

    kind: str = ""
    namespaced: bool = False

    def __init__(self, store: Store, namespace: Optional[str]) -> None:
        self._store = store
        self._namespace = namespace

    # -- to be provided per kind -----------------------------------------
    def _objects(self) -> dict:
        raise NotImplementedError

    def _upsert(self, obj) -> None:
        raise NotImplementedError

    def _delete(self, key: str):
        raise NotImplementedError

    def _key(self, name: str) -> str:
        if self.namespaced:
            return f"{self._namespace or 'default'}/{name}"
        return name

    def _visible(self, obj) -> bool:
        if not self.namespaced or self._namespace is None:
            return True
        return getattr(obj, "namespace", "default") == self._namespace

    # -- verbs ------------------------------------------------------------

    def get(self, name: str):
        obj = self._objects().get(self._key(name))
        if obj is None or not self._visible(obj):
            raise NotFound(f"{self.kind} {self._key(name)!r} not found")
        return obj

    def list(self, label_selector: Optional[dict] = None) -> list:
        out = []
        for obj in self._objects().values():
            if not self._visible(obj):
                continue
            if label_selector:
                labels = getattr(obj, "labels", {}) or {}
                if any(labels.get(k) != v
                       for k, v in label_selector.items()):
                    continue
            out.append(obj)
        return sorted(out, key=lambda o: getattr(o, "key",
                                                 getattr(o, "name", "")))

    def create(self, obj):
        key = getattr(obj, "key", getattr(obj, "name", None))
        if key in self._objects():
            raise Conflict(f"{self.kind} {key!r} already exists")
        self._upsert(obj)
        return obj

    def update(self, obj):
        key = getattr(obj, "key", getattr(obj, "name", None))
        if key not in self._objects():
            raise NotFound(f"{self.kind} {key!r} not found")
        self._upsert(obj)
        return obj

    def delete(self, name: str):
        obj = self.get(name)
        self._delete(self._key(name))
        return obj

    def watch(self, fn: Callable[[WatchEvent], None]) -> None:
        """Stream events for this kind (informer analog). The callback
        receives Added/Modified/Deleted WatchEvents."""
        kind = self.kind

        def relay(event):
            verb, k, obj = event
            if k != kind:
                return
            if not self._visible(obj):
                return
            fn(WatchEvent(_VERB_TO_WATCH.get(verb, verb), k, obj))

        self._store.watch(relay)


def _make_client(kind_, namespaced_, objects, upsert, delete=None):
    class C(_ResourceClient):
        kind = kind_
        namespaced = namespaced_

        def _objects(self):
            return objects(self._store)

        def _upsert(self, obj):
            upsert(self._store, obj)

        def _delete(self, key):
            if delete is None:
                raise NotImplementedError(
                    f"delete not supported for {self.kind}")
            return delete(self._store, key)

    C.__name__ = f"{kind_}Client"
    return C


ClusterQueueClient = _make_client(
    "ClusterQueue", False,
    lambda s: s.cluster_queues,
    lambda s, o: s.upsert_cluster_queue(o),
    lambda s, k: s.delete_cluster_queue(k))
LocalQueueClient = _make_client(
    "LocalQueue", True,
    lambda s: s.local_queues,
    lambda s, o: s.upsert_local_queue(o),
    lambda s, k: s.delete_local_queue(k))
CohortClient = _make_client(
    "Cohort", False,
    lambda s: s.cohorts,
    lambda s, o: s.upsert_cohort(o))
ResourceFlavorClient = _make_client(
    "ResourceFlavor", False,
    lambda s: s.resource_flavors,
    lambda s, o: s.upsert_resource_flavor(o))
TopologyClient = _make_client(
    "Topology", False,
    lambda s: s.topologies,
    lambda s, o: s.upsert_topology(o))
AdmissionCheckClient = _make_client(
    "AdmissionCheck", False,
    lambda s: s.admission_checks,
    lambda s, o: s.upsert_admission_check(o))
PriorityClassClient = _make_client(
    "WorkloadPriorityClass", False,
    lambda s: s.priority_classes,
    lambda s, o: s.upsert_priority_class(o))
NodeClient = _make_client(
    "Node", False,
    lambda s: s.nodes,
    lambda s, o: s.upsert_node(o),
    lambda s, k: s.delete_node(k))


class WorkloadClient(_ResourceClient):
    kind = "Workload"
    namespaced = True

    def _objects(self):
        return self._store.workloads

    def _upsert(self, obj):
        if obj.key in self._store.workloads:
            self._store.update_workload(obj)
        else:
            self._store.add_workload(obj)

    def _delete(self, key):
        return self._store.delete_workload(key)

    def patch_status(self, name: str, fn: Callable[[Workload], None],
                     cached: Optional[Workload] = None,
                     retry_on_conflict: bool = True):
        """Status-subresource update honoring WorkloadRequestUseMergePatch
        (reference: pkg/workload/workload.go patchStatus:1219-1249).

        - Gate ENABLED (merge patch): re-read the live object, apply
          `fn` to it, and write back — only the fields `fn` touches
          change, so concurrent controllers writing other status fields
          are preserved. A conflicting write between read and write
          (resource_version moved) retries when `retry_on_conflict`.
        - Gate DISABLED (legacy SSA-style replace): `fn` runs on the
          caller's `cached` copy (default: the live object) and the
          WHOLE status is written back — a stale cache clobbers
          concurrent writers, which is exactly the behavior the gate
          exists to fix.

        ALIASING HAZARD (gate enabled): the merge-patch path swaps a
        deepcopy into the store, so any pre-existing in-memory
        reference to the old object — a queued WorkloadInfo wrapper, a
        snapshot entry, a captured `cached` — keeps pointing at the
        STALE object until the store's update event re-syncs it. The
        legacy path mutated in place, so old references saw the write
        immediately. Callers holding long-lived references must
        re-fetch after a patch (or subscribe to store events) rather
        than reading through a pre-patch pointer; see
        docs/SOLVER_PROTOCOL.md "Known hazards".
        """
        import copy as _copy

        from kueue_oss_tpu import features

        if not features.enabled("WorkloadRequestUseMergePatch"):
            wl = cached if cached is not None else self.get(name)
            fn(wl)
            self._store.update_workload(wl)
            return wl
        for _ in range(10 if retry_on_conflict else 1):
            live = self.get(name)      # NotFound if deleted meanwhile
            observed = live.resource_version
            # fn mutates a fresh copy so a conflicting concurrent write
            # rolls back cleanly (no double-apply on retry, no partial
            # mutation behind a raised Conflict); the precondition and
            # the write are one atomic store operation, and a deleted
            # workload is never resurrected
            wl = _copy.deepcopy(live)
            fn(wl)
            if self._store.update_workload_if(wl, observed):
                return wl
            if not retry_on_conflict:
                raise Conflict(
                    f"Workload {name!r}: resourceVersion moved past "
                    f"{observed}")
        raise Conflict(f"Workload {name!r}: retries exhausted")


class Clientset:
    """Typed access to every kind (client-go clientset.Interface)."""

    def __init__(self, store: Store) -> None:
        self._store = store

    def cluster_queues(self) -> _ResourceClient:
        return ClusterQueueClient(self._store, None)

    def local_queues(self, namespace: Optional[str] = None):
        return LocalQueueClient(self._store, namespace)

    def cohorts(self):
        return CohortClient(self._store, None)

    def resource_flavors(self):
        return ResourceFlavorClient(self._store, None)

    def topologies(self):
        return TopologyClient(self._store, None)

    def admission_checks(self):
        return AdmissionCheckClient(self._store, None)

    def priority_classes(self):
        return PriorityClassClient(self._store, None)

    def nodes(self):
        return NodeClient(self._store, None)

    def workloads(self, namespace: Optional[str] = None) -> WorkloadClient:
        return WorkloadClient(self._store, namespace)
