"""Cache/queue dumper.

Reference parity: pkg/debugger/debugger.go:33-50 — SIGUSR2 dumps the
scheduler cache and pending queues to the log; pkg/cache/queue/dumper.go
formats the queue contents. The dump here is a plain dict/text so tests
and operators can snapshot state without a debugger.
"""

from __future__ import annotations

import signal
import sys
from typing import Optional, TextIO

from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store


class Dumper:
    def __init__(self, store: Store, queues: QueueManager) -> None:
        self.store = store
        self.queues = queues

    def dump(self) -> dict:
        """Structured snapshot of admitted usage + pending queues."""
        admitted = {}
        for wl in self.store.admitted_workloads():
            adm = wl.status.admission
            admitted.setdefault(adm.cluster_queue if adm else "?", []).append({
                "workload": wl.key,
                "priority": wl.priority,
                "usage": {
                    f"{psa.name}/{r}": q
                    for psa in (adm.podset_assignments if adm else [])
                    for r, q in psa.resource_usage.items()},
            })
        pending = {}
        for name, q in self.queues.queues.items():
            pending[name] = {
                "active": [i.key for i in q.snapshot_order()],
                "inadmissible": sorted(q.inadmissible),
            }
        from kueue_oss_tpu import obs

        return {
            "cluster_queues": sorted(self.store.cluster_queues),
            "cohorts": sorted(self.store.cohorts),
            "admitted_workloads": admitted,
            "pending_workloads": pending,
            # newest flight-recorder decisions: the dump should answer
            # "why is this pending?" without a live dashboard
            "recent_decisions": [
                ev.to_dict() for ev in obs.recorder.events()[-100:]],
        }

    def dump_text(self, out: Optional[TextIO] = None) -> str:
        out = out or sys.stderr
        lines = ["=== kueue_oss_tpu dump ==="]
        d = self.dump()
        for cq in d["cluster_queues"]:
            lines.append(f"ClusterQueue {cq}:")
            for item in d["admitted_workloads"].get(cq, []):
                lines.append(f"  admitted {item['workload']} "
                             f"priority={item['priority']} "
                             f"usage={item['usage']}")
            p = d["pending_workloads"].get(cq, {})
            lines.append(f"  pending active={p.get('active', [])} "
                         f"inadmissible={p.get('inadmissible', [])}")
        text = "\n".join(lines)
        print(text, file=out)
        return text

    def listen_for_signal(self) -> None:
        """Install the SIGUSR2 handler (debugger.go ListenForSignal)."""
        signal.signal(signal.SIGUSR2, lambda *_: self.dump_text())
