"""Profiling + tracing endpoints.

Reference parity: the reference exposes Go pprof via the manager's
pprofBindAddress (apis/config PprofBindAddress; pkg/config/config_test.go
:251) and structured per-phase log timing. The Python analogs here:

- `Profiler`: cProfile sessions with pstats summaries — the
  /debug/pprof/profile equivalent for the host scheduling path;
- `Tracer`: lightweight span recording with Chrome-trace JSON export
  (chrome://tracing / Perfetto-loadable, the same workflow used for
  JAX/XLA device traces), wired into the scheduler's cycle phases via
  `attach_to_scheduler`.
"""

from __future__ import annotations

import cProfile
import io
import json
import pstats
import sys
import threading
import time
from contextlib import contextmanager
from typing import Optional


class Profiler:
    """cProfile session manager (pprof 'profile' endpoint analog)."""

    def __init__(self) -> None:
        self._profile: Optional[cProfile.Profile] = None
        self._lock = threading.Lock()

    @property
    def running(self) -> bool:
        return self._profile is not None

    def start(self) -> None:
        with self._lock:
            if self._profile is not None:
                raise RuntimeError("profiler already running")
            self._profile = cProfile.Profile()
            self._profile.enable()

    def stop(self, top: int = 30, sort: str = "cumulative") -> str:
        """Stop and return a pstats text summary of the top functions."""
        with self._lock:
            if self._profile is None:
                raise RuntimeError("profiler not running")
            self._profile.disable()
            buf = io.StringIO()
            stats = pstats.Stats(self._profile, stream=buf)
            stats.sort_stats(sort).print_stats(top)
            self._profile = None
            return buf.getvalue()

    @contextmanager
    def profile(self, top: int = 30):
        """Context manager yielding a result holder; holder['report']
        has the summary after the block exits."""
        holder: dict = {}
        self.start()
        try:
            yield holder
        finally:
            holder["report"] = self.stop(top=top)


class SamplingProfiler:
    """Statistical whole-process profiler (py-spy style).

    cProfile instruments only the calling thread, so it cannot see a
    scheduler serving in its own thread. This sampler walks
    ``sys._current_frames()`` — every thread's live stack — at a fixed
    interval and aggregates leaf/stack counts; it is what the
    /debug/pprof/profile endpoint uses.
    """

    def __init__(self, interval: float = 0.005,
                 max_depth: int = 40) -> None:
        self.interval = interval
        self.max_depth = max_depth
        self._lock = threading.Lock()
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        #: a fixed-window sample_for() is in flight (distinct from the
        #: background-session _thread; both exclude each other)
        self._busy = False
        self._leaf_counts: dict[str, int] = {}
        self._stack_counts: dict[tuple, int] = {}
        self._samples = 0
        self._started_at = 0.0

    @property
    def running(self) -> bool:
        return self._thread is not None

    def _sample_once(self, skip_tids: set) -> None:
        for tid, frame in sys._current_frames().items():
            if tid in skip_tids:
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                code = f.f_code
                stack.append(
                    f"{code.co_name} "
                    f"({code.co_filename.rsplit('/', 1)[-1]}"
                    f":{f.f_lineno})")
                f = f.f_back
            if not stack:
                continue
            self._samples += 1
            self._leaf_counts[stack[0]] = (
                self._leaf_counts.get(stack[0], 0) + 1)
            key = tuple(reversed(stack))
            self._stack_counts[key] = self._stack_counts.get(key, 0) + 1

    def _report(self, seconds: float, top: int) -> str:
        lines = [f"{self._samples} samples over {seconds:.2f}s "
                 f"({self.interval * 1000:.0f}ms interval)", "",
                 "top functions (leaf samples):"]
        for name, n in sorted(self._leaf_counts.items(),
                              key=lambda kv: -kv[1])[:top]:
            lines.append(f"  {n:6d}  {name}")
        lines += ["", "top stacks:"]
        for stack, n in sorted(self._stack_counts.items(),
                               key=lambda kv: -kv[1])[:5]:
            lines.append(f"  {n:6d} samples:")
            for fr in stack[-10:]:
                lines.append(f"          {fr}")
        return "\n".join(lines)

    def _reset(self) -> None:
        self._leaf_counts = {}
        self._stack_counts = {}
        self._samples = 0

    def sample_for(self, seconds: float, top: int = 30) -> str:
        """Blocking window: sample every thread but this one for
        ``seconds``, return the aggregated report. The lock guards only
        the admission check — holding it across the window would make
        concurrent start/stop requests block for ``seconds`` and then
        run anyway, instead of failing fast with the 409 the endpoints
        promise."""
        with self._lock:
            if self._thread is not None or self._busy:
                raise RuntimeError(
                    "a sampling session is active; stop it "
                    "first (/debug/pprof/sample/stop)")
            self._busy = True
            self._reset()
        try:
            me = {threading.get_ident()}
            end = time.monotonic() + seconds
            while time.monotonic() < end:
                self._sample_once(me)
                time.sleep(self.interval)
            return self._report(seconds, top)
        finally:
            self._busy = False

    def start(self) -> None:
        """Begin open-ended background sampling (the
        /debug/pprof/sample/start endpoint): a daemon thread samples
        every OTHER thread until stop(). One session at a time."""
        with self._lock:
            if self._thread is not None or self._busy:
                raise RuntimeError("sampling profiler already running")
            self._reset()
            self._stop = threading.Event()
            self._started_at = time.monotonic()

            def run(stop=self._stop):
                skip = {threading.get_ident()}
                while not stop.is_set():
                    self._sample_once(skip)
                    stop.wait(self.interval)

            self._thread = threading.Thread(
                target=run, daemon=True, name="sampling-profiler")
            self._thread.start()

    def stop(self, top: int = 30) -> str:
        """End the background session and return its report."""
        with self._lock:
            if self._thread is None:
                raise RuntimeError("sampling profiler not running")
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._thread = None
            self._stop = None
            return self._report(time.monotonic() - self._started_at, top)


class Tracer:
    """Span recorder with Chrome-trace export.

    Bounded ring of spans; thread-safe; zero overhead when disabled.
    """

    def __init__(self, max_spans: int = 100_000,
                 clock=time.perf_counter) -> None:
        self.max_spans = max_spans
        self.clock = clock
        self.enabled = True
        self._lock = threading.Lock()
        #: ring of (name, thread id, start_us, duration_us, args) — the
        #: newest max_spans survive (an operator debugging a current
        #: stall needs the RECENT activity, not warm-up)
        self._spans: list[tuple] = []
        self._next = 0
        #: external span sources (sidecar solves, followers, the farm)
        #: get stable SYNTHETIC track ids so their spans never
        #: interleave with host threads on one Chrome-trace track.
        #: Small ids are safe: host tids are pthread pointers.
        self._tracks: dict[str, int] = {}
        self._track_meta: dict[str, dict] = {}
        self._next_track = 2

    @contextmanager
    def span(self, name: str, **args):
        if not self.enabled:
            yield
            return
        t0 = self.clock()
        try:
            yield
        finally:
            dur = self.clock() - t0
            self._push((name, threading.get_ident(),
                        int(t0 * 1e6), int(dur * 1e6), args or None))

    def track(self, source: str, **meta) -> int:
        """Stable synthetic track id for an external span source
        (``"sidecar:tenant-a"``, ``"farm"``, ``"follower:1"``).
        ``meta`` (process/tenant tags) accumulates onto the track and
        exports as Chrome thread_name metadata."""
        with self._lock:
            tid = self._tracks.get(source)
            if tid is None:
                tid = self._tracks[source] = self._next_track
                self._next_track += 1
            if meta:
                self._track_meta.setdefault(source, {}).update(meta)
            return tid

    def add_span(self, name: str, ts_us: int, dur_us: int,
                 tid: Optional[int] = None,
                 source: Optional[str] = None, **args) -> None:
        """Record an externally-timed span (e.g. a sidecar solve whose
        timing arrived over the wire) into the same ring, so host and
        remote activity export as one Chrome-trace timeline. Pass
        ``source`` for external spans — they land on that source's own
        synthetic track instead of the CALLER's thread track (merged
        remote spans used to interleave with host spans)."""
        if not self.enabled:
            return
        if tid is None:
            tid = (self.track(source) if source is not None
                   else threading.get_ident())
        self._push((name, tid, int(ts_us), int(dur_us), args or None))

    def _push(self, entry: tuple) -> None:
        with self._lock:
            if len(self._spans) < self.max_spans:
                self._spans.append(entry)
            else:
                self._spans[self._next % self.max_spans] = entry
            self._next += 1

    def spans(self) -> list[tuple]:
        with self._lock:
            if len(self._spans) < self.max_spans:
                return list(self._spans)
            cut = self._next % self.max_spans
            return self._spans[cut:] + self._spans[:cut]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._next = 0

    def durations_ms(self, name: str) -> list[float]:
        return [dur / 1000 for (n, _, _, dur, _) in self.spans()
                if n == name]

    def chrome_trace(self, spans: Optional[list] = None) -> str:
        """Chrome-trace JSON ('X' complete events) — loadable in
        chrome://tracing or Perfetto alongside a JAX device trace.
        Synthetic source tracks lead with 'M' thread_name metadata so
        the timeline labels them by source + tenant/process tags."""
        with self._lock:
            tracks = sorted(self._tracks.items(), key=lambda kv: kv[1])
            meta = {s: dict(m) for s, m in self._track_meta.items()}
        events = []
        for src, tid in tracks:
            args = {"name": src}
            args.update(meta.get(src, {}))
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tid, "args": args})
        for name, tid, ts, dur, args in (self.spans() if spans is None
                                         else spans):
            ev = {"name": name, "ph": "X", "pid": 1, "tid": tid,
                  "ts": ts, "dur": dur, "cat": "scheduler"}
            if args:
                ev["args"] = args
            events.append(ev)
        return json.dumps({"traceEvents": events,
                           "displayTimeUnit": "ms"})


class DebugServer:
    """HTTP debug endpoints (the pprofBindAddress analog):

    - ``GET /debug/pprof/profile?seconds=S`` — profile the process for
      S seconds, return the sampling report;
    - ``GET /debug/pprof/sample/start`` / ``.../sample/stop`` — the
      open-ended analog: start background sampling now, fetch the
      report whenever the incident is over (no fixed window up front);
    - ``GET /debug/trace`` — the tracer's Chrome-trace JSON;
    - ``GET /debug/trace/clear`` — reset the span ring.
    """

    def __init__(self, tracer: Optional[Tracer] = None,
                 port: int = 0) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlparse

        self.tracer = tracer
        sampler = SamplingProfiler()

        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code: int, body: str,
                       ctype: str = "text/plain") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                url = urlparse(self.path)
                if url.path == "/debug/pprof/profile":
                    qs = parse_qs(url.query)
                    try:
                        seconds = float(qs.get("seconds", ["1"])[0])
                    except ValueError:
                        self._reply(400, "seconds must be a number")
                        return
                    if not 0 < seconds <= 60:
                        self._reply(400, "seconds must be in (0, 60]")
                        return
                    # sampling profiler: sees every thread's stack, not
                    # just this handler thread (cProfile would not)
                    try:
                        self._reply(200, sampler.sample_for(seconds))
                    except RuntimeError as e:
                        self._reply(409, str(e))
                elif url.path == "/debug/pprof/sample/start":
                    try:
                        sampler.start()
                    except RuntimeError as e:
                        self._reply(409, str(e))
                    else:
                        self._reply(200, "sampling started")
                elif url.path == "/debug/pprof/sample/stop":
                    try:
                        self._reply(200, sampler.stop())
                    except RuntimeError as e:
                        self._reply(409, str(e))
                elif url.path == "/debug/trace":
                    if outer.tracer is None:
                        self._reply(404, "no tracer attached")
                    else:
                        self._reply(200, outer.tracer.chrome_trace(),
                                    "application/json")
                elif url.path == "/debug/trace/clear":
                    if outer.tracer is not None:
                        outer.tracer.clear()
                    self._reply(200, "ok")
                else:
                    self._reply(404, "not found")

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def attach_to_scheduler(scheduler, tracer: Tracer) -> None:
    """Wrap the scheduler's cycle phases in tracer spans: one
    'schedule' span per cycle with nested 'snapshot' / 'nominate'
    phases (the reference logs per-phase durations at V(2)). The tracer
    is also published on the scheduler so the solver engine's drain and
    imported sidecar spans land in the SAME ring — one merged timeline
    keyed by cycle id."""
    scheduler.tracer = tracer
    orig_schedule = scheduler.schedule
    orig_nominate = scheduler._nominate

    def schedule(now=None):
        with tracer.span("schedule", cycle=scheduler.cycle_count + 1):
            return orig_schedule(now)

    def nominate(heads, snapshot, now):
        with tracer.span("nominate", heads=len(heads)):
            return orig_nominate(heads, snapshot, now)

    scheduler.schedule = schedule
    scheduler._nominate = nominate
