"""Elastic jobs via workload slices (KEP-77).

Reference parity: pkg/workloadslicing/workloadslicing.go — scale-up of an
admitted job creates a *new slice* workload annotated as the replacement
for the old one; the scheduler treats the old slice's usage as removable
during flavor assignment (delta-only accounting, flavorassigner.go:779-787)
and, on admission of the new slice, marks the old slice Finished with
reason WorkloadSliceReplaced instead of preempting it (scheduler.go:441,
1045-1061).
"""

from __future__ import annotations

from typing import Optional

from kueue_oss_tpu import features
from kueue_oss_tpu.api.types import Workload, WorkloadConditionType
from kueue_oss_tpu.core.snapshot import Snapshot
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.core.workload_info import WorkloadInfo
from kueue_oss_tpu.scheduler.preemption import Target

#: annotation key/value enabling slicing on a job
ENABLED_ANNOTATION_KEY = "kueue.x-k8s.io/elastic-job"
ENABLED_ANNOTATION_VALUE = "true"

#: Finished-condition reason for a replaced slice
REASON_SLICE_REPLACED = "WorkloadSliceReplaced"
REASON_OUT_OF_SYNC = "OutOfSync"

#: Target.reason marker carried through the preemption-target list
TARGET_REASON = "WorkloadSliceReplacement"


def enabled(job) -> bool:
    """True when the job opts into slicing (workloadslicing.go Enabled).

    Jobs whose podsets carry topology requests additionally need the
    alpha ElasticJobsViaWorkloadSlicesWithTAS gate: a slice replacing a
    TAS-placed workload must re-run placement, which the base slicing
    path only supports behind that gate (kube_features.go)."""
    if not features.enabled("ElasticJobsViaWorkloadSlices"):
        return False
    if (getattr(job, "annotations", {}).get(ENABLED_ANNOTATION_KEY)
            != ENABLED_ANNOTATION_VALUE):
        return False
    uses_tas = any(ps.topology_request is not None
                   for ps in job.pod_sets())
    if uses_tas and not features.enabled(
            "ElasticJobsViaWorkloadSlicesWithTAS"):
        return False
    return True


def is_elastic_workload(wl: Workload) -> bool:
    return wl.replacement_for is not None


def is_replaced(wl: Workload) -> bool:
    """workloadslicing.go IsReplaced: Finished with WorkloadSliceReplaced."""
    c = wl.condition(WorkloadConditionType.FINISHED)
    return c is not None and c.status and c.reason == REASON_SLICE_REPLACED


def find_not_finished_workloads(store: Store, owner: str) -> list[Workload]:
    """Active slices for a job, oldest first (workloadslicing.go
    FindNotFinishedWorkloads sorts by creation timestamp)."""
    out = [wl for wl in store.workloads.values()
           if wl.owner == owner and not wl.is_finished and wl.active]
    out.sort(key=lambda w: (w.creation_time, w.uid))
    return out


def replaced_workload_slice(
    info: WorkloadInfo, snapshot: Snapshot
) -> tuple[list[Target], Optional[WorkloadInfo]]:
    """The old slice this workload replaces, as a preemption target, if it
    currently holds quota in the same CQ (workloadslicing.go:333-355)."""
    if not features.enabled("ElasticJobsViaWorkloadSlices"):
        return [], None
    slice_key = info.obj.replacement_for
    if slice_key is None:
        return [], None
    cq = snapshot.cluster_queue(info.cluster_queue)
    if cq is None:
        return [], None
    replaced = cq.workloads.get(slice_key)
    if replaced is None:
        return [], None
    return [Target(info=replaced, reason=TARGET_REASON, cq=cq)], replaced


def find_replaced_slice_target(
    preemptor: Workload, targets: list[Target]
) -> tuple[list[Target], Optional[Target]]:
    """Pull the old-slice target out of the preemption targets: it is
    finished (replaced), never evicted (workloadslicing.go:376-391)."""
    if not features.enabled("ElasticJobsViaWorkloadSlices"):
        return targets, None
    slice_key = preemptor.replacement_for
    if slice_key is None:
        return targets, None
    for i, t in enumerate(targets):
        if t.info.key == slice_key:
            return targets[:i] + targets[i + 1:], t
    return targets, None


def scaled_down(old_counts: dict[str, int], new_counts: dict[str, int]) -> bool:
    """Strictly-fewer-replicas in at least one podset, none grew."""
    return (any(new_counts[k] < old_counts[k] for k in old_counts)
            and all(new_counts[k] <= old_counts[k] for k in old_counts))


def _podset_counts(podsets) -> dict[str, int]:
    return {ps.name: ps.count for ps in podsets}


def ensure_workload_slices(store: Store, scheduler, job, job_podsets,
                           owner: str, now: float,
                           create) -> tuple[Optional[Workload], bool]:
    """The 0/1/2-active-slices state machine (workloadslicing.go:160-277).

    `create` is a callback (podsets, replacement_for, index) -> Workload
    supplied by the job reconciler (it owns naming and store insertion).
    Returns (workload-to-track, compatible); compatible=False means the
    existing workload has different podset keys and nothing was done.
    """
    job_counts = _podset_counts(job_podsets)
    slices = find_not_finished_workloads(store, owner)

    if len(slices) == 0:
        return create(job_podsets, None, _next_index(store, owner)), True

    if len(slices) == 2:
        old_wl, new_wl = slices
        admitted_as_replacement = (
            new_wl.is_quota_reserved
            and new_wl.replacement_for == old_wl.key)
        if (not old_wl.is_quota_reserved or old_wl.is_evicted
                or admitted_as_replacement):
            finish_slice(store, scheduler, old_wl, REASON_OUT_OF_SYNC,
                         "The workload slice is out of sync with its "
                         "parent job", now)
            slices = [new_wl]
        else:
            slices = [new_wl]  # evaluate the job against the newest slice
        wl = slices[0]
    else:
        wl = slices[0]

    wl_counts = _podset_counts(wl.podsets)
    if set(wl_counts) != set(job_counts):
        return None, False  # incompatible shapes; leave untouched
    if wl_counts == job_counts:
        return wl, True
    if not wl.is_quota_reserved or scaled_down(wl_counts, job_counts):
        apply_podset_counts(wl, job_counts)
        store.update_workload(wl)
        return wl, True
    # scale-up on an admitted slice → new replacement slice
    return create(job_podsets, wl.key, _next_index(store, owner)), True


def _next_index(store: Store, owner: str) -> int:
    n = sum(1 for w in store.workloads.values() if w.owner == owner)
    return n + 1


def apply_podset_counts(wl: Workload, counts: dict[str, int]) -> None:
    """In-place count update (+ shrink the recorded admission usage on an
    admitted scale-down so the caches release the freed quota)."""
    for ps in wl.podsets:
        if ps.name in counts:
            ps.count = counts[ps.name]
    if wl.status.admission is not None:
        for psa in wl.status.admission.podset_assignments:
            new_count = counts.get(psa.name)
            if new_count is None or psa.count in (0, new_count):
                continue
            ratio = new_count / psa.count
            psa.resource_usage = {
                r: int(q * ratio) for r, q in psa.resource_usage.items()}
            psa.count = new_count


def finish_slice(store: Store, scheduler, wl: Workload, reason: str,
                 message: str, now: float) -> None:
    """Finish (not evict) a replaced/out-of-sync slice, releasing quota."""
    if wl.is_finished:
        return
    wl.set_condition(WorkloadConditionType.FINISHED, True, reason=reason,
                     message=message, now=now)
    store.update_workload(wl)
    scheduler.queues.report_workload_finished(wl)
