"""Dynamic Resource Allocation support (KEP-2941).

Reference parity: pkg/dra — pods reference ResourceClaim(Template)s whose
device requests name a DeviceClass; the configured deviceClassMappings
translate device-class counts into *logical* resource names that flow
through the ordinary quota math (mapper.go:32-74, claims.go:56-244). Only
Exactly+ExactCount device requests are supported, like the reference's
step-1 scope; unsupported shapes are rejected with field errors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, Workload


class DRAError(ValueError):
    pass


ALLOCATION_EXACT_COUNT = "ExactCount"
ALLOCATION_ALL = "All"


@dataclass
class DeviceRequest:
    """resourcev1.DeviceRequest (Exactly form)."""

    name: str
    device_class: str
    count: int = 1
    allocation_mode: str = ALLOCATION_EXACT_COUNT
    admin_access: bool = False
    #: attribute equality selectors evaluated against DeviceSlice devices
    #: (stand-in for the reference's CEL selectors)
    selectors: dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceClaimTemplate:
    name: str
    requests: list[DeviceRequest] = field(default_factory=list)


@dataclass
class DeviceSlice:
    """resourcev1.ResourceSlice analog: devices published by a driver."""

    device_class: str
    count: int
    attributes: dict[str, str] = field(default_factory=dict)


def count_devices_per_class(claim: ResourceClaimTemplate) -> dict[str, int]:
    """Device-class → count for one claim (claims.go countDevicesPerClass).

    Raises DRAError on the shapes the reference rejects.
    """
    out: dict[str, int] = {}
    for req in claim.requests:
        if req.admin_access:
            raise DRAError(f"claim {claim.name}/{req.name}: "
                           "AdminAccess is not supported")
        if req.allocation_mode == ALLOCATION_ALL:
            raise DRAError(f"claim {claim.name}/{req.name}: "
                           "AllocationMode 'All' is not supported")
        if req.allocation_mode != ALLOCATION_EXACT_COUNT:
            raise DRAError(f"claim {claim.name}/{req.name}: unsupported "
                           f"allocation mode {req.allocation_mode!r}")
        if not req.device_class:
            continue
        out[req.device_class] = out.get(req.device_class, 0) + req.count
    return out


def selector_matches(req: DeviceRequest, dev_slice: DeviceSlice) -> bool:
    """Attribute-equality evaluation of a request against a slice
    (claims.go CEL selector evaluation, restricted to equality)."""
    if req.device_class != dev_slice.device_class:
        return False
    return all(dev_slice.attributes.get(k) == v
               for k, v in req.selectors.items())


def claim_satisfiable(claim: ResourceClaimTemplate,
                      slices: list[DeviceSlice]) -> bool:
    """Whether published ResourceSlices could satisfy the claim at all.

    Requests draw from a shared pool. Allocation is greedy but ordered to
    avoid the obvious mis-assignments: most-constrained requests (fewest
    matching slices) allocate first, and each request prefers slices that
    fewer other requests could use (exact feasibility is bipartite
    matching; this heuristic covers the practical shapes).
    """
    matches = {id(req): [i for i, s in enumerate(slices)
                         if selector_matches(req, s)]
               for req in claim.requests}
    demand_per_slice = [0] * len(slices)
    for req in claim.requests:
        for i in matches[id(req)]:
            demand_per_slice[i] += 1
    remaining = [s.count for s in slices]
    ordered = sorted(claim.requests,
                     key=lambda r: (len(matches[id(r)]), -r.count))
    for req in ordered:
        need = req.count
        for i in sorted(matches[id(req)], key=lambda i: demand_per_slice[i]):
            if need <= 0:
                break
            take = min(need, remaining[i])
            remaining[i] -= take
            need -= take
        if need > 0:
            return False
    return True


class DeviceClassMapper:
    """deviceClassMappings from the Configuration (mapper.go:32-74)."""

    def __init__(self, mappings: dict[str, str]) -> None:
        #: device class name -> logical resource name
        self.mappings = dict(mappings)

    def logical_resource(self, device_class: str) -> Optional[str]:
        return self.mappings.get(device_class)

    def resolve_claims(
        self, claims: list[ResourceClaimTemplate]
    ) -> dict[str, int]:
        """Claims → logical resource requests; unmapped classes error the
        way the reference marks the workload inadmissible."""
        out: dict[str, int] = {}
        for claim in claims:
            for dc, count in count_devices_per_class(claim).items():
                logical = self.logical_resource(dc)
                if logical is None:
                    raise DRAError(
                        f"device class {dc!r} has no deviceClassMapping")
                out[logical] = out.get(logical, 0) + count
        return out

    def apply_to_podset(self, ps: PodSet,
                        claims: list[ResourceClaimTemplate]) -> None:
        """Fold per-pod claim devices into the podset's requests."""
        for resource, count in self.resolve_claims(claims).items():
            ps.requests[resource] = ps.requests.get(resource, 0) + count

    def apply_to_workload(self, wl: Workload,
                          claims_by_podset: dict[str, list[ResourceClaimTemplate]]
                          ) -> None:
        for ps in wl.podsets:
            claims = claims_by_podset.get(ps.name)
            if claims:
                self.apply_to_podset(ps, claims)


# ---------------------------------------------------------------------------
# Extended resources backed by DRA (extended_resources.go)
# ---------------------------------------------------------------------------


@dataclass
class DeviceClass:
    """resourcev1.DeviceClass analog: spec.extendedResourceName lets
    containers keep requesting the familiar extended resource (e.g.
    vendor.com/gpu) while DRA backs it."""

    name: str
    extended_resource_name: Optional[str] = None


def is_extended_resource_name(name: str) -> bool:
    """util/resource IsExtendedResourceName: domain-prefixed, not a
    kubernetes.io-domain native resource, and not a quota-style
    `requests.`-prefixed key."""
    if "/" not in name or name.startswith("requests."):
        return False
    domain = name.split("/", 1)[0]
    return not (domain == "kubernetes.io"
                or domain.endswith(".kubernetes.io"))


def resolve_extended_resources(
    ps: PodSet,
    device_classes: list[DeviceClass],
    mapper: DeviceClassMapper,
) -> list[str]:
    """DRAExtendedResources (extended_resources.go:51-120, gated): an
    extended resource whose name matches a DeviceClass's
    extendedResourceName is replaced by the class's mapped LOGICAL
    resource, so DRA-backed devices flow through the ordinary quota
    math. Returns the replaced resource names; multiple DeviceClasses
    claiming one extended resource is an error (the reference rejects
    the ambiguity)."""
    from kueue_oss_tpu import features

    if not (features.enabled("DynamicResourceAllocation")
            and features.enabled("DRAExtendedResources")):
        return []
    by_ext: dict[str, list[DeviceClass]] = {}
    for dc in device_classes:
        if dc.extended_resource_name:
            by_ext.setdefault(dc.extended_resource_name, []).append(dc)
    # Resolve against a SNAPSHOT and validate everything before touching
    # ps.requests: a DRAError must not leave the podset half-translated,
    # and a logical name colliding with another class's
    # extendedResourceName must not chain-resolve.
    plan: list[tuple[str, str, int]] = []  # (extended, logical, qty)
    for resource, qty in ps.requests.items():
        if qty <= 0 or not is_extended_resource_name(resource):
            continue
        classes = by_ext.get(resource)
        if not classes:
            continue
        if len(classes) > 1:
            raise DRAError(
                f"extended resource {resource!r} is claimed by multiple "
                f"DeviceClasses: {sorted(dc.name for dc in classes)}")
        logical = mapper.logical_resource(classes[0].name)
        if logical is None:
            raise DRAError(f"device class {classes[0].name!r} has no "
                           "deviceClassMapping")
        plan.append((resource, logical, qty))
    # all deletions before all additions: a logical name that equals a
    # later-deleted extended name must not have its merged value removed
    for resource, _, _ in plan:
        del ps.requests[resource]
    for _, logical, qty in plan:
        ps.requests[logical] = ps.requests.get(logical, 0) + qty
    return [resource for resource, _, _ in plan]
