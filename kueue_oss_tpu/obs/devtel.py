"""Device telemetry: compile accounting, transfer ledger, deep capture.

Every observability layer before this one watched the HOST — the
flight recorder, the CycleLedger, the SLO engine. The device side
(XLA compiles, HBM residency, host<->device transfer volume, the
farm's grant-wait) was a black box even though four solver arms,
device-resident sessions, and a multi-tenant farm live there. This
module is the device-side collector, threaded through the solver
fabric (docs/OBSERVABILITY.md "Device telemetry & fabric tracing"):

- :class:`CompileDetector` — first-call compilation detection per
  (kernel, arm, pow2 shape-bucket). The engine's arm router used to
  discard the FIRST wall sample per arm unconditionally ("compile
  tainted"); with the detector enabled the verdict is per shape
  bucket, so a warm arm re-solving at a new padded width is caught
  (and a warm arm's first sample is no longer wasted).
- transfer ledger — the scattered donated/avoided byte counters in
  solver/delta.py unify into one
  ``solver_transfer_bytes_total{direction,arm,tenant}`` family, plus
  per-drain HBM watermark gauges (device ``memory_stats()`` where the
  backend exposes them, resident-problem byte bookkeeping as the
  portable fallback).
- :class:`DeepCapture` — tail-based deep capture: a bounded
  ``jax.profiler.trace`` session triggered when an SLO burn alert
  fires or the PhaseRegressionDetector trips. One in-flight capture,
  cooldown via the ladder's :class:`CooldownPolicy`, artifacts
  retained beside checkpoints, armed/drained via
  ``GET/POST /api/telemetry``.

The process-wide :data:`collector` follows the obs.recorder idiom;
``obs.configure()`` applies ``observability.devtel`` from config.
Everything is clock-injectable for virtual-time tests.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from kueue_oss_tpu import metrics
from kueue_oss_tpu.resilience import CooldownPolicy

#: device-delta counter name -> transfer direction (the unification of
#: solver/delta.py's scattered byte counters; counts are not bytes and
#: stay out of the transfer family)
TRANSFER_DIRECTIONS = {
    "donated_update_bytes": "h2d",
    "full_upload_bytes": "h2d",
    "avoided_copy_bytes": "avoided",
}


def shape_bucket(n: int) -> str:
    """Pow2 ceiling bucket for a solve's row count. XLA recompiles per
    padded shape; the engine pads to pow2-ish targets, so two solves in
    the same bucket share a compiled program."""
    if n <= 1:
        return "1" if n == 1 else "0"
    return str(1 << (int(n) - 1).bit_length())


def device_memory_stats() -> dict[str, int]:
    """``bytes_in_use`` per local device, where the backend exposes
    allocator stats (TPU/GPU PJRT; CPU usually returns nothing).
    Never raises — devtel must not be able to break a drain."""
    try:
        import jax

        out = {}
        for d in jax.local_devices():
            ms = getattr(d, "memory_stats", None)
            stats = ms() if callable(ms) else None
            if stats and "bytes_in_use" in stats:
                out[str(d.id)] = int(stats["bytes_in_use"])
        return out
    except Exception:
        return {}


class CompileDetector:
    """First-call compile detection on the engine's jitted entries.

    A (kernel, arm, shape-bucket) triple seen for the first time is a
    compile-bearing call: its wall upper-bounds compile time (the wall
    includes the traced execution) and must not feed the router's EMA.
    ``forget`` re-arms keys when the router resets an arm (mesh
    refresh, demotion) so the next solve is treated as fresh again —
    mirroring the legacy ``_arm_warm.discard`` touchpoints.
    """

    def __init__(self, tracer=None) -> None:
        self.tracer = tracer
        self._lock = threading.Lock()
        self._seen: set = set()
        #: total compile events since construction (bench/status)
        self.compiles = 0
        self._events: list = []

    def observe_solve(self, kernel: str, arm: str, n: int,
                      wall_s: float) -> bool:
        """Record one timed solve; True iff it carried a compile."""
        bucket = shape_bucket(n)
        key = (kernel, arm, bucket)
        with self._lock:
            if key in self._seen:
                return False
            self._seen.add(key)
            self.compiles += 1
            self._events.append({"kernel": kernel, "arm": arm,
                                 "bucket": bucket,
                                 "wallSeconds": round(float(wall_s), 6)})
        metrics.solver_compiles_total.inc(kernel, arm, bucket)
        metrics.solver_compile_seconds.observe(value=float(wall_s))
        tracer = self.tracer
        if tracer is not None and getattr(tracer, "enabled", False):
            dur_us = int(float(wall_s) * 1e6)
            now_us = int(tracer.clock() * 1e6)
            tracer.add_span("xla_compile", now_us - dur_us, dur_us,
                            source="devtel", kernel=kernel, arm=arm,
                            bucket=bucket)
        return True

    def seen(self, kernel: str, arm: str, n: int) -> bool:
        with self._lock:
            return (kernel, arm, shape_bucket(n)) in self._seen

    def forget(self, kernel: Optional[str] = None,
               arm: Optional[str] = None) -> None:
        """Drop seen keys matching kernel/arm (None = wildcard)."""
        with self._lock:
            self._seen = {k for k in self._seen
                          if not ((kernel is None or k[0] == kernel)
                                  and (arm is None or k[1] == arm))}

    def drain_events(self) -> list:
        """Pop compile events since the last drain (ledger-row field)."""
        with self._lock:
            out, self._events = self._events, []
            return out

    def reset(self) -> None:
        with self._lock:
            self._seen.clear()
            self._events.clear()
            self.compiles = 0


class DeepCapture:
    """Tail-based deep capture with one in-flight slot + cooldown.

    ``trigger`` starts a bounded capture session unless the capturer
    is disarmed, busy, or cooling down (:class:`CooldownPolicy` keyed
    ``("devtel", "capture")`` — the stamp is set at capture START, so
    back-to-back alert storms yield one artifact per cooldown window).
    A capture writes a ``capture.json`` marker into its own directory
    beside the checkpoints and, when ``use_profiler`` is set and jax's
    profiler is importable, brackets a real ``jax.profiler`` trace.
    ``poll`` finishes the session once ``max_seconds`` elapses; POST
    /api/telemetry can stop it early. All timing flows through the
    injected clock.
    """

    KEY = ("devtel", "capture")
    TRIGGERS = ("slo_burn", "phase_regression", "manual")

    def __init__(self, dir: Optional[str] = None,
                 max_seconds: float = 5.0,
                 cooldown_s: float = 300.0,
                 use_profiler: bool = False,
                 clock=time.monotonic) -> None:
        self.dir = dir
        self.max_seconds = float(max_seconds)
        self.cooldown_s = float(cooldown_s)
        self.use_profiler = bool(use_profiler)
        self.cooldowns = CooldownPolicy(clock)
        self.armed = True
        self._lock = threading.Lock()
        self._active: Optional[dict] = None
        self._seq = 0
        self.history: list = []

    @property
    def clock(self):
        return self.cooldowns.clock

    @clock.setter
    def clock(self, clock) -> None:
        self.cooldowns.clock = clock

    def trigger(self, reason: str, detail: Optional[dict] = None) -> bool:
        """Try to start a capture; False (with a counted outcome) when
        suppressed. Never raises."""
        reason = reason if reason in self.TRIGGERS else "manual"
        with self._lock:
            if not self.armed:
                metrics.solver_deep_captures_total.inc(reason, "disarmed")
                return False
            if self._active is not None:
                metrics.solver_deep_captures_total.inc(
                    reason, "suppressed_busy")
                return False
            cp = self.cooldowns
            if (cp.stamp(self.KEY) is not None
                    and not cp.elapsed(self.KEY, self.cooldown_s)):
                metrics.solver_deep_captures_total.inc(
                    reason, "suppressed_cooldown")
                return False
            cp.note_fault(self.KEY)  # cooldown runs from capture START
            self._seq += 1
            rec = {"seq": self._seq, "reason": reason,
                   "startedAt": cp.clock(), "detail": detail or {},
                   "profiler": False, "path": None}
            self._active = rec
        self._materialize(rec)
        metrics.solver_deep_captures_total.inc(reason, "started")
        return True

    def _materialize(self, rec: dict) -> None:
        """Create the artifact directory + start the profiler. Outside
        the lock — filesystem/profiler faults degrade to a marker-less
        capture, never to a failed trigger."""
        if self.dir:
            path = os.path.join(
                self.dir, f"capture-{rec['seq']:03d}-{rec['reason']}")
            try:
                os.makedirs(path, exist_ok=True)
                rec["path"] = path
                self._write_marker(rec)
            except OSError:
                rec["path"] = None
        if self.use_profiler and rec["path"]:
            try:
                import jax

                jax.profiler.start_trace(rec["path"])
                rec["profiler"] = True
            except Exception:
                rec["profiler"] = False

    def _write_marker(self, rec: dict) -> None:
        try:
            with open(os.path.join(rec["path"], "capture.json"),
                      "w") as fh:
                json.dump(rec, fh, indent=2, sort_keys=True,
                          default=str)
        except OSError:
            pass

    def poll(self, now: Optional[float] = None) -> bool:
        """Finish the in-flight capture once its budget elapses; True
        iff a capture was closed by this call."""
        with self._lock:
            rec = self._active
            if rec is None:
                return False
            t = self.clock() if now is None else now
            if t - rec["startedAt"] < self.max_seconds:
                return False
            self._active = None
        self._finish(rec, t)
        return True

    def stop(self) -> bool:
        """Force-finish the in-flight capture (POST /api/telemetry)."""
        with self._lock:
            rec = self._active
            if rec is None:
                return False
            self._active = None
        self._finish(rec, self.clock())
        return True

    def _finish(self, rec: dict, t: float) -> None:
        if rec.get("profiler"):
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
        rec["endedAt"] = t
        rec["durationSeconds"] = round(max(0.0, t - rec["startedAt"]), 6)
        if rec.get("path"):
            self._write_marker(rec)
        with self._lock:
            self.history.append(rec)
            del self.history[:-16]

    def active(self) -> Optional[dict]:
        with self._lock:
            return dict(self._active) if self._active else None

    def status(self) -> dict:
        cp = self.cooldowns
        stamp = cp.stamp(self.KEY)
        remaining = 0.0
        if stamp is not None:
            remaining = max(0.0, self.cooldown_s - (cp.clock() - stamp))
        with self._lock:
            return {"armed": self.armed,
                    "active": dict(self._active) if self._active
                    else None,
                    "maxSeconds": self.max_seconds,
                    "cooldownSeconds": self.cooldown_s,
                    "cooldownRemainingSeconds": round(remaining, 3),
                    "useProfiler": self.use_profiler,
                    "dir": self.dir,
                    "captures": [dict(r) for r in self.history]}

    def reset(self) -> None:
        self.stop()
        with self._lock:
            self.history.clear()
            self._seq = 0
            self.armed = True
        self.cooldowns.clear(self.KEY)


class DeviceTelemetry:
    """The collector the solver fabric threads through.

    Disabled by default (``enabled`` gates every hook to a cheap
    early-out, the bench twin's overhead contract); ``configure``
    applies a config.DevTelConfig. The engine calls ``observe_solve``
    from its arm-wall router, ``note_transfers``/``sample_residency``
    from its ledger path, and ``on_drain`` once per drain — which
    polls the phase-regression detector and ticks the capture budget.
    An SLO sink (registered on the process-wide engine when capture is
    enabled) fires captures on burn-alert transitions.
    """

    def __init__(self, clock=time.monotonic) -> None:
        self.enabled = False
        self.compile_enabled = True
        self.transfer_enabled = True
        self.hbm_enabled = True
        self.capture_enabled = False
        self.compiles = CompileDetector()
        self.capture = DeepCapture(clock=clock)
        self._lock = threading.Lock()
        #: direction -> total bytes (the bench/status aggregate of the
        #: metric family, kept label-free on purpose)
        self.transfer_bytes: dict = {}
        self.hbm_resident_bytes = 0
        self._sink_registered = False

    # -- wiring ------------------------------------------------------------

    @property
    def tracer(self):
        return self.compiles.tracer

    @tracer.setter
    def tracer(self, tracer) -> None:
        self.compiles.tracer = tracer

    def _slo_sink(self, transition: str, payload: dict) -> None:
        if transition == "fired" and self.enabled and self.capture_enabled:
            self.capture.trigger("slo_burn", {
                "scope": payload.get("scope"),
                "key": payload.get("key"),
                "exemplar": payload.get("exemplar")})

    def attach_alerts(self) -> None:
        """Register the capture trigger on the process-wide SLO engine
        (idempotent)."""
        if self._sink_registered:
            return
        from kueue_oss_tpu.obs.health import slo

        slo.add_sink(self._slo_sink)
        self._sink_registered = True

    def detach_alerts(self) -> None:
        if not self._sink_registered:
            return
        from kueue_oss_tpu.obs.health import slo

        slo.remove_sink(self._slo_sink)
        self._sink_registered = False

    # -- engine hooks ------------------------------------------------------

    def observe_solve(self, kernel: str, arm: str, n: int,
                      wall_s: float) -> bool:
        """Compile verdict for one timed solve (False when disabled —
        the engine then falls back to its legacy warm-set)."""
        if not (self.enabled and self.compile_enabled):
            return False
        return self.compiles.observe_solve(kernel, arm, n, wall_s)

    def forget(self, kernel: Optional[str] = None,
               arm: Optional[str] = None) -> None:
        if self.enabled and self.compile_enabled:
            self.compiles.forget(kernel, arm)

    def note_transfers(self, arm: str, tenant: str,
                       device_delta: dict) -> None:
        """Fold one drain's device-counter deltas into the unified
        transfer family."""
        if not (self.enabled and self.transfer_enabled):
            return
        for name, nbytes in (device_delta or {}).items():
            direction = TRANSFER_DIRECTIONS.get(name)
            if direction is None or not nbytes:
                continue
            metrics.solver_transfer_bytes_total.inc(
                direction, arm, tenant, by=float(nbytes))
            with self._lock:
                self.transfer_bytes[direction] = (
                    self.transfer_bytes.get(direction, 0) + int(nbytes))

    def note_wire(self, arm: str, tenant: str, nbytes: int) -> None:
        """One request frame's bytes on the sidecar wire (direction
        ``tx``)."""
        if not (self.enabled and self.transfer_enabled) or not nbytes:
            return
        metrics.solver_transfer_bytes_total.inc(
            "tx", arm, tenant, by=float(nbytes))
        with self._lock:
            self.transfer_bytes["tx"] = (
                self.transfer_bytes.get("tx", 0) + int(nbytes))

    def sample_residency(self, resident_bytes: int) -> dict:
        """Per-drain HBM watermark: gauges + the extra ledger-row
        device fields. Portable bookkeeping always; real allocator
        stats when the backend has them."""
        if not (self.enabled and self.hbm_enabled):
            return {}
        self.hbm_resident_bytes = int(resident_bytes)
        metrics.solver_hbm_resident_bytes.set(value=float(resident_bytes))
        out = {"hbm_resident_bytes": int(resident_bytes)}
        stats = device_memory_stats()
        for dev, in_use in stats.items():
            metrics.solver_hbm_bytes_in_use.set(dev, value=float(in_use))
        if stats:
            out["hbm_bytes_in_use"] = sum(stats.values())
        return out

    def on_drain(self) -> None:
        """Once per engine drain: trip captures on phase regressions
        and tick the in-flight capture's budget."""
        if not self.enabled:
            return
        if self.capture_enabled and self.capture.armed:
            if self.capture.active() is None:
                from kueue_oss_tpu.obs.health import phase_regression

                regressing = phase_regression.regressing()
                if regressing:
                    self.capture.trigger("phase_regression",
                                         {"phases": regressing[:4]})
            self.capture.poll()

    # -- config / surface --------------------------------------------------

    def configure(self, cfg, capture_dir: Optional[str] = None) -> None:
        """Apply a config.DevTelConfig (obs.configure calls this).
        ``capture_dir`` defaults captures beside the checkpoints when
        the config names no directory of its own."""
        self.enabled = bool(cfg.enabled)
        self.compile_enabled = bool(cfg.compile_accounting)
        self.transfer_enabled = bool(cfg.transfer_ledger)
        self.hbm_enabled = bool(cfg.hbm_watermarks)
        self.capture_enabled = bool(cfg.capture_enabled)
        self.capture.max_seconds = float(cfg.capture_max_seconds)
        self.capture.cooldown_s = float(cfg.capture_cooldown_seconds)
        self.capture.use_profiler = bool(cfg.capture_use_profiler)
        self.capture.dir = cfg.capture_dir or capture_dir
        if self.enabled and self.capture_enabled:
            self.attach_alerts()
        else:
            self.detach_alerts()

    def status(self) -> dict:
        """The GET /api/telemetry report."""
        with self._lock:
            transfers = dict(self.transfer_bytes)
        return {"enabled": self.enabled,
                "compile": {"enabled": self.compile_enabled,
                            "events": self.compiles.compiles},
                "transfer": {"enabled": self.transfer_enabled,
                             "bytes": transfers},
                "hbm": {"enabled": self.hbm_enabled,
                        "residentBytes": self.hbm_resident_bytes},
                "capture": dict(self.capture.status(),
                                enabled=self.capture_enabled)}

    def reset(self) -> None:
        """Test helper (the recorder idiom): back to the disabled
        defaults, sink detached, detector/capture state dropped."""
        self.detach_alerts()
        self.enabled = False
        self.compile_enabled = True
        self.transfer_enabled = True
        self.hbm_enabled = True
        self.capture_enabled = False
        self.compiles.reset()
        self.capture.reset()
        self.capture.dir = None
        self.capture.max_seconds = 5.0
        self.capture.cooldown_s = 300.0
        self.capture.use_profiler = False
        with self._lock:
            self.transfer_bytes.clear()
        self.hbm_resident_bytes = 0


#: process-wide collector (the obs.recorder idiom); obs.configure()
#: applies observability.devtel onto it
collector = DeviceTelemetry()


def reset() -> None:
    collector.reset()
