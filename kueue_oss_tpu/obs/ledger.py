"""Cycle ledger: one bounded structured record per scheduler cycle.

The flight recorder answers "why is THIS workload pending"; the ledger
answers "what did the CLUSTER do this cycle": one JSONL-dumpable row
per host scheduling cycle and per solver drain, keyed by the SAME
cycle id the recorder tags its DecisionEvents with — a ledger row and
the decision chain for a cycle join on that id (Gavel,
arXiv:2008.09213, treats per-round placement latencies as the primary
health artifact; this is our per-round record).

A host row carries the cycle's phase durations (the same phase names
the Tracer spans use — ``snapshot`` / ``nominate`` / ``entries`` /
``flush``), admitted/preempted/skipped counts with per-slug skip
breakdowns, and the solver breaker state at cycle end. A solver row
carries the chosen arm (host routing's third arm lives in the
scheduler), the session frame kind (sync/delta/legacy) with its
payload bytes and session churn stats, donated-buffer accounting
deltas from the resident device state, and the solve/apply walls.

Bounded ring (newest ``max_cycles`` rows), thread-safe, dumpable with
the same atomic + dir-fsynced discipline as the decision journal, and
persisted/restored alongside checkpoints by the PersistenceManager
(docs/DURABILITY.md).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu import metrics

#: row kinds — one host row per scheduler cycle, one solver row per
#: engine drain (both tagged with the host cycle id the drain served),
#: one stream row per productive micro-batched admission drain
HOST_CYCLE = "host"
SOLVER_DRAIN = "solver"
STREAM_DRAIN = "stream"
#: degradation-ladder transition rows (resilience.DegradationController):
#: the transition entry rides in ``detail``; cycle-outcome fields stay 0
DEGRADATION_ROW = "degradation"


@dataclass
class CycleRecord:
    """One per-cycle (or per-drain) ledger row. Fields not meaningful
    for the row's kind stay at their zero values and are omitted from
    ``to_dict`` where empty."""

    seq: int
    ts: float
    cycle: int
    kind: str = HOST_CYCLE
    breaker: str = "closed"
    duration_s: float = 0.0
    #: phase name -> seconds (host rows: snapshot/nominate/entries/
    #: flush; solver rows: solve/apply)
    phases: dict = field(default_factory=dict)
    # -- host cycle outcome counts --------------------------------------
    heads: int = 0
    admitted: int = 0
    preempted: int = 0
    skipped: int = 0
    inadmissible: int = 0
    #: bounded reason slug -> count for this cycle's skips
    skip_slugs: dict = field(default_factory=dict)
    # -- solver drain routing + session wire ----------------------------
    solver_arm: str = ""            # "single" / "mesh" / "remote"
    rounds: int = 0
    parked: int = 0
    evicted: int = 0
    #: session frame kind: "delta" / "sync" / "legacy" (sessions off)
    frame_kind: str = ""
    #: payload bytes the frame shipped (delta rows+meta, or the full
    #: wire state for a sync)
    frame_bytes: int = 0
    #: why a full sync was forced ("" for deltas)
    frame_reason: str = ""
    #: HostDeltaSession churn stats (added/removed keys, dirty rows)
    session: dict = field(default_factory=dict)
    #: milliseconds this drain's solve request waited for its farm DRR
    #: grant (0 = dedicated sidecar / host path / farm idle)
    grant_wait_ms: float = 0.0
    #: resident-device accounting DELTAS for this drain: donated
    #: scatter bytes, avoided full-copy bytes, full uploads, donated
    #: full syncs (DeviceResidentProblem counters)
    device: dict = field(default_factory=dict)
    detail: Optional[dict] = None

    def to_dict(self) -> dict:
        d = {"seq": self.seq, "ts": self.ts, "cycle": self.cycle,
             "kind": self.kind, "breaker": self.breaker,
             "durationS": self.duration_s}
        if self.phases:
            d["phases"] = self.phases
        if self.kind == HOST_CYCLE:
            d.update(heads=self.heads, admitted=self.admitted,
                     preempted=self.preempted, skipped=self.skipped,
                     inadmissible=self.inadmissible)
            if self.skip_slugs:
                d["skipSlugs"] = self.skip_slugs
        else:
            d.update(admitted=self.admitted, parked=self.parked,
                     evicted=self.evicted, rounds=self.rounds,
                     solverArm=self.solver_arm,
                     frameKind=self.frame_kind,
                     frameBytes=self.frame_bytes)
            if self.frame_reason:
                d["frameReason"] = self.frame_reason
            if self.session:
                d["session"] = self.session
            if self.grant_wait_ms:
                d["grantWaitMs"] = self.grant_wait_ms
            if self.device:
                d["device"] = self.device
        if self.detail:
            d["detail"] = self.detail
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "CycleRecord":
        return cls(
            seq=int(d.get("seq", 0)), ts=float(d.get("ts", 0.0)),
            cycle=int(d.get("cycle", 0)),
            kind=str(d.get("kind", HOST_CYCLE)),
            breaker=str(d.get("breaker", "closed")),
            duration_s=float(d.get("durationS", 0.0)),
            phases=dict(d.get("phases") or {}),
            heads=int(d.get("heads", 0)),
            admitted=int(d.get("admitted", 0)),
            preempted=int(d.get("preempted", 0)),
            skipped=int(d.get("skipped", 0)),
            inadmissible=int(d.get("inadmissible", 0)),
            skip_slugs=dict(d.get("skipSlugs") or {}),
            solver_arm=str(d.get("solverArm", "")),
            rounds=int(d.get("rounds", 0)),
            parked=int(d.get("parked", 0)),
            evicted=int(d.get("evicted", 0)),
            frame_kind=str(d.get("frameKind", "")),
            frame_bytes=int(d.get("frameBytes", 0)),
            frame_reason=str(d.get("frameReason", "")),
            session=dict(d.get("session") or {}),
            grant_wait_ms=float(d.get("grantWaitMs", 0.0)),
            device=dict(d.get("device") or {}),
            detail=d.get("detail"))


class CycleLedger:
    """Bounded thread-safe ring of CycleRecords.

    ``record()`` is called once per scheduler cycle and once per solver
    drain — never per workload — so the steady-state cost is one
    dataclass and one deque append per cycle; ``enabled = False``
    reduces it to an attribute read (the bench twin's off arm).
    """

    def __init__(self, max_cycles: int = 4096, clock=time.time) -> None:
        self.enabled = True
        self.max_cycles = max_cycles
        self.clock = clock
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._ring: deque[CycleRecord] = deque(maxlen=max_cycles)

    # -- emission ----------------------------------------------------------

    def record(self, cycle: int, kind: str = HOST_CYCLE,
               **fields) -> Optional[CycleRecord]:
        if not self.enabled:
            return None
        row = CycleRecord(seq=next(self._seq), ts=self.clock(),
                          cycle=cycle, kind=kind, **fields)
        with self._lock:
            self._ring.append(row)
        metrics.ledger_records_total.inc(kind)
        if row.phases:
            # ledger-driven regression detection: every recorded row
            # feeds the per-(kind, phase) EWMA-vs-baseline detector
            # (obs/health.py; kueue_cycle_phase_regression)
            from kueue_oss_tpu.obs.health import phase_regression

            phase_regression.feed(kind, row.phases)
        return row

    # -- queries -----------------------------------------------------------

    def rows(self, last: int = 0) -> list[CycleRecord]:
        """Ring snapshot, oldest-first (newest ``last`` rows if given)."""
        with self._lock:
            rows = list(self._ring)
        return rows[-last:] if last else rows

    def rows_for_cycle(self, cycle: int) -> list[CycleRecord]:
        """Every row tagged with this cycle id (one host row and, when
        a drain served the cycle, one solver row) — the join the
        recorder's decisions share."""
        return [r for r in self.rows() if r.cycle == cycle]

    def last_row(self, kind: Optional[str] = None
                 ) -> Optional[CycleRecord]:
        with self._lock:
            for r in reversed(self._ring):
                if kind is None or r.kind == kind:
                    return r
        return None

    # -- journal dump / load / restore -------------------------------------

    def dump_jsonl(self, path: str) -> int:
        """Atomic + dir-fsynced, the decision-journal discipline."""
        from kueue_oss_tpu.obs import _atomic_write_jsonl

        rows = self.rows()
        _atomic_write_jsonl(path, (r.to_dict() for r in rows))
        return len(rows)

    def restore(self, rows: list[CycleRecord]) -> int:
        """Replace the ring with a persisted dump (recovery path); the
        seq counter continues past the restored rows so post-restart
        records keep a monotone journal order."""
        with self._lock:
            self._ring.clear()
            for r in rows[-self.max_cycles:]:
                self._ring.append(r)
            top = max((r.seq for r in self._ring), default=0)
            self._seq = itertools.count(top + 1)
        return len(self._ring)

    def resize(self, max_cycles: int) -> None:
        """Rebuild the ring at a new bound, keeping the newest rows
        (obs.configure applying ObservabilityConfig.ledger_max_cycles)."""
        with self._lock:
            self.max_cycles = max_cycles
            self._ring = deque(self._ring, maxlen=max_cycles)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def load_ledger_jsonl(path: str) -> list[CycleRecord]:
    """Tolerant ledger-dump loader (torn/corrupt lines skipped with a
    counted warning — the decision journal's shared policy)."""
    from kueue_oss_tpu.obs import _tolerant_load_jsonl

    out, skipped = _tolerant_load_jsonl(path, CycleRecord.from_dict,
                                        "ledger")
    load_ledger_jsonl.last_skipped = skipped
    return out


load_ledger_jsonl.last_skipped = 0


#: process-wide ledger (the obs.recorder idiom); tests clear() it
ledger = CycleLedger()
