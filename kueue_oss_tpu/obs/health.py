"""Queue-wait SLO engine: burn-rate alerts + starvation watchdog.

The SLI is **time-to-admit**: the seconds between a workload's creation
and its quota reservation, observed once per admission on both the host
cycle path and the solver drain path (the same wait
``metrics.admitted_workload`` feeds into the wait-time histograms).
An admission is *good* when its wait is within the objective's
threshold; the error budget is ``1 - target``.

Alerting is the classic multi-window burn-rate scheme: the burn rate is
``bad_fraction / error_budget`` over a window, and an alert fires only
when BOTH the fast window (default 5m — catches a live regression) and
the slow window (default 1h — suppresses blips) burn above the
threshold; it clears when the fast window recovers. Every piece of
time is injectable (``clock=`` / ``now=``), so tests drive
deterministic fire/clear sequences on a virtual clock.

The starvation watchdog is the fairness backstop the windows cannot
see: an empty-window CQ with a decade-old pending head has a zero burn
rate but is maximally unhealthy (arXiv:2512.10980 treats oldest-pending
age as the first-class starvation signal). ``evaluate(queues=...)``
surfaces the oldest pending age per CQ against its own threshold.

Each bad admission keeps an exemplar ({cycle, workload, wait}) — the
same exemplar the wait-time histogram's bucket carries — so a firing
alert links straight to the cycle's ledger row and the workload's
decision chain (the acceptance contract in docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu import metrics

#: SLI scopes — per-ClusterQueue and per-priority-class series (the
#: priority scope keys on WorkloadPriorityClass NAMES when the
#: workload carries one, falling back to the stringified integer —
#: /api/slo groups by class, not by raw integer)
SCOPE_CQ = "cq"
SCOPE_PRIORITY = "priority"

FIRING = "firing"
CLEAR = "clear"


def priority_class_of(store, wl) -> str:
    """The SLI key for a workload's priority: its declared
    WorkloadPriorityClass name, else the name of a class whose value
    matches the raw integer, else the stringified integer. One
    mapping shared by the host admit path and the solver commit."""
    if wl.priority_class:
        return str(wl.priority_class)
    if store is not None:
        for name, pc in store.priority_classes.items():
            if pc.value == wl.priority:
                return name
    return str(wl.priority)


class _WindowSeries:
    """Time-bucketed good/bad admission counts covering the slow
    window. Fixed-size ring of buckets; a bucket is lazily reset when
    its wall slot is reused, so feeding and summing are O(1)/O(ring)
    with no timers."""

    def __init__(self, bucket_s: float, n_buckets: int) -> None:
        self.bucket_s = bucket_s
        self.n = n_buckets
        self._epoch = [-1] * n_buckets
        self._total = [0] * n_buckets
        self._bad = [0] * n_buckets

    def _slot(self, t: float) -> tuple[int, int]:
        epoch = int(t // self.bucket_s)
        return epoch, epoch % self.n

    def add(self, t: float, good: bool) -> None:
        epoch, slot = self._slot(t)
        if self._epoch[slot] != epoch:
            self._epoch[slot] = epoch
            self._total[slot] = 0
            self._bad[slot] = 0
        self._total[slot] += 1
        if not good:
            self._bad[slot] += 1

    def sums(self, now: float, window_s: float) -> tuple[int, int]:
        """(total, bad) over the trailing window ending at ``now``."""
        newest = int(now // self.bucket_s)
        oldest = int((now - window_s) // self.bucket_s) + 1
        total = bad = 0
        for slot in range(self.n):
            e = self._epoch[slot]
            if oldest <= e <= newest:
                total += self._total[slot]
                bad += self._bad[slot]
        return total, bad


@dataclass
class Alert:
    scope: str
    key: str
    state: str = CLEAR
    since: float = 0.0
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    #: {cycle, workload, waitSeconds} of the newest breaching
    #: admission — the link into the ledger row + explain chain
    exemplar: Optional[dict] = None

    def to_dict(self) -> dict:
        d = {"scope": self.scope, "key": self.key, "state": self.state,
             "since": self.since,
             "burnFast": round(self.burn_fast, 3),
             "burnSlow": round(self.burn_slow, 3)}
        if self.exemplar:
            d["exemplar"] = self.exemplar
        return d


class SLOEngine:
    """Per-CQ and per-priority queue-wait SLIs with multi-window
    burn-rate alerts. Feeding (``observe_admission``) is O(1) and
    lock-held; evaluation walks every known key once."""

    def __init__(self, *, target: float = 0.99,
                 threshold_s: float = 300.0,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 burn_threshold: float = 6.0,
                 starvation_threshold_s: float = 1800.0,
                 clock=time.time) -> None:
        self.enabled = True
        self.target = target
        self.threshold_s = threshold_s
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.burn_threshold = burn_threshold
        self.starvation_threshold_s = starvation_threshold_s
        self.clock = clock
        #: newest instant this engine has been told about (feeds and
        #: scheduler advance() calls). The scheduler drives the whole
        #: system on a caller-supplied logical clock (virtual in tests
        #: and benches, time.monotonic in serve()), so evaluate()
        #: must default to the FEED domain's newest instant — walling
        #: it to time.time() would put every fed bucket outside the
        #: windows and read burn 0 forever.
        self._now = 0.0
        self._set_geometry(fast_window_s, slow_window_s)
        self._lock = threading.Lock()
        #: serializes whole evaluations: two dashboard threads hitting
        #: /api/slo and /api/health at once must not race the alert
        #: state machine into double fired/cleared transitions
        self._eval_lock = threading.Lock()
        self._series: dict[tuple[str, str], _WindowSeries] = {}
        #: newest breaching admission per key (alert exemplars)
        self._breach: dict[tuple[str, str], dict] = {}
        self.alerts: dict[tuple[str, str], Alert] = {}
        #: last starvation snapshot (evaluate(queues=...))
        self._starvation: list[dict] = []
        #: pluggable alert sinks: callables invoked as
        #: ``sink(transition, alert_dict)`` on every fire/clear
        #: transition. Failures are counted
        #: (kueue_slo_alert_deliveries_total{outcome}) and never
        #: break evaluation. ``_config_sink`` is the slot
        #: obs.configure() owns (a webhook from SLOConfig); add_sink
        #: registrations are programmatic and survive reconfigures.
        self.sinks: list = []
        self._config_sink = None

    def _set_geometry(self, fast_window_s: float,
                      slow_window_s: float) -> None:
        #: bucket width: 1/30 of the fast window (>= 1s) keeps the fast
        #: window's edge error under ~3%
        self._bucket_s = max(1.0, fast_window_s / 30.0)
        self._n_buckets = int(math.ceil(slow_window_s
                                        / self._bucket_s)) + 2

    def reconfigure(self, *, target: float, threshold_s: float,
                    fast_window_s: float, slow_window_s: float,
                    burn_threshold: float,
                    starvation_threshold_s: float) -> None:
        """Apply new objectives and rebuild the window geometry; the
        window and alert state start clean (a reconfigured objective
        must not inherit burn computed against the old one)."""
        self.target = target
        self.threshold_s = threshold_s
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.burn_threshold = burn_threshold
        self.starvation_threshold_s = starvation_threshold_s
        self._set_geometry(fast_window_s, slow_window_s)
        self.reset()

    @classmethod
    def from_config(cls, cfg, clock=time.time) -> "SLOEngine":
        """Build from config.SLOConfig."""
        return cls(target=cfg.queue_wait_target,
                   threshold_s=cfg.queue_wait_threshold_seconds,
                   fast_window_s=cfg.fast_window_seconds,
                   slow_window_s=cfg.slow_window_seconds,
                   burn_threshold=cfg.burn_rate_threshold,
                   starvation_threshold_s=(
                       cfg.starvation_threshold_seconds),
                   clock=clock)

    # -- feeding -----------------------------------------------------------

    def observe_admission(self, cq: str, wait_s: float, *,
                          priority: int = 0, priority_class: str = "",
                          now: Optional[float] = None,
                          cycle: int = 0, workload: str = "") -> None:
        """One admitted workload's time-to-admit, fed at the same call
        sites as ``metrics.admitted_workload`` (scheduler._admit and
        the solver engine's commit). ``priority_class`` keys the
        priority-scope SLI by WorkloadPriorityClass name
        (priority_class_of); blank falls back to the raw integer."""
        if not self.enabled:
            return
        t = now if now is not None else self.clock()
        if t > self._now:
            self._now = t
        good = wait_s <= self.threshold_s
        pkey = priority_class or str(priority)
        with self._lock:
            for key in ((SCOPE_CQ, cq), (SCOPE_PRIORITY, pkey)):
                s = self._series.get(key)
                if s is None:
                    s = self._series[key] = _WindowSeries(
                        self._bucket_s, self._n_buckets)
                s.add(t, good)
                if not good:
                    self._breach[key] = {
                        "cycle": cycle, "workload": workload,
                        "waitSeconds": round(float(wait_s), 3)}

    def replay_journal(self, events) -> int:
        """Rebuild the SLI windows from a restored decision journal
        (the SLO engine's window state dies with the process; the
        durable journal carries each admission's wait in its detail —
        docs/DURABILITY.md recovery path). Returns admissions replayed."""
        from kueue_oss_tpu import obs

        n = 0
        for ev in events:
            if ev.kind not in (obs.ASSIGNED, obs.SOLVER_ADMITTED):
                continue
            detail = ev.detail or {}
            if "waitSeconds" not in detail:
                continue
            self.observe_admission(
                ev.cluster_queue, float(detail["waitSeconds"]),
                priority=int(detail.get("priority", 0)),
                priority_class=str(detail.get("priorityClass", "")),
                now=ev.ts, cycle=ev.cycle, workload=ev.workload)
            n += 1
        return n

    def advance(self, now: float) -> None:
        """Advance the engine's logical clock (the scheduler calls
        this each cycle, including empty ones): windows roll and
        alerts can clear even when no admissions arrive."""
        if self.enabled and now > self._now:
            self._now = now

    # -- evaluation --------------------------------------------------------

    def _burn(self, total: int, bad: int) -> float:
        if total == 0:
            return 0.0
        budget = max(1e-9, 1.0 - self.target)
        return (bad / total) / budget

    def evaluate(self, now: Optional[float] = None,
                 queues=None) -> dict:
        """Walk every SLI key, update alert states + gauges, and (with
        ``queues``) refresh the starvation watchdog. Returns the
        /api/slo report."""
        with self._eval_lock:
            return self._evaluate(now, queues)

    def _evaluate(self, now: Optional[float], queues) -> dict:
        # default to the feed domain's newest instant — the dashboard
        # threads don't know the driver's clock; self.clock is only
        # the fallback before anything has been fed or advanced
        t = now if now is not None else (self._now or self.clock())
        slis = []
        with self._lock:
            keys = list(self._series.items())
            breach = dict(self._breach)
        for key, series in keys:
            scope, name = key
            # sum under the feed lock: add() writes _epoch[slot] before
            # zeroing the counts, so a lock-free sums() could pair a
            # current epoch with a stale bucket's tallies
            with self._lock:
                ft, fb = series.sums(t, self.fast_window_s)
                st, sb = series.sums(t, self.slow_window_s)
            burn_fast, burn_slow = self._burn(ft, fb), self._burn(st, sb)
            alert = self.alerts.get(key)
            if alert is None:
                alert = self.alerts[key] = Alert(scope=scope, key=name)
            alert.burn_fast, alert.burn_slow = burn_fast, burn_slow
            should_fire = (burn_fast > self.burn_threshold
                           and burn_slow > self.burn_threshold)
            recovered = burn_fast <= self.burn_threshold
            if alert.state != FIRING and should_fire:
                alert.state, alert.since = FIRING, t
                alert.exemplar = breach.get(key)
                metrics.slo_alert_transitions_total.inc(
                    scope, name, "fired")
                self._notify("fired", alert)
            elif alert.state == FIRING and recovered:
                alert.state, alert.since = CLEAR, t
                metrics.slo_alert_transitions_total.inc(
                    scope, name, "cleared")
                self._notify("cleared", alert)
            elif alert.state == FIRING:
                # keep the exemplar pointing at the newest breach while
                # the alert stays up
                alert.exemplar = breach.get(key, alert.exemplar)
            metrics.slo_burn_rate.set(scope, name, "fast",
                                      value=burn_fast)
            metrics.slo_burn_rate.set(scope, name, "slow",
                                      value=burn_slow)
            metrics.slo_alerts_firing.set(
                scope, name, value=1.0 if alert.state == FIRING else 0.0)
            slis.append({
                "scope": scope, "key": name,
                "fast": {"total": ft, "bad": fb},
                "slow": {"total": st, "bad": sb},
                "burnFast": round(burn_fast, 3),
                "burnSlow": round(burn_slow, 3),
                "alert": alert.to_dict(),
            })
        if queues is not None:
            self._starvation = self._watch_starvation(t, queues)
        return {
            "objective": self.objective(),
            "evaluatedAt": t,
            "slis": slis,
            "alerts": [a.to_dict() for a in self.alerts.values()
                       if a.state == FIRING],
            "starvation": list(self._starvation),
        }

    def objective(self) -> dict:
        return {"target": self.target,
                "thresholdSeconds": self.threshold_s,
                "fastWindowSeconds": self.fast_window_s,
                "slowWindowSeconds": self.slow_window_s,
                "burnRateThreshold": self.burn_threshold,
                "starvationThresholdSeconds": (
                    self.starvation_threshold_s)}

    def firing(self) -> list[Alert]:
        return [a for a in self.alerts.values() if a.state == FIRING]

    # -- alert sinks -------------------------------------------------------

    def add_sink(self, sink) -> None:
        """Register ``sink(transition, alert_dict)`` for fire/clear
        notifications (transition in {"fired", "cleared"})."""
        self.sinks.append(sink)

    def remove_sink(self, sink) -> None:
        if sink in self.sinks:
            self.sinks.remove(sink)

    def set_config_sink(self, sink) -> None:
        """The one config-owned sink slot (obs.configure); replacing
        or clearing it never touches programmatic registrations."""
        self._config_sink = sink

    def _notify(self, transition: str, alert: Alert) -> None:
        """Deliver one transition to every sink. Delivery runs inline
        with evaluation (transitions are rare by construction); a
        failing sink is counted, never raised — alerting must not be
        able to break the scheduler or the dashboard."""
        sinks = list(self.sinks)
        if self._config_sink is not None:
            sinks.append(self._config_sink)
        if not sinks:
            return
        payload = alert.to_dict()
        payload["transition"] = transition
        for sink in sinks:
            try:
                sink(transition, dict(payload))
                metrics.slo_alert_deliveries_total.inc("ok")
            except Exception:
                metrics.slo_alert_deliveries_total.inc("error")

    def _watch_starvation(self, now: float, queues) -> list[dict]:
        """Oldest pending age per CQ (heap + parked), newest snapshot.
        O(pending) — evaluation-time only, never per cycle."""
        out = []
        ages: dict[tuple, float] = {}
        for name, age, key in oldest_pending(queues, now):
            ages[(name,)] = age
            out.append({"clusterQueue": name,
                        "oldestAgeSeconds": round(age, 3),
                        "workload": key,
                        "starved": age > self.starvation_threshold_s})
        # replace_prefix, not per-key set: a CQ whose backlog drained
        # must report 0 once and then drop, not stay frozen at its
        # last starved age forever
        metrics.starvation_oldest_pending_seconds.replace_prefix(
            (), ages)
        out.sort(key=lambda d: -d["oldestAgeSeconds"])
        return out

    def reset(self) -> None:
        """Test helper: drop windows, alerts, and starvation state."""
        with self._lock:
            self._series.clear()
            self._breach.clear()
        self.alerts.clear()
        self._starvation = []
        self._now = 0.0


def oldest_pending(queues, now: float) -> list[tuple[str, float, str]]:
    """(cq, oldest pending age, workload key) for every CQ with any
    pending (heap or parked-inadmissible) workload. Walks the queue
    dicts under the QueueManager's mutex — evaluation runs on
    dashboard HTTP threads while the scheduler thread mutates them."""
    import contextlib

    mu = getattr(queues, "_mu", None)
    out = []
    with mu if mu is not None else contextlib.nullcontext():
        for name, q in queues.queues.items():
            oldest_t, oldest_key = None, ""
            for infos in (q._in_heap.values(), q.inadmissible.values()):
                for info in infos:
                    ct = info.obj.creation_time
                    if oldest_t is None or ct < oldest_t:
                        oldest_t, oldest_key = ct, info.key
            if oldest_t is not None:
                out.append((name, max(0.0, now - oldest_t), oldest_key))
    return out


class WebhookSink:
    """POSTs each fire/clear transition as JSON to a webhook URL.

    Delivery failures raise (the engine's _notify counts them under
    kueue_slo_alert_deliveries_total{outcome="error"}); the short
    timeout bounds how long a dead receiver can stall an evaluation.
    """

    def __init__(self, url: str, timeout_s: float = 2.0) -> None:
        self.url = url
        self.timeout_s = timeout_s

    def __call__(self, transition: str, payload: dict) -> None:
        import json
        import urllib.request

        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            r.read()


class PhaseRegressionDetector:
    """Ledger-driven regression detection over per-cycle phase walls.

    Every CycleLedger row's ``phases`` dict feeds a per-(kind, phase)
    pair of EWMAs: a slow baseline (what this phase normally costs)
    and a fast tracker (what it costs right now). After a warm-up
    sample count, ``fast > ratio * baseline`` flags the phase as
    regressing — surfaced as kueue_cycle_phase_regression{kind,phase}
    and a ``health`` signal in /api/health. The baseline keeps
    adapting slowly, so a permanent plan change (bigger store, new
    hardware) re-baselines instead of alerting forever; a sudden 2x+
    jump (lock contention, a pathological snapshot, GC storms) fires
    within a handful of cycles.
    """

    def __init__(self, *, ratio: float = 2.0, min_samples: int = 20,
                 fast_alpha: float = 0.3,
                 slow_alpha: float = 0.02) -> None:
        self.enabled = True
        self.ratio = ratio
        self.min_samples = min_samples
        self.fast_alpha = fast_alpha
        self.slow_alpha = slow_alpha
        self._lock = threading.Lock()
        #: (kind, phase) -> [fast_ewma, slow_ewma, samples, regressing]
        self._state: dict[tuple[str, str], list] = {}

    def feed(self, kind: str, phases: dict) -> None:
        if not self.enabled or not phases:
            return
        with self._lock:
            for phase, wall in phases.items():
                try:
                    w = float(wall)
                except (TypeError, ValueError):
                    continue
                st = self._state.get((kind, phase))
                if st is None:
                    st = self._state[(kind, phase)] = [w, w, 0, False]
                st[0] += self.fast_alpha * (w - st[0])
                st[1] += self.slow_alpha * (w - st[1])
                st[2] += 1
                ratio = st[0] / st[1] if st[1] > 0 else 1.0
                was = st[3]
                st[3] = (st[2] >= self.min_samples
                         and ratio > self.ratio)
                metrics.cycle_phase_regression_ratio.set(
                    kind, phase, value=ratio)
                if st[3] != was:
                    metrics.cycle_phase_regression.set(
                        kind, phase, value=1.0 if st[3] else 0.0)

    def regressing(self) -> list[dict]:
        """Currently regressing phases (the /api/health signal)."""
        with self._lock:
            return [{"kind": k, "phase": p,
                     "fastSeconds": round(st[0], 6),
                     "baselineSeconds": round(st[1], 6),
                     "ratio": round(st[0] / st[1], 3) if st[1] > 0
                     else 1.0}
                    for (k, p), st in self._state.items() if st[3]]

    def reset(self) -> None:
        with self._lock:
            self._state.clear()


#: process-wide engine (the obs.recorder idiom); obs.configure() swaps
#: its objectives in from an ObservabilityConfig
slo = SLOEngine()

#: process-wide phase-regression detector, fed by CycleLedger.record
phase_regression = PhaseRegressionDetector()
