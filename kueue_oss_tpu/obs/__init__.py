"""Admission flight recorder: per-workload decision traces.

The hardest operational question for the reference Kueue is "why is my
job still pending?" — the answer is scattered across events, conditions
and logs, and the TPU solver path adds a second, opaque decision-maker.
This subsystem stitches the raw signals into an answer: a bounded,
thread-safe journal of one structured ``DecisionEvent`` per per-workload
outcome per cycle, tagged with the cycle id, the deciding path (host
cycle loop vs solver drain) and the solver breaker state at decision
time (Gavel, arXiv:2008.09213, and arXiv:2512.10980 both treat per-job
placement *reasons* as the primary debugging/fairness-audit artifact).

Surfaces:

- ``recorder.explain(key)`` — a workload's event history, newest-first
  (the dashboard's ``/api/workloads/<ns>/<name>/explain``);
- ``recorder.decisions(last_cycles=N)`` — the last N cycles' events
  (``/api/decisions``);
- ``recorder.dump_jsonl(path)`` / ``load_jsonl(path)`` — an offline
  journal for ``tools/explain.py``;
- every ``record()`` also bumps ``kueue_decision_events_total{kind}``
  and, for skips, ``kueue_decision_skips_total{reason}`` (the reason
  label is a bounded SLUG, never the free-form message).

The global ring keeps the newest ``max_events`` events (an operator
debugging a stall needs recent activity, not warm-up); a per-workload
side index keeps each workload's newest ``per_workload`` events even
after the ring has rotated past them, so ``explain`` stays useful for
long-pending workloads in a busy cluster.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu import metrics

logger = logging.getLogger(__name__)

# -- event kinds (the per-workload outcome vocabulary) ----------------------

NOMINATED = "nominated"          # entered the cycle; outcome still pending
ASSIGNED = "assigned"            # quota reserved by the host cycle
SKIPPED = "skipped"              # left the cycle unadmitted, with a reason
PREEMPTED = "preempted"          # evicted to make room for another workload
EVICTED = "evicted"              # evicted for a non-preemption reason
SOLVER_ADMITTED = "solver-admitted"  # quota reserved by the solver plan
SOLVER_FALLBACK = "solver-fallback"  # solver path degraded to the host path
DEGRADATION = "degradation"          # a degradation-ladder transition

KINDS = (NOMINATED, ASSIGNED, SKIPPED, PREEMPTED, EVICTED,
         SOLVER_ADMITTED, SOLVER_FALLBACK, DEGRADATION)

# -- decision paths ---------------------------------------------------------

HOST = "host"
SOLVER = "solver"
#: streaming micro-batched admission fast path (scheduler/streaming.py)
STREAM = "stream"

#: placeholder workload key for cycle-level events (e.g. a whole drain
#: degrading because the breaker is open) that belong to no one workload
CYCLE_SCOPE = "-"

_BREAKER_NAMES = {0.0: "closed", 1.0: "half-open", 2.0: "open"}


def breaker_state_name() -> str:
    """Current solver breaker state as a name, read from the gauge the
    resilience layer maintains (shared by the recorder's event tags and
    the dashboard's solver view — one mapping, not two)."""
    return _BREAKER_NAMES.get(
        metrics.solver_breaker_state.value(), "closed")


@dataclass
class DecisionEvent:
    """One per-workload outcome. ``reason`` is the human-readable
    explanation (the flavor assigner's no-fit message survives here
    verbatim); ``reason_slug`` is the bounded label used for the
    per-reason skip counters."""

    seq: int
    ts: float
    cycle: int
    kind: str
    workload: str
    cluster_queue: str = ""
    path: str = HOST
    reason: str = ""
    reason_slug: str = ""
    breaker: str = "closed"
    detail: Optional[dict] = field(default=None)

    def to_dict(self) -> dict:
        d = {
            "seq": self.seq, "ts": self.ts, "cycle": self.cycle,
            "kind": self.kind, "workload": self.workload,
            "clusterQueue": self.cluster_queue, "path": self.path,
            "reason": self.reason, "reasonSlug": self.reason_slug,
            "breaker": self.breaker,
        }
        if self.detail:
            d["detail"] = self.detail
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "DecisionEvent":
        return cls(seq=int(d.get("seq", 0)), ts=float(d.get("ts", 0.0)),
                   cycle=int(d.get("cycle", 0)),
                   kind=str(d.get("kind", "")),
                   workload=str(d.get("workload", "")),
                   cluster_queue=str(d.get("clusterQueue", "")),
                   path=str(d.get("path", HOST)),
                   reason=str(d.get("reason", "")),
                   reason_slug=str(d.get("reasonSlug", "")),
                   breaker=str(d.get("breaker", "closed")),
                   detail=d.get("detail"))


class FlightRecorder:
    """Bounded, thread-safe decision journal.

    ``record()`` is called from the scheduler cycle, the solver apply
    path, and eviction flows — possibly from different threads (the
    serve loop vs controller callbacks), so every mutation holds the
    lock. Recording is cheap (one dataclass + two deque appends + a
    counter inc); ``enabled = False`` reduces it to one attribute read.
    """

    def __init__(self, max_events: int = 65_536, per_workload: int = 64,
                 max_workloads: int = 100_000,
                 clock=time.time) -> None:
        self.enabled = True
        self.max_events = max_events
        self.per_workload = per_workload
        self.max_workloads = max_workloads
        self.clock = clock
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._ring: deque[DecisionEvent] = deque(maxlen=max_events)
        #: workload key -> its newest events (LRU-bounded so a stream of
        #: one-shot workloads cannot grow the index without limit)
        self._by_workload: OrderedDict[str, deque] = OrderedDict()

    # -- emission ----------------------------------------------------------

    def record(self, kind: str, workload: str, *, cycle: int = 0,
               cluster_queue: str = "", path: str = HOST,
               reason: str = "", reason_slug: str = "",
               detail: Optional[dict] = None,
               breaker: Optional[str] = None) -> Optional[DecisionEvent]:
        """``breaker`` defaults to the LIVE breaker state; the journal
        replay layer passes the recorded value through so a replayed
        incident keeps its breaker tags."""
        if not self.enabled:
            return None
        if breaker is None:
            breaker = breaker_state_name()
        ev = DecisionEvent(
            seq=next(self._seq), ts=self.clock(), cycle=cycle, kind=kind,
            workload=workload, cluster_queue=cluster_queue, path=path,
            reason=reason, reason_slug=reason_slug, breaker=breaker,
            detail=detail)
        with self._lock:
            self._ring.append(ev)
            if workload != CYCLE_SCOPE:
                dq = self._by_workload.get(workload)
                if dq is None:
                    dq = deque(maxlen=self.per_workload)
                    self._by_workload[workload] = dq
                    if len(self._by_workload) > self.max_workloads:
                        self._by_workload.popitem(last=False)
                else:
                    self._by_workload.move_to_end(workload)
                dq.append(ev)
        metrics.decision_events_total.inc(kind)
        if kind in (SKIPPED, SOLVER_FALLBACK) and reason_slug:
            metrics.decision_skips_total.inc(reason_slug)
        return ev

    # -- queries -----------------------------------------------------------

    def explain(self, workload: str) -> list[DecisionEvent]:
        """The workload's event history, newest-first."""
        with self._lock:
            dq = self._by_workload.get(workload)
            return list(reversed(dq)) if dq else []

    def events(self) -> list[DecisionEvent]:
        """Ring snapshot, oldest-first."""
        with self._lock:
            return list(self._ring)

    def decisions(self, last_cycles: int = 10) -> list[dict]:
        """The last N distinct cycles' events, newest cycle first.

        Host and solver events sharing a cycle id land in the same
        group — the merged per-cycle view is the point."""
        with self._lock:
            snapshot = list(self._ring)
        groups: dict[int, list[DecisionEvent]] = {}
        for ev in snapshot:
            groups.setdefault(ev.cycle, []).append(ev)
        cycles = sorted(groups, reverse=True)[:max(0, last_cycles)]
        return [{"cycle": c,
                 "events": [ev.to_dict() for ev in groups[c]]}
                for c in cycles]

    # -- journal dump / load ----------------------------------------------

    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(ev.to_dict())
                         for ev in self.events()) + "\n"

    def dump_jsonl(self, path: str) -> int:
        """Atomically write the journal: a crash mid-dump must never
        leave a half-written file where a previous complete journal
        stood (replay/simulation consume these dumps). The write goes
        to a same-directory temp file, lands via ``os.replace``, and
        the directory is fsynced too — an fsynced file behind an
        un-fsynced rename is not durable across power loss (the same
        discipline as the persist/ checkpoint writer)."""
        events = self.events()
        _atomic_write_jsonl(path, (ev.to_dict() for ev in events))
        return len(events)

    def restore(self, events: list[DecisionEvent]) -> int:
        """Replace the journal with a persisted dump (the recovery
        path, docs/DURABILITY.md): the ring, the per-workload index,
        and the seq counter all continue from the restored state so
        post-restart events keep a monotone journal order."""
        with self._lock:
            self._ring.clear()
            self._by_workload.clear()
            top = 0
            for ev in events[-self.max_events:]:
                self._ring.append(ev)
                top = max(top, ev.seq)
                if ev.workload == CYCLE_SCOPE:
                    continue
                dq = self._by_workload.get(ev.workload)
                if dq is None:
                    dq = deque(maxlen=self.per_workload)
                    self._by_workload[ev.workload] = dq
                    if len(self._by_workload) > self.max_workloads:
                        self._by_workload.popitem(last=False)
                else:
                    self._by_workload.move_to_end(ev.workload)
                dq.append(ev)
            self._seq = itertools.count(top + 1)
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._by_workload.clear()


def _atomic_write_jsonl(path: str, dicts) -> None:
    """Shared durable-JSONL writer: same-directory temp file, fsync,
    ``os.replace``, directory fsync (the checkpoint writer's
    discipline — used by both the decision journal and the ledger)."""
    from kueue_oss_tpu.util.fsutil import fsync_dir

    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            for d in dicts:
                f.write(json.dumps(d) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(os.path.dirname(os.path.abspath(path)))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_jsonl(path: str) -> list[DecisionEvent]:
    """Load a journal dump written by ``dump_jsonl`` (tools/explain.py's
    and the sim replay layer's offline input).

    Blank lines are skipped. Torn or corrupt lines (a journal written
    by a pre-atomic dump that crashed mid-write, or one truncated in
    transit) are SKIPPED with one counted warning instead of raising:
    a damaged tail must not poison replay of the millions of intact
    events before it. The skip count of the MOST RECENT call is kept
    on the function as ``load_jsonl.last_skipped`` — best-effort
    module-level state (concurrent loads race on it); a diagnostic,
    not an API."""
    out, skipped = _tolerant_load_jsonl(path, DecisionEvent.from_dict,
                                        "journal")
    load_jsonl.last_skipped = skipped
    return out


load_jsonl.last_skipped = 0


def _tolerant_load_jsonl(path: str, parse, label: str
                         ) -> tuple[list, int]:
    """Shared tolerant JSONL reader (the decision journal's and the
    cycle ledger's one torn-line policy): blank lines skipped, corrupt
    lines skipped with one counted warning. Returns (rows, skipped)."""
    out = []
    skipped = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
                if not isinstance(d, dict):
                    raise ValueError(f"{label} line is not an object")
                out.append(parse(d))
            except (ValueError, TypeError, KeyError):
                skipped += 1
                if skipped == 1:
                    logger.warning(
                        "%s %s: skipping corrupt line %d "
                        "(torn write?)", label, path, lineno)
    if skipped > 1:
        logger.warning("%s %s: skipped %d corrupt line(s) total",
                       label, path, skipped)
    return out, skipped


#: process-wide recorder (the metrics.registry idiom); tests swap or
#: clear() it via the autouse fixture
recorder = FlightRecorder()

# -- cluster health layer (ledger + SLO engine; imported AFTER the
# recorder exists — both modules may import this package lazily) ------------

from kueue_oss_tpu.obs.health import (  # noqa: E402
    SLOEngine,
    oldest_pending,
)
from kueue_oss_tpu.obs.health import slo as slo_engine  # noqa: E402
from kueue_oss_tpu.obs.health import (  # noqa: E402
    WebhookSink,
    priority_class_of,
)
from kueue_oss_tpu.obs.health import (  # noqa: E402
    phase_regression as phase_regression,
)
from kueue_oss_tpu.obs.ledger import (  # noqa: E402
    DEGRADATION_ROW,
    HOST_CYCLE,
    SOLVER_DRAIN,
    STREAM_DRAIN,
    CycleLedger,
    CycleRecord,
    load_ledger_jsonl,
)
from kueue_oss_tpu.obs.ledger import ledger as cycle_ledger  # noqa: E402
from kueue_oss_tpu.obs import devtel  # noqa: E402
from kueue_oss_tpu.obs.devtel import (  # noqa: E402
    CompileDetector,
    DeepCapture,
    DeviceTelemetry,
)
from kueue_oss_tpu.obs.devtel import collector as device_telemetry  # noqa: E402


def configure(obs_cfg, capture_dir=None) -> None:
    """Apply a config.ObservabilityConfig to the process-wide obs
    state: the recorder/ledger switches and bounds, the metrics
    exemplar switch, the SLO engine's objectives (windows and alert
    state reset — a reconfigured objective starts clean), and the
    device-telemetry collector. ``capture_dir`` defaults devtel's
    deep-capture artifacts beside the checkpoints (callers pass
    ``cfg.persistence.dir``)."""
    recorder.enabled = obs_cfg.recorder_enabled
    cycle_ledger.enabled = obs_cfg.ledger_enabled
    if obs_cfg.ledger_max_cycles != cycle_ledger.max_cycles:
        cycle_ledger.resize(obs_cfg.ledger_max_cycles)
    metrics.exemplars_enabled = obs_cfg.exemplars
    s = obs_cfg.slo
    slo_engine.enabled = obs_cfg.slo_enabled
    slo_engine.reconfigure(
        target=s.queue_wait_target,
        threshold_s=s.queue_wait_threshold_seconds,
        fast_window_s=s.fast_window_seconds,
        slow_window_s=s.slow_window_seconds,
        burn_threshold=s.burn_rate_threshold,
        starvation_threshold_s=s.starvation_threshold_seconds)
    # alert sinks: a configured webhook replaces any previously
    # config-wired one (programmatic add_sink registrations persist)
    slo_engine.set_config_sink(
        WebhookSink(s.alert_webhook_url,
                    timeout_s=s.alert_webhook_timeout_seconds)
        if s.alert_webhook_url else None)
    devtel.collector.configure(obs_cfg.devtel, capture_dir=capture_dir)
