"""kueue_oss_tpu — a TPU-native job-queueing & admission framework.

Capabilities mirror the reference (hiboyang/kueue_oss, a Kueue fork):
hierarchical quota over ClusterQueues/Cohorts with borrowing/lending limits,
flavor-fungible admission, fair sharing (dominant resource share), priority and
fair-sharing preemption, topology-aware placement, two-phase admission checks,
and the surrounding queueing control plane.

The defining difference: the per-cycle scheduling core (flavor assignment,
cohort quota algebra, fair-sharing math, preemption search) is expressed twice:

- ``kueue_oss_tpu.core`` / ``kueue_oss_tpu.scheduler``: a scalar Python
  "oracle" implementation mirroring the reference semantics exactly
  (used as correctness reference and fallback path), and
- ``kueue_oss_tpu.solver``: a batched, jitted JAX/Pallas implementation over
  dense [node x flavor-resource] tensors that solves whole scheduling cycles
  on TPU, sharded over a ``jax.sharding.Mesh`` for multi-chip scale.
"""

__version__ = "0.1.0"
