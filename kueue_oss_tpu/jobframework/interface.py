"""The GenericJob contract.

Reference parity: pkg/controller/jobframework/interface.go:40-64 — Object,
IsSuspended, Suspend, RunWithPodSetsInfo, RestorePodSetsInfo, Finished,
PodSets, IsActive, PodsReady, GVK — plus the podset.PodSetInfo carrier
(pkg/podset) used to inject flavor node-selectors and scheduling gates.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Optional

from kueue_oss_tpu.api.types import PodSet, Toleration


class StopReason:
    """Reference parity: interface.go StopReason values."""

    WORKLOAD_DELETED = "WorkloadDeleted"
    WORKLOAD_EVICTED = "WorkloadEvicted"
    NO_MATCHING_WORKLOAD = "NoMatchingWorkload"
    NOT_ADMITTED = "NotAdmitted"


@dataclass
class PodSetInfo:
    """What admission injects into a job's podset before it runs.

    Reference parity: pkg/podset/podset.go PodSetInfo {NodeSelector,
    Tolerations, Labels, Annotations, SchedulingGates, Count}.
    """

    name: str = "main"
    count: int = 0
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    #: scheduling gates to place on pods (TAS topology ungating)
    scheduling_gates: list[str] = field(default_factory=list)


class GenericJob(abc.ABC):
    """Every integration implements this (interface.go:40-64)."""

    kind: str = ""

    @property
    @abc.abstractmethod
    def key(self) -> str:
        """'namespace/name' identity."""

    @abc.abstractmethod
    def is_suspended(self) -> bool: ...

    @abc.abstractmethod
    def do_suspend(self) -> None: ...

    @abc.abstractmethod
    def run_with_podsets_info(self, infos: list[PodSetInfo]) -> None:
        """Inject node selectors / counts and unsuspend."""

    @abc.abstractmethod
    def restore_podsets_info(self, infos: list[PodSetInfo]) -> bool:
        """Restore original podset templates; True if anything changed."""

    @abc.abstractmethod
    def finished(self) -> tuple[str, bool, bool]:
        """(message, success, finished)."""

    @abc.abstractmethod
    def pod_sets(self) -> list[PodSet]:
        """Workload podsets corresponding to the job."""

    @abc.abstractmethod
    def is_active(self) -> bool:
        """True if any pods are running."""

    @abc.abstractmethod
    def pods_ready(self) -> bool: ...


@dataclass
class BaseJob(GenericJob):
    """Common state shared by the concrete integrations.

    Concrete jobs supply `kind` and `pod_sets()`; suspension, podset-info
    injection/restore and finish bookkeeping live here so each integration
    is just its podset shape (mirrors how the reference integrations lean
    on jobframework helpers).
    """

    name: str = ""
    namespace: str = "default"
    #: kueue.x-k8s.io/queue-name label on the reference
    queue_name: str = ""
    #: spec.managedBy (JobWithManagedBy): a job delegated to the
    #: MultiKueue controller runs on a WORKER cluster; the local
    #: reconciler must never unsuspend it (job_multikueue_adapter.go)
    managed_by: Optional[str] = None
    suspend: bool = True
    priority_class: Optional[str] = None
    priority: int = 0
    max_execution_time: Optional[float] = None
    creation_time: float = 0.0
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)

    # runtime status (maintained by the simulator / tests)
    active_pods: int = 0
    ready_pods: int = 0
    is_finished: bool = False
    finish_success: bool = True
    finish_message: str = ""

    #: podset infos injected at admission (None = not running under kueue)
    injected: Optional[list[PodSetInfo]] = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_suspended(self) -> bool:
        return self.suspend

    def do_suspend(self) -> None:
        self.suspend = True
        self.active_pods = 0
        self.ready_pods = 0

    def run_with_podsets_info(self, infos: list[PodSetInfo]) -> None:
        self.injected = infos
        self.suspend = False

    def restore_podsets_info(self, infos: list[PodSetInfo]) -> bool:
        changed = self.injected is not None
        self.injected = None
        return changed

    def finished(self) -> tuple[str, bool, bool]:
        return self.finish_message, self.finish_success, self.is_finished

    def pod_sets(self) -> list[PodSet]:  # pragma: no cover - abstract-ish
        raise NotImplementedError

    def is_active(self) -> bool:
        return self.active_pods > 0

    def pods_ready(self) -> bool:
        total = sum(ps.count for ps in self.pod_sets())
        return self.ready_pods >= total

    # -- test/simulator helpers -------------------------------------------

    def mark_running(self, ready: bool = True) -> None:
        total = sum(ps.count for ps in self.pod_sets())
        self.active_pods = total
        self.ready_pods = total if ready else 0

    def mark_finished(self, success: bool = True, message: str = "") -> None:
        self.is_finished = True
        self.finish_success = success
        self.finish_message = message or ("JobFinished" if success else "JobFailed")
        self.active_pods = 0
