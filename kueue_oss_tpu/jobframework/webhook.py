"""Job admission webhooks.

Reference parity: pkg/controller/jobframework/base_webhook.go (suspend on
create when managed) + validation.go (queue-name immutability while
admitted/running).
"""

from __future__ import annotations

import re

from kueue_oss_tpu.jobframework.interface import GenericJob


class JobWebhookError(ValueError):
    pass


def default_job(job: GenericJob,
                manage_jobs_without_queue_name: bool = False,
                store=None) -> None:
    """Mutating webhook: a managed job is created suspended so kueue
    controls its start (base_webhook.go Default). Under the
    LocalQueueDefaulting gate (GA), a job with no queue-name label in a
    namespace that has a LocalQueue named "default" is defaulted onto
    it (localqueue_defaulting webhook)."""
    from kueue_oss_tpu import features

    if (not job.queue_name and store is not None
            and features.enabled("LocalQueueDefaulting")
            and f"{job.namespace}/default" in store.local_queues):
        job.queue_name = "default"
    if job.queue_name or manage_jobs_without_queue_name:
        if not job.is_suspended():
            job.do_suspend()


#: same constraint as Job spec.managedBy (validation_admissiongatedby.go)
_MAX_GATE_NAME_LEN = 63
_NAME_PART_RE = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")


def is_qualified_name(value: str) -> bool:
    """k8s qualified name: optional `prefix/` (DNS subdomain, <=253)
    plus a name part (<=63) — metavalidation.ValidateLabelName's shape
    and length rules, shared by the topology-level checks."""
    prefix, sep, name = value.rpartition("/")
    if sep and (not prefix or len(prefix) > 253
                or not _NAME_PART_RE.match(prefix)):
        return False
    return bool(name) and len(name) <= 63 and bool(
        _NAME_PART_RE.match(name))


def is_domain_prefixed_path(value: str) -> bool:
    """validation.IsDomainPrefixedPath: a REQUIRED `prefix/name` form
    with a DNS-subdomain prefix. Admission gate names use this (the
    reference's validation_admissiongatedby.go), so bare names like
    'mygate' are rejected; topology label names keep the
    prefix-optional qualified-name rules above."""
    prefix, sep, name = value.partition("/")
    if not sep or not prefix or not name:
        return False
    if len(prefix) > 253 or not _NAME_PART_RE.match(prefix):
        return False
    return is_qualified_name(name)


def _gated_by(job) -> str:
    from kueue_oss_tpu.jobframework.reconciler import (
        ADMISSION_GATED_BY_ANNOTATION,
    )

    return (getattr(job, "annotations", {}) or {}).get(
        ADMISSION_GATED_BY_ANNOTATION, "")


def _validate_gated_by_format(value: str) -> list[str]:
    """validation_admissiongatedby.go:90-130 — CSV of qualified gate
    names, each non-empty, unique, and at most 63 chars."""
    if not value:
        return []
    errs: list[str] = []
    seen: set[str] = set()
    for gate in [g.strip() for g in value.split(",")]:
        if not gate:
            errs.append("admission-gated-by: cannot contain empty gate "
                        "names")
            continue
        if gate in seen:
            errs.append(f"admission-gated-by: duplicate gate {gate!r}")
        seen.add(gate)
        if len(gate) > _MAX_GATE_NAME_LEN:
            errs.append(f"admission-gated-by: gate {gate!r} exceeds "
                        f"{_MAX_GATE_NAME_LEN} chars")
        elif not is_domain_prefixed_path(gate):
            errs.append(f"admission-gated-by: gate {gate!r} is not a "
                        "domain-prefixed path (want 'prefix/name')")
    return errs


def validate_admission_gated_by_update(old, new) -> list[str]:
    """validation_admissiongatedby.go:45-88 — the annotation cannot be
    added after creation, and gates may only be removed."""
    old_val, new_val = _gated_by(old), _gated_by(new)
    errs: list[str] = []
    if not old_val and new_val:
        errs.append("admission-gated-by: cannot add admission gate "
                    "after creation")
    if old_val and new_val:
        old_gates = [g.strip() for g in old_val.split(",")]
        for gate in [g.strip() for g in new_val.split(",")]:
            if gate not in old_gates:
                errs.append("admission-gated-by: can only remove gates, "
                            "not add new ones")
                break
    errs.extend(_validate_gated_by_format(new_val))
    return errs


def validate_tas_podset_request(ps) -> list[str]:
    """Shared TAS topology-request validation
    (jobframework/tas_validation.go ValidateTASPodSetRequest): at most
    one topology mode; level values are label names; slice topology
    and slice size come as a pair; a podset group excludes slices and
    needs a required/preferred mode."""
    tr = ps.topology_request
    if tr is None:
        return []
    p = f"podset {ps.name}"
    errs: list[str] = []
    modes = ((tr.required is not None) + (tr.preferred is not None)
             + (1 if tr.unconstrained else 0))
    if modes > 1:
        errs.append(f"{p}: must not contain more than one topology "
                    "annotation (required, preferred, unconstrained)")
    for what, val in (("required", tr.required),
                      ("preferred", tr.preferred),
                      ("slice required",
                       tr.podset_slice_required_topology)):
        if val is not None and not is_qualified_name(val):
            errs.append(f"{p}: {what} topology {val!r} is not a valid "
                        "label name")
    # nested multi-layer slice constraints (KEP multi-layer topology):
    # each layer needs a valid level label and a positive size — a zero
    # size would divide-by-zero in the scheduler's slice roll-up
    for i, layer in enumerate(tr.podset_slice_constraints):
        if not is_qualified_name(layer.topology):
            errs.append(f"{p}: slice constraint [{i}] topology "
                        f"{layer.topology!r} is not a valid label name")
        if layer.size <= 0:
            errs.append(f"{p}: slice constraint [{i}] size must be a "
                        "positive integer")
    if (tr.podset_slice_required_topology is not None
            and tr.podset_slice_size is None):
        errs.append(f"{p}: slice size must be set when slice topology "
                    "is specified")
    if (tr.podset_slice_size is not None
            and tr.podset_slice_required_topology is None):
        errs.append(f"{p}: slice size may not be set without slice "
                    "topology")
    if tr.podset_slice_size is not None and tr.podset_slice_size <= 0:
        errs.append(f"{p}: slice size must be a positive integer")
    if tr.podset_group_name is not None:
        if tr.podset_slice_size is not None or (
                tr.podset_slice_required_topology is not None):
            errs.append(f"{p}: podset group may not be combined with "
                        "slice topology")
        if tr.required is None and tr.preferred is None:
            errs.append(f"{p}: podset group requires a required or "
                        "preferred topology")
    return errs


def validate_job_create(job: GenericJob) -> list[str]:
    from kueue_oss_tpu import features

    errs = []
    seen_ps: set[str] = set()
    for ps in job.pod_sets():
        if ps.name in seen_ps:
            errs.append(f"podset {ps.name}: duplicate podset name")
        seen_ps.add(ps.name)
        if ps.count < 0:
            errs.append(f"podset {ps.name}: negative count")
        if ps.min_count is not None and not 0 < ps.min_count <= ps.count:
            errs.append(f"podset {ps.name}: minCount must be in (0, count]")
        for r, q in ps.requests.items():
            if q < 0:
                errs.append(f"podset {ps.name}: negative request {r}")
        if features.enabled("TopologyAwareScheduling"):
            errs.extend(validate_tas_podset_request(ps))
    if features.enabled("AdmissionGatedBy"):
        errs.extend(_validate_gated_by_format(_gated_by(job)))
    # per-framework rules (the reference's *_webhook.go ValidateCreate
    # bodies); an integration opts in by defining validate()
    custom = getattr(job, "validate", None)
    if callable(custom):
        errs.extend(custom())
    return errs


def validate_job_update(old: GenericJob, new: GenericJob) -> list[str]:
    """queue-name is immutable while the job is unsuspended
    (validation.go ValidateJobOnUpdate)."""
    from kueue_oss_tpu import features

    errs = validate_job_create(new)
    if old.queue_name != new.queue_name and not old.is_suspended():
        errs.append("queueName is immutable while the job is running")
    if features.enabled("AdmissionGatedBy"):
        errs.extend(e for e in validate_admission_gated_by_update(old, new)
                    if e not in errs)
    # per-framework update rules (the reference's *_webhook.go
    # ValidateUpdate bodies beyond the shared queue-name check)
    custom = getattr(new, "validate_update", None)
    if callable(custom):
        errs.extend(custom(old))
    return errs
