"""Job admission webhooks.

Reference parity: pkg/controller/jobframework/base_webhook.go (suspend on
create when managed) + validation.go (queue-name immutability while
admitted/running).
"""

from __future__ import annotations

import re

from kueue_oss_tpu.jobframework.interface import GenericJob


class JobWebhookError(ValueError):
    pass


def default_job(job: GenericJob,
                manage_jobs_without_queue_name: bool = False,
                store=None) -> None:
    """Mutating webhook: a managed job is created suspended so kueue
    controls its start (base_webhook.go Default). Under the
    LocalQueueDefaulting gate (GA), a job with no queue-name label in a
    namespace that has a LocalQueue named "default" is defaulted onto
    it (localqueue_defaulting webhook)."""
    from kueue_oss_tpu import features

    if (not job.queue_name and store is not None
            and features.enabled("LocalQueueDefaulting")
            and f"{job.namespace}/default" in store.local_queues):
        job.queue_name = "default"
    if job.queue_name or manage_jobs_without_queue_name:
        if not job.is_suspended():
            job.do_suspend()


#: same constraint as Job spec.managedBy (validation_admissiongatedby.go)
_MAX_GATE_NAME_LEN = 63
_GATE_NAME_RE = re.compile(
    r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?(/[A-Za-z0-9]"
    r"([-A-Za-z0-9_.]*[A-Za-z0-9])?)?$")


def _gated_by(job) -> str:
    from kueue_oss_tpu.jobframework.reconciler import (
        ADMISSION_GATED_BY_ANNOTATION,
    )

    return (getattr(job, "annotations", {}) or {}).get(
        ADMISSION_GATED_BY_ANNOTATION, "")


def _validate_gated_by_format(value: str) -> list[str]:
    """validation_admissiongatedby.go:90-130 — CSV of qualified gate
    names, each non-empty, unique, and at most 63 chars."""
    if not value:
        return []
    errs: list[str] = []
    seen: set[str] = set()
    for gate in [g.strip() for g in value.split(",")]:
        if not gate:
            errs.append("admission-gated-by: cannot contain empty gate "
                        "names")
            continue
        if gate in seen:
            errs.append(f"admission-gated-by: duplicate gate {gate!r}")
        seen.add(gate)
        if len(gate) > _MAX_GATE_NAME_LEN:
            errs.append(f"admission-gated-by: gate {gate!r} exceeds "
                        f"{_MAX_GATE_NAME_LEN} chars")
        elif not _GATE_NAME_RE.match(gate):
            errs.append(f"admission-gated-by: gate {gate!r} is not a "
                        "qualified name")
    return errs


def validate_admission_gated_by_update(old, new) -> list[str]:
    """validation_admissiongatedby.go:45-88 — the annotation cannot be
    added after creation, and gates may only be removed."""
    old_val, new_val = _gated_by(old), _gated_by(new)
    errs: list[str] = []
    if not old_val and new_val:
        errs.append("admission-gated-by: cannot add admission gate "
                    "after creation")
    if old_val and new_val:
        old_gates = [g.strip() for g in old_val.split(",")]
        for gate in [g.strip() for g in new_val.split(",")]:
            if gate not in old_gates:
                errs.append("admission-gated-by: can only remove gates, "
                            "not add new ones")
                break
    errs.extend(_validate_gated_by_format(new_val))
    return errs


def validate_job_create(job: GenericJob) -> list[str]:
    from kueue_oss_tpu import features

    errs = []
    seen_ps: set[str] = set()
    for ps in job.pod_sets():
        if ps.name in seen_ps:
            errs.append(f"podset {ps.name}: duplicate podset name")
        seen_ps.add(ps.name)
        if ps.count < 0:
            errs.append(f"podset {ps.name}: negative count")
        if ps.min_count is not None and not 0 < ps.min_count <= ps.count:
            errs.append(f"podset {ps.name}: minCount must be in (0, count]")
        for r, q in ps.requests.items():
            if q < 0:
                errs.append(f"podset {ps.name}: negative request {r}")
    if features.enabled("AdmissionGatedBy"):
        errs.extend(_validate_gated_by_format(_gated_by(job)))
    # per-framework rules (the reference's *_webhook.go ValidateCreate
    # bodies); an integration opts in by defining validate()
    custom = getattr(job, "validate", None)
    if callable(custom):
        errs.extend(custom())
    return errs


def validate_job_update(old: GenericJob, new: GenericJob) -> list[str]:
    """queue-name is immutable while the job is unsuspended
    (validation.go ValidateJobOnUpdate)."""
    from kueue_oss_tpu import features

    errs = validate_job_create(new)
    if old.queue_name != new.queue_name and not old.is_suspended():
        errs.append("queueName is immutable while the job is running")
    if features.enabled("AdmissionGatedBy"):
        errs.extend(e for e in validate_admission_gated_by_update(old, new)
                    if e not in errs)
    # per-framework update rules (the reference's *_webhook.go
    # ValidateUpdate bodies beyond the shared queue-name check)
    custom = getattr(new, "validate_update", None)
    if callable(custom):
        errs.extend(custom(old))
    return errs
