"""Job admission webhooks.

Reference parity: pkg/controller/jobframework/base_webhook.go (suspend on
create when managed) + validation.go (queue-name immutability while
admitted/running).
"""

from __future__ import annotations

from kueue_oss_tpu.jobframework.interface import GenericJob


class JobWebhookError(ValueError):
    pass


def default_job(job: GenericJob,
                manage_jobs_without_queue_name: bool = False,
                store=None) -> None:
    """Mutating webhook: a managed job is created suspended so kueue
    controls its start (base_webhook.go Default). Under the
    LocalQueueDefaulting gate (GA), a job with no queue-name label in a
    namespace that has a LocalQueue named "default" is defaulted onto
    it (localqueue_defaulting webhook)."""
    from kueue_oss_tpu import features

    if (not job.queue_name and store is not None
            and features.enabled("LocalQueueDefaulting")
            and f"{job.namespace}/default" in store.local_queues):
        job.queue_name = "default"
    if job.queue_name or manage_jobs_without_queue_name:
        if not job.is_suspended():
            job.do_suspend()


def validate_job_create(job: GenericJob) -> list[str]:
    errs = []
    for ps in job.pod_sets():
        if ps.count < 0:
            errs.append(f"podset {ps.name}: negative count")
        if ps.min_count is not None and not 0 < ps.min_count <= ps.count:
            errs.append(f"podset {ps.name}: minCount must be in (0, count]")
        for r, q in ps.requests.items():
            if q < 0:
                errs.append(f"podset {ps.name}: negative request {r}")
    return errs


def validate_job_update(old: GenericJob, new: GenericJob) -> list[str]:
    """queue-name is immutable while the job is unsuspended
    (validation.go ValidateJobOnUpdate)."""
    errs = validate_job_create(new)
    if old.queue_name != new.queue_name and not old.is_suspended():
        errs.append("queueName is immutable while the job is running")
    return errs
