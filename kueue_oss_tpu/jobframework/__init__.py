"""Job integration framework.

Reference parity: pkg/controller/jobframework — the GenericJob contract
(interface.go:40-64), the generic reconciler (reconciler.go:281
ReconcileGenericJob), the integration registry (integrationmanager.go) and
the suspend-on-create base webhook (base_webhook.go).
"""

from kueue_oss_tpu.jobframework.interface import (
    BaseJob,
    GenericJob,
    PodSetInfo,
    StopReason,
)
from kueue_oss_tpu.jobframework.registry import (
    IntegrationManager,
    integration_manager,
)
from kueue_oss_tpu.jobframework.reconciler import JobReconciler
from kueue_oss_tpu.jobframework.webhook import (
    JobWebhookError,
    default_job,
    validate_job_create,
    validate_job_update,
)

__all__ = [
    "BaseJob",
    "GenericJob",
    "PodSetInfo",
    "StopReason",
    "IntegrationManager",
    "integration_manager",
    "JobReconciler",
    "JobWebhookError",
    "default_job",
    "validate_job_create",
    "validate_job_update",
]
