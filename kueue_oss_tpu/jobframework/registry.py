"""Integration registry.

Reference parity: pkg/controller/jobframework/integrationmanager.go — each
integration registers its kind at import time; the manager setup enables
the subset named in Configuration.integrations (cmd/kueue/main.go:433-436).
"""

from __future__ import annotations

from typing import Callable, Optional, Type

from kueue_oss_tpu.jobframework.interface import GenericJob


class IntegrationManager:
    def __init__(self) -> None:
        self._by_kind: dict[str, Type[GenericJob]] = {}
        self._enabled: Optional[set[str]] = None  # None = all registered

    def register(self, cls: Type[GenericJob]) -> Type[GenericJob]:
        """Usable as a class decorator on integrations."""
        if not cls.kind:
            raise ValueError(f"{cls.__name__} must set a kind")
        self._by_kind[cls.kind] = cls
        return cls

    def get(self, kind: str) -> Optional[Type[GenericJob]]:
        return self._by_kind.get(kind)

    def kinds(self) -> list[str]:
        return sorted(self._by_kind)

    def enable(self, kinds: Optional[list[str]]) -> None:
        """Restrict reconciliation to the listed kinds (None = all)."""
        if kinds is None:
            self._enabled = None
            return
        unknown = [k for k in kinds if k not in self._by_kind]
        if unknown:
            raise ValueError(f"unknown integrations: {unknown}")
        self._enabled = set(kinds)

    #: kinds additionally guarded by a feature gate (kube_features.go
    #: SparkApplicationIntegration: alpha integrations need the gate on
    #: top of the integrations list)
    GATED_KINDS = {"SparkApplication": "SparkApplicationIntegration"}

    def is_enabled(self, kind: str) -> bool:
        if kind not in self._by_kind:
            return False
        gate = self.GATED_KINDS.get(kind)
        if gate is not None:
            from kueue_oss_tpu import features

            if not features.enabled(gate):
                return False
        return self._enabled is None or kind in self._enabled


#: process-wide registry, like the reference's package-level manager
integration_manager = IntegrationManager()
