"""The generic job reconciler.

Reference parity: pkg/controller/jobframework/reconciler.go
ReconcileGenericJob (:281) — ensure a Workload mirrors the job's podsets,
unsuspend the job with injected node selectors once the Workload is
admitted, stop the job when the Workload is evicted/deleted, and mark the
Workload Finished when the job completes.
"""

from __future__ import annotations

from typing import Optional

from kueue_oss_tpu.api.types import (
    PodSet,
    Workload,
    WorkloadConditionType,
)
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.jobframework.interface import (
    GenericJob,
    PodSetInfo,
    StopReason,
)
from kueue_oss_tpu.jobframework.registry import (
    IntegrationManager,
    integration_manager,
)
from kueue_oss_tpu.scheduler.scheduler import Scheduler


#: CSV of scheduling-gate names holding the workload's admission
#: (reference: constants.AdmissionGatedByAnnotation)
ADMISSION_GATED_BY_ANNOTATION = "kueue.x-k8s.io/admission-gated-by"


def propagate_admission_gated_by(job: GenericJob, wl: Workload) -> bool:
    """Copy the admission-gated-by annotation job → workload
    (reference: jobframework.PropagateAdmissionGatedByAnnotation,
    reconciler.go:1043). Returns True if the workload changed."""
    val = (getattr(job, "annotations", {}) or {}).get(
        ADMISSION_GATED_BY_ANNOTATION)
    if not val or wl.annotations.get(ADMISSION_GATED_BY_ANNOTATION) == val:
        return False
    wl.annotations[ADMISSION_GATED_BY_ANNOTATION] = val
    return True


def update_admission_gated_by(store: Store, job: GenericJob,
                              wl: Workload) -> bool:
    """Sync later annotation edits (gates may only be removed — the
    webhook rejects additions) job → workload
    (reference: jobframework.UpdateAdmissionGatedBy, reconciler.go:1018)."""
    val = (getattr(job, "annotations", {}) or {}).get(
        ADMISSION_GATED_BY_ANNOTATION)
    cur = wl.annotations.get(ADMISSION_GATED_BY_ANNOTATION)
    if (val or None) == (cur or None):
        return False
    if val:
        wl.annotations[ADMISSION_GATED_BY_ANNOTATION] = val
    else:
        wl.annotations.pop(ADMISSION_GATED_BY_ANNOTATION, None)
    store.update_workload(wl)
    return True


def workload_name_for(job: GenericJob) -> str:
    """Reference parity: jobframework/workload_names.go
    GetWorkloadNameForOwnerWithGVK. Under the ShortWorkloadNames gate,
    names over the DNS-label limit truncate with a stable hash suffix
    (workload_names.go short-name hashing); otherwise the in-memory
    store has no length limit and the plain kind-prefixed name is
    used."""
    from kueue_oss_tpu import features

    name = f"{job.kind.lower()}-{job.name}"
    if features.enabled("ShortWorkloadNames") and len(name) > 63:
        import hashlib

        digest = hashlib.sha256(name.encode()).hexdigest()[:8]
        name = f"{name[:54]}-{digest}"
    return name


class JobReconciler:
    """Bridges GenericJobs to Workloads over the in-memory store."""

    def __init__(self, store: Store, scheduler: Scheduler,
                 manager: IntegrationManager = integration_manager,
                 manage_jobs_without_queue_name: bool = False,
                 managed_jobs_namespace_selector=None,
                 workload_reconciler=None) -> None:
        self.store = store
        self.scheduler = scheduler
        self.manager = manager
        self.manage_jobs_without_queue_name = manage_jobs_without_queue_name
        #: namespace -> bool predicate (the reference's label selector
        #: over Namespace objects, reconciler.go:96)
        self.managed_jobs_namespace_selector = managed_jobs_namespace_selector
        #: optional WorkloadReconciler for PodsReady propagation
        self.workload_reconciler = workload_reconciler
        #: jobs under management, keyed "namespace/name" per kind
        self.jobs: dict[tuple[str, str], GenericJob] = {}
        #: every owner id this instance has managed (orphan-GC ground
        #: truth; see _finish_orphans)
        self._known_owners: set[str] = set()

    # -- job lifecycle ------------------------------------------------------

    def upsert_job(self, job: GenericJob) -> None:
        if not self.manager.is_enabled(job.kind):
            raise ValueError(f"integration {job.kind} is not enabled")
        self.jobs[(job.kind, job.key)] = job
        self._known_owners.add(f"{job.kind}/{job.key}")

    def delete_job(self, job: GenericJob, now: float = 0.0) -> None:
        self.jobs.pop((job.kind, job.key), None)
        owner = f"{job.kind}/{job.key}"
        # the owned workloads are deleted below; keeping the owner id
        # would only grow _known_owners without bound
        self._known_owners.discard(owner)
        # All workloads owned by the job — the base workload and, for
        # elastic jobs, every slice (suffixIndexed names).
        keys = [wl.key for wl in self.store.workloads.values()
                if wl.owner == owner]
        base = f"{job.namespace}/{workload_name_for(job)}"
        if base in self.store.workloads and base not in keys:
            keys.append(base)
        for key in keys:
            self.scheduler.evict_workload(
                key, reason="WorkloadDeleted", message="owner job deleted",
                now=now, requeue=False)
            self.store.delete_workload(key)

    def reconcile_all(self, now: float) -> None:
        for job in list(self.jobs.values()):
            self.reconcile(job, now)
        self._finish_orphans(now)

    def _finish_orphans(self, now: float) -> None:
        """FinishOrphanedWorkloads gate: a workload whose owner job no
        longer exists finishes instead of holding quota forever (the
        reference GC's workloads with dead ownerReferences). Ground
        truth here is owners THIS reconciler has actually managed
        (`_known_owners`) — a freshly restarted reconciler must not
        sweep workloads whose jobs simply have not been re-upserted
        yet."""
        from kueue_oss_tpu import features

        if not features.enabled("FinishOrphanedWorkloads"):
            return
        live = {f"{job.kind}/{job.key}" for job in self.jobs.values()}
        for wl in list(self.store.workloads.values()):
            if (wl.owner and wl.owner in self._known_owners
                    and wl.owner not in live and not wl.is_finished):
                self.scheduler.finish_workload(wl.key, now=now)

    # -- core ---------------------------------------------------------------

    def workload_for(self, job: GenericJob) -> Optional[Workload]:
        return self.store.workloads.get(
            f"{job.namespace}/{workload_name_for(job)}")

    def reconcile(self, job: GenericJob, now: float) -> None:
        """One pass of ReconcileGenericJob (reconciler.go:281)."""
        from kueue_oss_tpu import workloadslicing

        if not job.queue_name and not self.manage_jobs_without_queue_name:
            return
        # namespace opt-in (reconciler.go:342-358, :398-410): the
        # selector always bounds manageJobsWithoutQueueName; with the
        # AlwaysRespected gate it bounds queue-named jobs too
        from kueue_oss_tpu import features

        selector = self.managed_jobs_namespace_selector
        if selector is not None and not selector(job.namespace):
            if not job.queue_name:
                return
            if features.enabled("ManagedJobsNamespaceSelectorAlwaysRespected"):
                return

        if workloadslicing.enabled(job):
            self._reconcile_elastic(job, now)
            return

        wl = self.workload_for(job)

        # 1. Job finished → propagate Finished to the workload and stop.
        msg, success, finished = job.finished()
        if finished:
            if wl is not None and not wl.is_finished:
                self.scheduler.finish_workload(wl.key, now=now)
            return

        # 2. Ensure the Workload exists and mirrors the job's podsets
        #    (equivalence check, reconciler.go ensureOneWorkload).
        podsets = job.pod_sets()
        if wl is None:
            wl = self._create_workload(job, podsets, now)
        elif not _equivalent(wl, podsets, running=not job.is_suspended()):
            if wl.is_quota_reserved:
                # Shape changed under an admitted workload: release quota
                # and rebuild (the reference stops the job and recreates).
                self._stop_job(job, wl, StopReason.NO_MATCHING_WORKLOAD, now)
                self.scheduler.evict_workload(
                    wl.key, reason="NoMatchingWorkload",
                    message="job podsets changed", now=now, requeue=False)
            self.store.delete_workload(wl.key)
            wl = self._create_workload(job, podsets, now)

        if features.enabled("AdmissionGatedBy"):
            update_admission_gated_by(self.store, job, wl)
        self._sync_reclaimable(job, wl)
        self._sync_running_state(job, wl, now)

    def _sync_reclaimable(self, job: GenericJob, wl: Workload) -> None:
        """JobWithReclaimablePods (optional interface): finished pods of a
        running job release their quota share. Counts are monotone
        non-decreasing until the workload is evicted (the reference
        rejects decreases in the workload webhook)."""
        from kueue_oss_tpu import features

        getter = getattr(job, "reclaimable_pods", None)
        if not callable(getter) or not features.enabled("ReclaimablePods"):
            return
        counts = getter() or {}
        merged = dict(wl.status.reclaimable_pods)
        changed = False
        for name, n in counts.items():
            if n > merged.get(name, 0):
                merged[name] = n
                changed = True
        if changed:
            wl.status.reclaimable_pods = merged
            self.store.update_workload(wl)

    def _sync_running_state(self, job: GenericJob, wl: Workload,
                            now: float) -> None:
        # Not admitted → the job must be suspended.
        if not wl.is_admitted:
            if not job.is_suspended():
                self._stop_job(job, wl, StopReason.NOT_ADMITTED, now)
            return

        # A job managedBy the MultiKueue controller executes on a WORKER
        # cluster; the hub-side copy stays suspended even once admitted
        # (MultiKueueBatchJobWithManagedBy, job_multikueue_adapter.go).
        from kueue_oss_tpu import features
        from kueue_oss_tpu.multikueue.controller import (
            MULTIKUEUE_CONTROLLER_NAME,
        )

        if (getattr(job, "managed_by", None) == MULTIKUEUE_CONTROLLER_NAME
                and features.enabled("MultiKueueBatchJobWithManagedBy")):
            return

        # Admitted → run with injected podset infos.
        if job.is_suspended():
            job.run_with_podsets_info(self._podset_infos(wl))

        # Propagate pod readiness to the Workload condition.
        if self.workload_reconciler is not None:
            self.workload_reconciler.set_pods_ready(
                wl.key, job.pods_ready(), now)

    # -- elastic jobs (workload slices, KEP-77) -----------------------------

    def _reconcile_elastic(self, job: GenericJob, now: float) -> None:
        """Slice-aware reconcile: scale-up creates a replacement slice
        instead of recreating the workload (workloadslicing.go
        EnsureWorkloadSlices)."""
        from kueue_oss_tpu import workloadslicing

        owner = f"{job.kind}/{job.key}"
        msg, success, finished = job.finished()
        if finished:
            for wl in workloadslicing.find_not_finished_workloads(
                    self.store, owner):
                self.scheduler.finish_workload(wl.key, now=now)
            return

        def create(podsets, replacement_for, index):
            wl = self._create_workload(job, podsets, now,
                                       name_suffix=f"-{index}")
            wl.replacement_for = replacement_for
            self.store.update_workload(wl)
            return wl

        wl, compatible = workloadslicing.ensure_workload_slices(
            self.store, self.scheduler, job, job.pod_sets(), owner, now,
            create)
        if not compatible or wl is None:
            return
        # The job keeps running on whichever slice currently holds
        # admission; a pending replacement slice must not suspend it.
        running = next(
            (w for w in workloadslicing.find_not_finished_workloads(
                self.store, owner) if w.is_admitted), None)
        target = running if running is not None else wl
        if (running is not None and not job.is_suspended()
                and job.injected is not None):
            admitted_counts = {
                psa.name: psa.count
                for psa in (running.status.admission.podset_assignments
                            if running.status.admission else [])}
            injected_counts = {i.name: i.count for i in job.injected}
            if admitted_counts != injected_counts:
                # New slice took over: re-inject so the scaled pods start
                # (workloadslicing.go StartWorkloadSlicePods analog).
                job.run_with_podsets_info(self._podset_infos(running))
        self._sync_running_state(job, target, now)

    # -- helpers ------------------------------------------------------------

    def _create_workload(self, job: GenericJob, podsets: list[PodSet],
                         now: float, name_suffix: str = "") -> Workload:
        from kueue_oss_tpu import features

        labels = (dict(getattr(job, "labels", {}))
                  if features.enabled("PropagateBatchJobLabelsToWorkload")
                  else {})
        wl = Workload(
            name=workload_name_for(job) + name_suffix,
            namespace=job.namespace,
            queue_name=job.queue_name,
            labels=labels,
            priority=getattr(job, "priority", 0),
            priority_class=getattr(job, "priority_class", None),
            max_execution_time=getattr(job, "max_execution_time", None),
            podsets=[PodSet(
                name=ps.name, count=ps.count, requests=dict(ps.requests),
                min_count=ps.min_count,
                topology_request=ps.topology_request,
                node_selector=dict(ps.node_selector),
                tolerations=list(ps.tolerations),
            ) for ps in podsets],
            creation_time=getattr(job, "creation_time", now) or now,
        )
        wl.owner = f"{job.kind}/{job.key}"
        if features.enabled("AdmissionGatedBy"):
            propagate_admission_gated_by(job, wl)
        self.store.add_workload(wl)
        from kueue_oss_tpu import features, metrics

        if features.enabled("MetricForWorkloadCreationLatency"):
            metrics.workload_creation_latency_seconds.observe(
                job.kind, value=max(now - wl.creation_time, 0.0))
        return wl

    def _stop_job(self, job: GenericJob, wl: Workload, reason: str,
                  now: float) -> None:
        job.restore_podsets_info(self._podset_infos(wl))
        if not job.is_suspended():
            job.do_suspend()

    def _podset_infos(self, wl: Workload) -> list[PodSetInfo]:
        """Build the injected infos from the admission: flavor node labels
        + tolerations, admission-check podSetUpdates (provisioned-capacity
        steering), TAS selector (reconciler.go getPodSetsInfoFromStatus)."""
        if wl.status.admission is None:
            return [PodSetInfo(name=ps.name, count=ps.count)
                    for ps in wl.podsets]
        from kueue_oss_tpu import features

        infos: list[PodSetInfo] = []
        for psa in wl.status.admission.podset_assignments:
            info = PodSetInfo(name=psa.name, count=psa.count)
            for flavor_name in set(psa.flavors.values()):
                rf = self.store.resource_flavors.get(flavor_name)
                if rf is None:
                    continue
                info.node_selector.update(rf.node_labels)
                info.tolerations.extend(rf.tolerations)
            if features.enabled("AssignQueueLabelsForPods"):
                # queue provenance labels on every created pod
                # (reconciler.go:1537 assignQueueLabels)
                info.labels["kueue.x-k8s.io/queue-name"] = wl.queue_name
                info.labels["kueue.x-k8s.io/cluster-queue"] = (
                    wl.status.admission.cluster_queue)
            # admission-check podSetUpdates (e.g. the provisioning
            # controller's consume-provisioning-request annotations)
            for cs in wl.status.admission_checks.values():
                for upd in cs.pod_set_updates:
                    if upd.name != psa.name:
                        continue
                    info.node_selector.update(upd.node_selector)
                    info.labels.update(upd.labels)
                    info.annotations.update(upd.annotations)
                    info.tolerations.extend(upd.tolerations)
            if psa.topology_assignment is not None:
                info.scheduling_gates.append(
                    "kueue.x-k8s.io/topology")  # ungated per-domain by TAS
            infos.append(info)
        return infos


def _equivalent(wl: Workload, podsets: list[PodSet],
                running: bool = False) -> bool:
    """Shape equality of workload vs job podsets (name/count/requests).

    For a RUNNING job the expected counts are the ADMITTED counts, not
    the spec counts: partial admission shrinks the job (parallelism /
    executor.instances) below the workload's declared podsets, and that
    must not read as a shape change (reference
    jobframework/reconciler.go equivalentToWorkload compares against the
    admission's counts for unsuspended jobs)."""
    if len(wl.podsets) != len(podsets):
        return False
    admitted_counts = {}
    if running and wl.status.admission is not None:
        admitted_counts = {psa.name: psa.count
                           for psa in wl.status.admission.podset_assignments}
    for a, b in zip(wl.podsets, podsets):
        expect = admitted_counts.get(a.name, a.count)
        if (a.name, sorted(a.requests.items())) != (
                b.name, sorted(b.requests.items())):
            return False
        if b.count not in (a.count, expect):
            return False
    return True
