"""Filesystem durability primitives shared across subsystems.

An fsynced file behind an un-fsynced rename is not durable: the data
blocks survive power loss but the directory entry pointing at them may
not. Every atomic-write site (the persist/ checkpoint writer, the obs
journal dump) pairs ``os.replace`` with a directory fsync through this
helper (docs/DURABILITY.md).
"""

from __future__ import annotations

import os


def fsync_dir(dir_path: str) -> None:
    """Make directory-entry changes (os.replace, create, unlink)
    durable. No-op on platforms whose directories reject O_RDONLY
    opens (never the POSIX targets this runs on)."""
    try:
        fd = os.open(dir_path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
