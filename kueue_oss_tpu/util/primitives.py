"""Primitive concurrency/retry utilities.

Reference parity: pkg/util/parallelize/parallelize.go (bounded fan-out
with first-error propagation), pkg/util/routine/wrapper.go (hooked
goroutine spawner), pkg/util/wait/backoff.go (exponential backoff +
SpeedSignal-driven polling loop).
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

#: parallelize.go maxParallelism
MAX_PARALLELISM = 8


def parallelize_until(n: int, fn: Callable[[int], None],
                      max_workers: int = MAX_PARALLELISM) -> None:
    """Run fn(0..n-1) over a bounded worker pool; the FIRST exception
    wins and is re-raised after all workers drain (parallelize.Until +
    ErrorChannel: one buffered error slot, later errors dropped)."""
    if n <= 0:
        return
    first_error: list[BaseException] = []
    lock = threading.Lock()

    def run(i: int) -> None:
        try:
            fn(i)
        except BaseException as e:  # noqa: BLE001 - propagated below
            with lock:
                if not first_error:
                    first_error.append(e)

    if n == 1 or max_workers <= 1:
        for i in range(n):
            run(i)
    else:
        with ThreadPoolExecutor(max_workers=min(max_workers, n)) as pool:
            list(pool.map(run, range(n)))
    if first_error:
        raise first_error[0]


class RoutineWrapper:
    """routine.Wrapper: spawn work with before/after hooks — the
    reference uses it to attach leader-demotion guards around scheduler
    goroutines."""

    def __init__(self, before: Optional[Callable[[], None]] = None,
                 after: Optional[Callable[[], None]] = None) -> None:
        self.before = before
        self.after = after

    def run(self, f: Callable[[], None]) -> threading.Thread:
        if self.before is not None:
            self.before()

        def body() -> None:
            try:
                f()
            finally:
                if self.after is not None:
                    self.after()

        t = threading.Thread(target=body, daemon=True)
        t.start()
        return t


class Backoff:
    """wait.Backoff analog: exponential growth with cap and jitter.

    wait_time(iteration) returns the duration for the i-th retry
    (backoff.go:44-53): initial * factor^(i-1), capped, with
    `jitter`-fraction uniform noise added.
    """

    def __init__(self, initial: float, cap: float = 0.0,
                 factor: float = 2.0, jitter: float = 0.0,
                 rng: Optional[random.Random] = None) -> None:
        if initial <= 0 or factor < 1.0:
            raise ValueError("initial must be > 0 and factor >= 1")
        self.initial = initial
        self.cap = cap or float("inf")
        self.factor = factor
        self.jitter = jitter
        self.rng = rng or random.Random()

    def wait_time(self, iteration: int) -> float:
        if iteration <= 0:
            return 0.0
        duration = min(self.initial * self.factor ** (iteration - 1),
                       self.cap)
        if self.jitter > 0:
            duration += duration * self.jitter * self.rng.random()
        return min(duration, self.cap * (1 + self.jitter))


class SpeedSignal:
    """backoff.go SpeedSignal: the loop body reports whether to keep
    the current cadence or slow down."""

    KEEP_GOING = "KeepGoing"
    SLOW_DOWN = "SlowDown"


def until_with_backoff(f: Callable[[], str], backoff: Backoff,
                       stop: Callable[[], bool],
                       sleep: Callable[[float], None] = time.sleep) -> int:
    """Run f repeatedly until stop(); SlowDown signals stack the
    backoff iteration, KeepGoing resets it (backoff.go
    UntilWithBackoff). Returns the number of invocations."""
    iteration = 0
    calls = 0
    while not stop():
        signal = f()
        calls += 1
        if signal == SpeedSignal.KEEP_GOING:
            iteration = 0
        else:
            iteration += 1
        if stop():
            break
        sleep(backoff.wait_time(iteration))
    return calls
