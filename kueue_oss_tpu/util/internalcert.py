"""Internal certificate bootstrap + rotation.

Reference parity: pkg/util/cert (internal cert bootstrap the manager
uses when cert-manager isn't installed; config/components/internalcert)
— a self-signed serving certificate is generated on first start and
rotated before expiry, so the TLS-enabled HTTP servers (visibility,
dashboard, webhook) can serve without external PKI. Pairs with
util/tlsconfig: `ensure_cert` returns (cert_file, key_file) ready for
TLSOptions.
"""

from __future__ import annotations

import datetime
import os
from pathlib import Path
from typing import Optional

CERT_NAME = "tls.crt"
KEY_NAME = "tls.key"


def _pair_valid_until(cert_path: Path,
                      key_path: Path) -> Optional[datetime.datetime]:
    """Expiry of a HEALTHY pair: the cert parses, the key parses, and
    the key matches the cert's public key (a crash mid-rotation or a
    corrupt file must regenerate, not serve a broken chain forever)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import serialization

    try:
        cert = x509.load_pem_x509_certificate(cert_path.read_bytes())
        key = serialization.load_pem_private_key(
            key_path.read_bytes(), password=None)
    except (ValueError, TypeError, OSError):
        return None
    if (key.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo)
            != cert.public_key().public_bytes(
                serialization.Encoding.DER,
                serialization.PublicFormat.SubjectPublicKeyInfo)):
        return None
    return cert.not_valid_after_utc


def _write_private(path: Path, data: bytes) -> None:
    """0600 atomic write (the key must never be world-readable).

    os.write may write fewer bytes than asked (signals, quotas); loop
    until everything is on disk so the rename can never persist a
    truncated private key."""
    tmp = path.with_suffix(path.suffix + ".tmp")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        view = memoryview(data)
        while view:
            written = os.write(fd, view)
            view = view[written:]
    finally:
        os.close(fd)
    os.replace(tmp, path)


def _write_atomic(path: Path, data: bytes) -> None:
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_bytes(data)
    os.replace(tmp, path)


def ensure_cert(directory: str | Path,
                common_name: str = "kueue-tpu-controller",
                dns_names: tuple[str, ...] = ("localhost",),
                validity_days: int = 365,
                rotate_before_days: int = 30,
                now: Optional[datetime.datetime] = None,
                ) -> tuple[str, str]:
    """Return (cert_file, key_file), generating or ROTATING the
    self-signed pair when absent, unparsable, or within
    `rotate_before_days` of expiry (cert.go rotation contract)."""
    import fcntl

    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    cert_path = directory / CERT_NAME
    key_path = directory / KEY_NAME
    now = now or datetime.datetime.now(datetime.timezone.utc)

    # serialize bootstrap across processes sharing the directory
    # (visibility + dashboard + webhook servers starting concurrently
    # must not interleave the key/cert renames into a mismatched pair)
    lock = open(directory / ".bootstrap.lock", "w")
    fcntl.flock(lock, fcntl.LOCK_EX)
    try:
        return _ensure_cert_locked(cert_path, key_path, common_name,
                                   dns_names, validity_days,
                                   rotate_before_days, now)
    finally:
        fcntl.flock(lock, fcntl.LOCK_UN)
        lock.close()


def _ensure_cert_locked(cert_path: Path, key_path: Path,
                        common_name: str, dns_names: tuple[str, ...],
                        validity_days: int, rotate_before_days: int,
                        now: datetime.datetime) -> tuple[str, str]:
    if cert_path.exists() and key_path.exists():
        not_after = _pair_valid_until(cert_path, key_path)
        if (not_after is not None
                and not_after - now
                > datetime.timedelta(days=rotate_before_days)):
            return str(cert_path), str(key_path)

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    subject = x509.Name([
        x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    cert = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=validity_days))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName(n) for n in dns_names]),
            critical=False)
        # a SERVING leaf, not a CA (pkg/util/cert parity): clients
        # trusting it must not implicitly trust a signer
        .add_extension(
            x509.BasicConstraints(ca=False, path_length=None),
            critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True, key_encipherment=True,
                content_commitment=False, data_encipherment=False,
                key_agreement=False, key_cert_sign=False,
                crl_sign=False, encipher_only=False,
                decipher_only=False),
            critical=True)
        .add_extension(
            x509.ExtendedKeyUsage([ExtendedKeyUsageOID.SERVER_AUTH]),
            critical=False)
        .sign(key, hashes.SHA256())
    )
    # key first, cert last, both atomic: a crash between the renames
    # leaves new-key + old-cert, which the health check above detects
    # as a mismatch and regenerates on the next start
    _write_private(key_path, key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    _write_atomic(cert_path, cert.public_bytes(
        serialization.Encoding.PEM))
    return str(cert_path), str(key_path)
