"""Event recording.

Reference parity: the scheduler and controllers emit Kubernetes Events
on every admission, preemption, eviction, and requeue
(scheduler.go:952-973, 996, 1012 — r.recorder.Eventf calls). Here
events land in an in-process ring buffer consumable by the visibility
server, the CLI (kueuectl describe), and tests.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

NORMAL = "Normal"
WARNING = "Warning"


@dataclass
class Event:
    object_key: str      # "namespace/name" of the involved object
    kind: str            # involved object kind (Workload, ClusterQueue...)
    type: str            # Normal | Warning
    reason: str          # QuotaReserved / Admitted / Preempted / Pending...
    message: str
    time: float = 0.0


class EventRecorder:
    """Bounded in-memory event sink (one per process, like a recorder
    wired to the manager's broadcaster)."""

    def __init__(self, capacity: int = 2048) -> None:
        self.events: deque[Event] = deque(maxlen=capacity)

    def eventf(self, object_key: str, kind: str, type_: str, reason: str,
               message: str, now: float = 0.0) -> None:
        self.events.append(Event(object_key, kind, type_, reason,
                                 message, now))

    def for_object(self, object_key: str) -> list[Event]:
        return [e for e in self.events if e.object_key == object_key]

    def by_reason(self, reason: str) -> list[Event]:
        return [e for e in self.events if e.reason == reason]


#: process-wide recorder (the reference shares one EventBroadcaster)
recorder = EventRecorder()
