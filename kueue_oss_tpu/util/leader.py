"""Leader election + role tracking for HA replicas.

Reference parity: cmd/kueue/main.go:281,617 leader election via
controller-runtime lease + pkg/util/roletracker (labels logs/metrics by
leader/follower role, resyncs gauges on election) and the
leader-aware reconcilers (non-leader replicas keep their caches warm
from the watch stream so failover starts scheduling immediately,
pkg/controller/core leader_aware_reconciler.go).

In-process model: a Lease object arbitrates; each Replica holds a fully
wired QueueManager + Scheduler over the shared store (its caches stay
warm because both are watch-driven), but only the leader's
run_until_quiet/schedule make decisions.
"""

from __future__ import annotations

import time
from typing import Optional

LEADER = "leader"
FOLLOWER = "follower"


class Lease:
    """A lease with holder identity and expiry (coordination.k8s.io
    Lease analog)."""

    def __init__(self, duration_s: float = 15.0,
                 clock=time.monotonic) -> None:
        self.duration_s = duration_s
        self.clock = clock
        self.holder: Optional[str] = None
        self.renewed_at: float = -1e18

    def try_acquire(self, identity: str) -> bool:
        now = self.clock()
        expired = now - self.renewed_at > self.duration_s
        if self.holder is None or expired or self.holder == identity:
            self.holder = identity
            self.renewed_at = now
            return True
        return False

    def release(self, identity: str) -> None:
        if self.holder == identity:
            self.holder = None
            self.renewed_at = -1e18


class RoleTracker:
    """Labels the process's role; callbacks fire on transitions
    (pkg/util/roletracker/tracker.go — metric gauges resync when the
    role flips)."""

    def __init__(self) -> None:
        self.role = FOLLOWER
        self._on_promote: list = []
        self._on_demote: list = []

    def on_promote(self, fn) -> None:
        self._on_promote.append(fn)

    def on_demote(self, fn) -> None:
        self._on_demote.append(fn)

    def set_role(self, role: str) -> None:
        if role == self.role:
            return
        self.role = role
        for fn in (self._on_promote if role == LEADER else self._on_demote):
            fn()


class Replica:
    """One manager replica: warm caches always, decisions only as leader.

    Wraps a Scheduler whose QueueManager watches the shared store — the
    follower's heaps and snapshots track reality continuously, so
    `tick()` after a leadership change schedules immediately without a
    cache rebuild.
    """

    def __init__(self, identity: str, scheduler, lease: Lease,
                 warm=None) -> None:
        self.identity = identity
        self.scheduler = scheduler
        self.lease = lease
        self.tracker = RoleTracker()
        #: durability hook (docs/DURABILITY.md): called on every
        #: follower->leader transition BEFORE the first scheduling pass,
        #: so a cold replica (fresh process after the old leader died)
        #: warms its store by checkpoint+WAL replay — typically
        #: ``PersistenceManager.recover(store=..., emit=True)``, which
        #: also streams the replay through the store's watchers so the
        #: QueueManager heaps rebuild in the same pass. In-process
        #: replicas sharing a watch-driven store leave it None.
        self.warm = warm

    @property
    def is_leader(self) -> bool:
        return self.tracker.role == LEADER

    def tick(self, now: Optional[float] = None,
             max_cycles: int = 10_000, tick: float = 0.0) -> int:
        """Renew/acquire the lease; schedule if leader. Returns cycles
        run (0 as follower)."""
        if self.lease.try_acquire(self.identity):
            if self.tracker.role != LEADER and self.warm is not None:
                # promoted: catch the store up to durable state before
                # taking traffic
                self.warm()
            self.tracker.set_role(LEADER)
            return self.scheduler.run_until_quiet(
                now=now, max_cycles=max_cycles, tick=tick)
        self.tracker.set_role(FOLLOWER)
        return 0

    def step_down(self) -> None:
        self.lease.release(self.identity)
        self.tracker.set_role(FOLLOWER)
