"""Structured logging.

Reference parity: the reference logs through zap via controller-runtime
(cmd/kueue/main.go zap options; every reconciler logs key-value pairs
with object context, e.g. scheduler.go log.V(2).Info("Workload assumed",
"workload", klog.KObj(...))). The analog: a leveled key-value logger
emitting one JSON object per line, with child loggers carrying bound
context the way logr's WithValues does.
"""

from __future__ import annotations

import io
import json
import sys
import threading
import time
from typing import Any, Optional, TextIO


class StructuredLogger:
    """Leveled JSON-lines logger with bound key-value context.

    - `level` gates verbosity like logr's V(n): messages logged at
      verbosity > level are dropped;
    - `with_values(**kv)` returns a child sharing the sink with extra
      bound context (logr WithValues);
    - `with_name(name)` appends a logger-name segment (logr WithName).
    """

    def __init__(self, sink: Optional[TextIO] = None, level: int = 0,
                 name: str = "", clock=time.time,
                 _bound: Optional[dict] = None,
                 _lock: Optional[threading.Lock] = None,
                 _level_ref: Optional[list] = None) -> None:
        self.sink = sink if sink is not None else sys.stderr
        #: verbosity is SHARED with child loggers by reference, so
        #: set_verbosity() after children were created (the documented
        #: startup flow: construct components, then apply config)
        #: affects every logger in the tree
        self._level_ref = _level_ref if _level_ref is not None else [level]
        self.name = name
        self.clock = clock
        self._bound = dict(_bound or {})
        self._lock = _lock or threading.Lock()

    @property
    def level(self) -> int:
        return self._level_ref[0]

    @level.setter
    def level(self, value: int) -> None:
        self._level_ref[0] = value

    # -- context ------------------------------------------------------------

    def with_values(self, **kv: Any) -> "StructuredLogger":
        bound = dict(self._bound)
        bound.update(kv)
        return StructuredLogger(self.sink, name=self.name,
                                clock=self.clock, _bound=bound,
                                _lock=self._lock,
                                _level_ref=self._level_ref)

    def with_name(self, name: str) -> "StructuredLogger":
        full = f"{self.name}.{name}" if self.name else name
        return StructuredLogger(self.sink, name=full, clock=self.clock,
                                _bound=self._bound, _lock=self._lock,
                                _level_ref=self._level_ref)

    # -- emit ---------------------------------------------------------------

    def _emit(self, severity: str, v: int, msg: str, kv: dict) -> None:
        if v > self.level:
            return
        record = {"ts": round(self.clock(), 6), "severity": severity,
                  "v": v, "msg": msg}
        if self.name:
            record["logger"] = self.name
        record.update(self._bound)
        record.update(kv)
        line = json.dumps(record, default=str)
        with self._lock:
            self.sink.write(line + "\n")

    def info(self, msg: str, v: int = 0, **kv: Any) -> None:
        self._emit("info", v, msg, kv)

    def error(self, msg: str, **kv: Any) -> None:
        # errors bypass verbosity gating (logr Error)
        record_v = 0
        self._emit("error", record_v, msg, kv)


class CapturingLogger(StructuredLogger):
    """Test helper: records parsed JSON records instead of writing."""

    def __init__(self, level: int = 0) -> None:
        self._buffer = io.StringIO()
        super().__init__(sink=self._buffer, level=level,
                         clock=lambda: 0.0)

    @property
    def records(self) -> list[dict]:
        out = []
        for line in self._buffer.getvalue().splitlines():
            out.append(json.loads(line))
        return out


#: process-wide root logger (the reference wires one zap logger into
#: controller-runtime); verbosity is adjusted at startup from config
root = StructuredLogger()


def set_verbosity(level: int) -> None:
    root.level = level
