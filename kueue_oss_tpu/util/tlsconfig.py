"""TLS options for the framework's HTTP servers.

Reference parity: pkg/util/tlsconfig/tlsconfig.go — ParseTLSOptions
converts the Configuration's TLSOptions (minVersion, cipherSuites)
into concrete TLS settings, rejecting pre-1.2 versions and unknown
cipher names; BuildTLSOptions applies them only when the TLSOptions
feature gate is enabled. Here the product is an ``ssl.SSLContext``
the visibility/debugger/viz HTTP servers wrap their sockets with.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass, field
from typing import Optional

_VERSIONS = {
    "": ssl.TLSVersion.TLSv1_2,
    "VersionTLS12": ssl.TLSVersion.TLSv1_2,
    "VersionTLS13": ssl.TLSVersion.TLSv1_3,
}
_REJECTED_VERSIONS = {"VersionTLS10", "VersionTLS11"}


class TLSOptionsError(ValueError):
    pass


@dataclass
class TLSOptions:
    """Configuration.tls analog (config TLSOptions struct)."""

    min_version: str = ""
    cipher_suites: list[str] = field(default_factory=list)
    #: PEM paths; both required to actually serve TLS
    cert_file: Optional[str] = None
    key_file: Optional[str] = None


@dataclass
class TLS:
    """Parsed options (tlsconfig.go TLS struct analog)."""

    min_version: ssl.TLSVersion
    cipher_suites: list[str] = field(default_factory=list)
    cert_file: Optional[str] = None
    key_file: Optional[str] = None


#: TLS 1.3 suites are configured by OpenSSL's set_ciphersuites, which
#: the Python ssl module does not expose; they are on by default, so
#: naming them validates as a no-op (documented in build_ssl_context).
_TLS13_SUITES = {
    "TLS_AES_128_GCM_SHA256",
    "TLS_AES_256_GCM_SHA384",
    "TLS_CHACHA20_POLY1305_SHA256",
    "TLS_AES_128_CCM_SHA256",
    "TLS_AES_128_CCM_8_SHA256",
}


def _iana_to_openssl(name: str) -> str:
    """Translate an IANA suite name (the format the reference's config
    uses, e.g. TLS_ECDHE_RSA_WITH_AES_128_GCM_SHA256) to OpenSSL's
    (ECDHE-RSA-AES128-GCM-SHA256), which set_ciphers understands."""
    s = name
    if s.startswith("TLS_"):
        s = s[4:]
    s = s.replace("_WITH_", "_")
    s = s.replace("_", "-")
    for bits in ("128", "256"):
        s = s.replace(f"AES-{bits}", f"AES{bits}")
        s = s.replace(f"CAMELLIA-{bits}", f"CAMELLIA{bits}")
    s = s.replace("3DES-EDE-CBC", "DES-CBC3")
    # OpenSSL spells ChaCha20 suites without the HMAC suffix...
    if s.endswith("CHACHA20-POLY1305-SHA256"):
        s = s[: -len("-SHA256")]
    # ...and CBC suites without the CBC token (ECDHE-RSA-AES128-SHA)
    s = s.replace("-CBC-", "-")
    return s


def _settable(cipher_string: str) -> bool:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    try:
        ctx.set_ciphers(cipher_string)
        return True
    except ssl.SSLError:
        return False


def _resolve_ciphers(names: list[str]) -> tuple[list[str], list[str]]:
    """Per name: accept TLS 1.3 suites as no-ops; otherwise accept the
    OpenSSL spelling directly or via the IANA translation. Returns
    (openssl_names_for_set_ciphers, invalid_names)."""
    resolved, bad = [], []
    for name in names:
        if name in _TLS13_SUITES:
            continue
        if _settable(name):
            resolved.append(name)
        elif _settable(_iana_to_openssl(name)):
            resolved.append(_iana_to_openssl(name))
        else:
            bad.append(name)
    return resolved, bad


def parse_tls_options(cfg: Optional[TLSOptions]) -> Optional[TLS]:
    """Validate and convert (ParseTLSOptions, tlsconfig.go:36-59).

    Returns None for an absent config; raises TLSOptionsError on a
    pre-1.2 minVersion or unknown cipher names.
    """
    if cfg is None:
        return None
    errs = []
    if cfg.min_version in _REJECTED_VERSIONS:
        errs.append("invalid minVersion. Please use VersionTLS12 or "
                    "VersionTLS13")
        version = ssl.TLSVersion.TLSv1_2
    elif cfg.min_version not in _VERSIONS:
        errs.append(f"invalid minVersion {cfg.min_version!r}. Please use "
                    "VersionTLS12 or VersionTLS13")
        version = ssl.TLSVersion.TLSv1_2
    else:
        version = _VERSIONS[cfg.min_version]
    suites = []
    if cfg.cipher_suites:
        resolved, bad = _resolve_ciphers(cfg.cipher_suites)
        if bad:
            errs.append(f"invalid cipher suites: {bad}. Please use "
                        "secure cipher names (IANA or OpenSSL format)")
        else:
            suites = resolved
    if errs:
        raise TLSOptionsError("; ".join(errs))
    return TLS(min_version=version, cipher_suites=suites,
               cert_file=cfg.cert_file, key_file=cfg.key_file)


def build_ssl_context(tls: Optional[TLS],
                      bootstrap_dir: Optional[str] = None,
                      ) -> Optional[ssl.SSLContext]:
    """BuildTLSOptions analog: None when the gate is off or no options.

    The returned context has minimum_version and cipher suites applied;
    cert/key are loaded when provided. With `bootstrap_dir` and no
    configured cert, a self-signed pair is generated/rotated there
    (util/internalcert — the reference's internal-cert path when
    cert-manager is absent).
    """
    from kueue_oss_tpu import features

    if tls is None or not features.enabled("TLSOptions"):
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = tls.min_version
    if tls.cipher_suites:
        # ssl expects an OpenSSL cipher string; names join with ':'
        ctx.set_ciphers(":".join(tls.cipher_suites))
    cert_file, key_file = tls.cert_file, tls.key_file
    if not (cert_file and key_file) and bootstrap_dir:
        from kueue_oss_tpu.util.internalcert import ensure_cert

        cert_file, key_file = ensure_cert(bootstrap_dir)
    if cert_file and key_file:
        ctx.load_cert_chain(cert_file, key_file)
        #: servers key their "serve TLS" decision off this (a context
        #: without a chain is still returned for option inspection)
        ctx.kueue_cert_loaded = True
    return ctx
