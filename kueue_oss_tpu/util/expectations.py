"""In-flight preemption expectations.

Reference parity: pkg/util/expectations/store.go:30-75 — the scheduler
records the UIDs of workloads whose preemption it has issued; until the
eviction is OBSERVED (the workload loses its quota reservation), repeated
cycles must not double-issue preemptions for the same victims, and a
pending preemptor keeps waiting instead of recomputing a second plan.
The reference needs this because evictions are asynchronous apiserver
patches; here evictions apply synchronously in-process, but controllers
(MultiKueue orchestrated preemption, admission-check flows) can defer
them, so the guard carries the same contract.
"""

from __future__ import annotations

import threading


class ExpectationsStore:
    """Tracks (owner key -> expected-to-be-preempted workload UIDs)."""

    def __init__(self, name: str = "preemption") -> None:
        self.name = name
        self._lock = threading.Lock()
        self._store: dict[str, set[int]] = {}

    def expect_uids(self, owner: str, uids: list[int]) -> None:
        """Record that `owner`'s plan preempts these workloads
        (store.go ExpectUIDs)."""
        with self._lock:
            self._store.setdefault(owner, set()).update(uids)

    def observed_uid(self, owner: str, uid: int) -> None:
        """One expected eviction materialized (store.go ObservedUID)."""
        with self._lock:
            uids = self._store.get(owner)
            if uids is None:
                return
            uids.discard(uid)
            if not uids:
                del self._store[owner]

    def satisfied(self, owner: str) -> bool:
        """All of the owner's expected evictions have been observed
        (store.go Satisfied)."""
        with self._lock:
            return not self._store.get(owner)

    def pending_uids(self) -> set[int]:
        """Union of all UIDs still expected to be evicted."""
        with self._lock:
            out: set[int] = set()
            for uids in self._store.values():
                out |= uids
            return out

    def observe(self, uid: int) -> None:
        """An eviction materialized; clear it from every plan expecting
        it (the watch-driven ObservedUID path, owner-agnostic)."""
        with self._lock:
            for owner in list(self._store):
                self._store[owner].discard(uid)
                if not self._store[owner]:
                    del self._store[owner]

    def forget(self, owner: str) -> None:
        with self._lock:
            self._store.pop(owner, None)
