"""Persistent XLA compilation cache (production default: on).

The solver's programs are compiled per (padded-shape, caps) key; a
production deployment — and the bench's subprocess-per-scenario
protocol — must not pay that compile more than once per machine.
JAX only honors the JAX_COMPILATION_CACHE_DIR environment variable on
some versions; setting the config keys explicitly works on all, so
every entry point (bench scenarios, the solver sidecar, serve()) calls
:func:`enable` before the first compile.

Reference analog: the reference amortizes scheduling-logic cost by
being a long-lived controller process (cmd/kueue main.go); our
device programs amortize through this cache plus long-lived serve()
loops.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = "/tmp/kueue_oss_tpu_xla_cache"

_enabled = False


def enable(path: str | None = None) -> str | None:
    """Idempotently point JAX's persistent compilation cache at *path*.

    Returns the cache dir, or None if disabled via
    KUEUE_TPU_XLA_CACHE=off or an unavailable jax.
    """
    global _enabled
    if os.environ.get("KUEUE_TPU_XLA_CACHE", "").lower() in ("off", "0"):
        return None
    path = path or os.environ.get("JAX_COMPILATION_CACHE_DIR", _DEFAULT_DIR)
    if _enabled:
        return path
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        return None
    _enabled = True
    return path
