"""Visibility API: on-demand pending-workload summaries.

Reference parity: pkg/visibility (extension API server serving
apis/visibility/v1beta2 PendingWorkloadsSummary straight from the queue
manager, pkg/visibility/storage). Here the server surface is a plain
object API plus an optional stdlib HTTP endpoint; positions are computed
from the live heaps exactly like the reference's snapshot-order walk.
"""

from __future__ import annotations

import json
import threading
from dataclasses import asdict, dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kueue_oss_tpu.core.queue_manager import QueueManager


@dataclass
class PendingWorkload:
    """apis/visibility/v1beta2/types.go:66-80."""

    name: str
    namespace: str
    priority: int
    local_queue_name: str
    position_in_cluster_queue: int
    position_in_local_queue: int


@dataclass
class PendingWorkloadsSummary:
    items: list[PendingWorkload] = field(default_factory=list)


class VisibilityService:
    def __init__(self, queues: QueueManager) -> None:
        self.queues = queues

    def _check_gate(self):
        from kueue_oss_tpu import features

        if not features.enabled("VisibilityOnDemand"):
            raise PermissionError(
                "visibility API disabled (VisibilityOnDemand gate)")

    def pending_workloads_in_cq(
        self, cq_name: str, limit: Optional[int] = None, offset: int = 0
    ) -> PendingWorkloadsSummary:
        """Pending workloads of a ClusterQueue in admission order
        (active heap order first, then parked inadmissible)."""
        self._check_gate()
        q = self.queues.queues.get(cq_name)
        if q is None:
            return PendingWorkloadsSummary()
        lq_positions: dict[tuple[str, str], int] = {}
        items: list[PendingWorkload] = []
        ordered = q.snapshot_order() + sorted(
            q.inadmissible.values(), key=lambda i: i.key)
        for pos, info in enumerate(ordered):
            wl = info.obj
            lq_key = (wl.namespace, wl.queue_name)
            lq_pos = lq_positions.get(lq_key, 0)
            lq_positions[lq_key] = lq_pos + 1
            items.append(PendingWorkload(
                name=wl.name, namespace=wl.namespace,
                priority=wl.priority,
                local_queue_name=wl.queue_name,
                position_in_cluster_queue=pos,
                position_in_local_queue=lq_pos,
            ))
        end = None if limit is None else offset + limit
        return PendingWorkloadsSummary(items=items[offset:end])

    def pending_workloads_in_lq(
        self, namespace: str, lq_name: str,
        limit: Optional[int] = None, offset: int = 0
    ) -> PendingWorkloadsSummary:
        cq_name = None
        lq = self.queues.store.local_queues.get(f"{namespace}/{lq_name}")
        if lq is not None:
            cq_name = lq.cluster_queue
        if cq_name is None:
            return PendingWorkloadsSummary()
        all_cq = self.pending_workloads_in_cq(cq_name)
        items = [i for i in all_cq.items
                 if i.local_queue_name == lq_name and i.namespace == namespace]
        end = None if limit is None else offset + limit
        return PendingWorkloadsSummary(items=items[offset:end])


class VisibilityServer:
    """Optional stdlib HTTP wrapper:
    GET /apis/visibility/v1beta2/clusterqueues/<cq>/pendingworkloads
    GET /apis/visibility/v1beta2/namespaces/<ns>/localqueues/<lq>/pendingworkloads
    """

    def __init__(self, service: VisibilityService, port: int = 0,
                 tls=None, tls_bootstrap_dir=None) -> None:
        """`tls`: a parsed util.tlsconfig.TLS — applied via
        build_ssl_context (no-op unless the TLSOptions gate is on and a
        cert/key pair is available; reference: config.go:182-190).
        Without a configured pair, `tls_bootstrap_dir` generates and
        rotates a self-signed one (util/internalcert — the reference's
        internal-cert path when cert-manager is absent)."""
        svc = service

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self) -> None:
                parts = [p for p in self.path.split("/") if p]
                summary = None
                if (len(parts) >= 5 and parts[3] == "clusterqueues"
                        and parts[-1] == "pendingworkloads"):
                    summary = svc.pending_workloads_in_cq(parts[4])
                elif (len(parts) >= 7 and parts[3] == "namespaces"
                        and parts[5] == "localqueues"
                        and parts[-1] == "pendingworkloads"):
                    summary = svc.pending_workloads_in_lq(parts[4], parts[6])
                if summary is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(asdict(summary)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.tls_active = False
        if tls is not None:
            from kueue_oss_tpu.util.tlsconfig import build_ssl_context

            # one bootstrap path: build_ssl_context generates/rotates
            # the internal cert ONLY when the TLSOptions gate is on
            # (no key material written for a gated-off config)
            ctx = build_ssl_context(tls, bootstrap_dir=tls_bootstrap_dir)
            if ctx is not None and getattr(ctx, "kueue_cert_loaded",
                                           False):
                self._httpd.socket = ctx.wrap_socket(
                    self._httpd.socket, server_side=True)
                self.tls_active = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
