"""Columnar, delta-native store→tensor assembly.

:func:`tensors.export_problem` rebuilds every array from scratch with
per-workload Python loops — an O(W) dict-of-dataclass walk that costs
seconds at 1M pending workloads even though most drains change almost
nothing. :class:`ColumnarStore` keeps the export decomposed into flat
numpy *blocks* (one per (section, ClusterQueue): heap / parked /
admitted) that are updated in place from the ``ExportCache`` dirty-key
feed, so a re-export is one of four escalating paths:

``cached``
    Nothing changed (memberships identical, no store events): return
    the previously assembled :class:`SolverProblem` object. Pure
    identity compares — microseconds per thousand rows.
``scatter``
    Row content changed but no workload entered or left any section:
    rebuild only the dirty rows (O(dirty) Python), copy-on-write the
    affected final columns, and re-derive only the groups whose inputs
    moved (timestamp ranks, class densify, request gathers).
``assemble``
    Membership changed: rebuild only the blocks whose lists changed
    (O(changed block) Python), then re-concatenate + vectorized
    post-processing. No per-row Python over unchanged blocks.
``rebuild``
    The export stamp moved (spec edit, gate flip, vocabulary change):
    everything is re-derived — equivalent to the classic walk.

Bit-identity contract: for the SAME :class:`ExportCache` (shape and
class-token interning is shared state), every array of the returned
problem is byte-identical to what the classic walk in
``export_problem(..., columnar=False)`` would produce. Anything this
view cannot prove identical — AFS-active exports, caller-pinned
snapshots — bails by returning ``None`` so the classic walk runs.

The returned problem must be treated as READ-ONLY: the ``cached`` path
returns the same object again, and the ``scatter`` path aliases every
unchanged array into the new problem.

Each export also attaches a :class:`ColumnarHint` as
``problem._columnar_hint``: the changed-row positions that let
``HostDeltaSession`` (solver/delta.py) encode DELTA frames straight
from the dirty columns instead of re-diffing two full padded exports.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import numpy as np

from kueue_oss_tpu.core.snapshot import build_snapshot
from kueue_oss_tpu.solver import tensors as T

__all__ = ["ColumnarStore", "ColumnarHint"]

#: dirty-log compaction bound: past this many un-drained events the
#: incremental bookkeeping is worth less than a fresh build.
_LOG_CAP = 1 << 20


def _infos_match(a, b) -> bool:
    """Membership check with a per-element identity shortcut.

    ``pending_backlog`` rebuilds its lists every call but reuses the
    WorkloadInfo objects for untouched entries, so a plain ``a == b``
    runs the full dataclass field compare for every member — dataclass
    ``__eq__`` has no identity fast path, which turns the validity scan
    O(W x fields) at million-row scale (~6 s/export observed at 1M).
    ``x is y`` settles the common case; the value compare only runs for
    rebuilt-but-equal infos."""
    if a is b:
        return True
    if a is None or b is None or len(a) != len(b):
        return False
    return all(x is y or x == y for x, y in zip(a, b))


class ColumnarHint:
    """Delta-session side-channel riding each columnar export.

    ``seq``/``base_seq`` chain consecutive exports of the same mode
    (lean vs full); ``changed`` maps workload key → row position in the
    *unpadded* problem (positions survive :func:`tensors.pad_workloads`
    because inert rows are inserted before the null row). When
    ``membership_changed`` is set the positions are meaningless and the
    session must run its classic content diff.
    """

    __slots__ = ("seq", "base_seq", "membership_changed", "changed",
                 "mode", "n_workloads")

    def __init__(self, seq: int, base_seq: int, membership_changed: bool,
                 changed: dict, mode: str, n_workloads: int):
        self.seq = seq
        self.base_seq = base_seq
        self.membership_changed = membership_changed
        self.changed = changed
        self.mode = mode
        self.n_workloads = n_workloads


class _Block:
    """One section's rows for one ClusterQueue as flat numpy columns.

    ``kind`` is "h" (heap, FIFO rank = position), "p" (parked, rank
    BIG) or "a" (the single admitted block, rank BIG + admission
    usage). Content columns mirror the per-row quantities the classic
    walk pulls out of ``ExportCache`` rows; membership validity is an
    identity compare of ``infos`` against the caller's current list.
    """

    __slots__ = ("kind", "infos", "keys", "cids", "prio", "uid",
                 "raw_ts", "evicted", "shape_id", "class_tok",
                 "admit_ts", "rows", "cq_frs", "u_rows", "u_fs", "u_qs",
                 "member_seq", "log_pos", "events_mark", "_pos")

    def __init__(self, kind: str):
        self.kind = kind
        self._pos: Optional[dict] = None

    def pos(self) -> dict:
        if self._pos is None:
            self._pos = {k: i for i, k in enumerate(self.keys)}
        return self._pos


class _Assembly:
    """One mode's (lean or full) cached final problem + re-derivation
    inputs, with the marks that prove it still current."""

    __slots__ = ("order", "build_seqs", "log_pos", "log_epoch", "stamp",
                 "snap_mark", "stack_len", "tok_len", "scale", "problem",
                 "seq", "offsets", "n_heap", "n_pending", "W", "toks",
                 "shape_ids", "ad_usage_raw", "n_ts", "n_admit_rank",
                 "n_classes")


class _Restart(Exception):
    """A patched row drifted to another CQ mid-validation; re-derive
    the vocabulary with that block invalidated."""


class ColumnarStore:
    """Incremental columnar view over one subscribed ExportCache."""

    def __init__(self, cache) -> None:
        self.cache = cache
        self._blocks: dict[tuple, _Block] = {}
        self._key_home: dict[str, tuple] = {}
        #: append-only dirty-key log; blocks and assemblies carry
        #: positions into it (compacted by invalidating both).
        self._log: list[str] = []
        self._log_epoch = 0
        self._asms: dict[bool, _Assembly] = {}
        self._row_stamp: Optional[tuple] = None
        self._snap_mark: Optional[tuple] = None
        self._snapshot = None
        self._nodes: Optional[list] = None
        self._node_frs: Optional[set] = None
        self._usage_key = None
        self._usage_raw: Optional[np.ndarray] = None
        self._spec_key = None
        self._spec: Optional[dict] = None
        self._cq_frs_gen = -1
        self._cq_frs_map: dict[str, set] = {}
        self._build_seq = 0
        self.exports = 0
        #: timing/mode telemetry of the most recent export (the engine
        #: folds this into the CycleLedger export phase breakdown)
        self.last_stats: dict = {}

    # -- event feed --------------------------------------------------------

    def note_dirty(self, key: str) -> None:
        """Called by ExportCache._on_event for every Workload event."""
        self._log.append(key)
        if len(self._log) >= _LOG_CAP:
            # Compact: positions into the log die, so anything that
            # relied on them (block row currency, assembly patch sets)
            # must rebuild from scratch on the next export.
            self._log = []
            self._log_epoch += 1
            self._blocks.clear()
            self._key_home.clear()
            self._asms.clear()

    # -- spec-keyed derived state -----------------------------------------

    def _cq_frs(self, name: str, spec_gen: int) -> set:
        """(flavor, resource) vocabulary contribution of one CQ's
        resource groups — the classic per-pending-info expansion, keyed
        per CQ per spec generation."""
        if self._cq_frs_gen != spec_gen:
            self._cq_frs_map = {}
            self._cq_frs_gen = spec_gen
        s = self._cq_frs_map.get(name)
        if s is None:
            cq = self.cache.store.cluster_queues[name]
            s = {(fq.name, r) for rg in cq.resource_groups
                 for fq in rg.flavors for r in rg.covered_resources}
            self._cq_frs_map[name] = s
        return s

    def _spec_state(self, spec_gen: int, fr_list: list, forest,
                    nodes: list) -> dict:
        """Node-structural and CQ arrays (everything in the classic
        export that depends only on specs + the FR vocabulary, not on
        usage or the backlog), cached per (spec_gen, fr vocabulary)."""
        key = (spec_gen, tuple(fr_list))
        if self._spec_key == key:
            return self._spec

        store = self.cache.store
        fr_index = {fr: i for i, fr in enumerate(fr_list)}
        F = max(1, len(fr_list))
        n_nodes = len(nodes)
        null = n_nodes
        index = {id(n): i for i, n in enumerate(nodes)}

        parent = np.full(n_nodes + 1, null, dtype=np.int32)
        depth = np.zeros(n_nodes + 1, dtype=np.int32)
        has_parent = np.zeros(n_nodes + 1, dtype=bool)
        nominal = np.zeros((n_nodes + 1, F), dtype=np.int64)
        subtree = np.zeros((n_nodes + 1, F), dtype=np.int64)
        local_quota = np.zeros((n_nodes + 1, F), dtype=np.int64)
        has_borrow = np.zeros((n_nodes + 1, F), dtype=bool)
        borrow_limit = np.zeros((n_nodes + 1, F), dtype=np.int64)
        for i, n in enumerate(nodes):
            if n.parent is not None:
                parent[i] = index[id(n.parent)]
                has_parent[i] = True
                depth[i] = depth[parent[i]] + 1
            for fr, q in n.quotas.items():
                j = fr_index[fr]
                nominal[i, j] = q.nominal
                if q.borrowing_limit is not None:
                    has_borrow[i, j] = True
                    borrow_limit[i, j] = q.borrowing_limit
            for fr, v in n.subtree_quota.items():
                subtree[i, fr_index[fr]] = v
            for j, fr in enumerate(fr_list):
                local_quota[i, j] = n.local_quota(fr)

        D = int(depth.max()) + 1 if n_nodes else 1
        path = np.full((n_nodes + 1, D), null, dtype=np.int32)
        for i, n in enumerate(nodes):
            cur, d = i, 0
            while cur != null and d < D:
                path[i, d] = cur
                cur = parent[cur]
                d += 1

        height = np.zeros(n_nodes + 1, dtype=np.int32)
        for i in range(n_nodes - 1, -1, -1):
            n = nodes[i]
            h = min(len(n.children), 1)
            for c in n.children.values():
                if not c.is_cq:
                    h = max(h, height[index[id(c)]] + 1)
            height[i] = h

        cq_names = sorted(forest.cqs.keys())
        C = len(cq_names)
        cq_node = np.zeros(C, dtype=np.int32)
        cq_strict = np.zeros(C, dtype=bool)
        cq_try_next = np.zeros(C, dtype=bool)
        cq_root_height = np.zeros(C, dtype=np.int32)
        cq_nflavors = np.zeros(C, dtype=np.int32)
        cq_within_policy = np.zeros(C, dtype=np.int32)
        cq_reclaim_policy = np.zeros(C, dtype=np.int32)
        cq_bwc_forbidden = np.zeros(C, dtype=bool)
        cq_bwc_threshold = np.full(C, T.NO_THRESHOLD, dtype=np.int32)
        cq_preempt_try_next = np.zeros(C, dtype=bool)
        cq_pref_pob = np.zeros(C, dtype=bool)
        cq_fair_weight = np.ones(C, dtype=np.float32)
        cq_root = np.zeros(C, dtype=np.int32)
        cq_ngroups = np.ones(C, dtype=np.int32)
        cq_afs_spec = np.zeros(C, dtype=bool)
        cq_option_flavors: dict[str, list[str]] = {}
        cq_resource_group: dict[str, dict[str, int]] = {}
        cq_options: dict[str, list[tuple[int, str]]] = {}
        K = 1
        for cid, name in enumerate(cq_names):
            spec = store.cluster_queues[name]
            node = forest.cqs[name]
            cq_node[cid] = index[id(node)]
            cq_strict[cid] = (spec.queueing_strategy
                              == T.QueueingStrategy.STRICT_FIFO)
            cq_try_next[cid] = (
                spec.flavor_fungibility.when_can_borrow
                == T.FlavorFungibilityPolicy.TRY_NEXT_FLAVOR)
            cq_preempt_try_next[cid] = (
                spec.flavor_fungibility.when_can_preempt
                == T.FlavorFungibilityPolicy.TRY_NEXT_FLAVOR)
            cq_pref_pob[cid] = (
                spec.flavor_fungibility.preference
                == T.FlavorFungibilityPreference.PREEMPTION_OVER_BORROWING)
            cq_root_height[cid] = height[index[id(node.root())]]
            cq_root[cid] = index[id(node.root())]
            cq_within_policy[cid] = T._POLICY_CODE[
                spec.preemption.within_cluster_queue]
            cq_reclaim_policy[cid] = T._POLICY_CODE[
                spec.preemption.reclaim_within_cohort]
            bwc = spec.preemption.borrow_within_cohort
            cq_bwc_forbidden[cid] = (
                bwc.policy == T.PreemptionPolicyValue.NEVER)
            if bwc.max_priority_threshold is not None:
                cq_bwc_threshold[cid] = bwc.max_priority_threshold
            cq_fair_weight[cid] = spec.fair_sharing.weight
            scope = spec.admission_scope
            cq_afs_spec[cid] = (
                scope is not None
                and scope.admission_mode == "UsageBasedAdmissionFairSharing")
            options: list[tuple[int, str]] = []
            rg_of_resource: dict[str, int] = {}
            for g, rg in enumerate(spec.resource_groups):
                for r in rg.covered_resources:
                    rg_of_resource[r] = g
                for fq in rg.flavors:
                    options.append((g, fq.name))
            cq_options[name] = options
            cq_option_flavors[name] = [f for _, f in options]
            cq_resource_group[name] = rg_of_resource
            cq_ngroups[cid] = max(1, len(spec.resource_groups))
            cq_nflavors[cid] = len(options)
            K = max(K, len(options))

        cq_opt_group = np.full((C, K), -1, dtype=np.int32)
        for cid, name in enumerate(cq_names):
            for k, (g, _) in enumerate(cq_options[name]):
                cq_opt_group[cid, k] = g

        resources = sorted({fr[1] for fr in fr_list}) or ["_"]
        res_index = {r: i for i, r in enumerate(resources)}
        fr_resource = np.asarray(
            [res_index[fr[1]] for fr in fr_list] or [0], dtype=np.int32)
        node_fair_weight = np.ones(n_nodes + 1, dtype=np.float32)
        for i, n in enumerate(nodes):
            node_fair_weight[i] = n.fair_weight
        node_names = [n.name for n in nodes]

        self._spec = dict(
            fr_list=list(fr_list), fr_index=fr_index, F=F,
            n_nodes=n_nodes, parent=parent, depth=depth,
            has_parent=has_parent, path=path, height=height,
            nominal=nominal, subtree=subtree, local_quota=local_quota,
            has_borrow=has_borrow, borrow_limit=borrow_limit,
            cq_names=cq_names, C=C, cq_node=cq_node, cq_strict=cq_strict,
            cq_try_next=cq_try_next, cq_root_height=cq_root_height,
            cq_nflavors=cq_nflavors, cq_within_policy=cq_within_policy,
            cq_reclaim_policy=cq_reclaim_policy,
            cq_bwc_forbidden=cq_bwc_forbidden,
            cq_bwc_threshold=cq_bwc_threshold,
            cq_preempt_try_next=cq_preempt_try_next,
            cq_pref_pob=cq_pref_pob, cq_fair_weight=cq_fair_weight,
            cq_root=cq_root, cq_ngroups=cq_ngroups,
            cq_opt_group=cq_opt_group, cq_afs_spec=cq_afs_spec,
            cq_afs_zero=np.zeros(C, dtype=bool),
            cq_id={name: i for i, name in enumerate(cq_names)},
            cq_option_flavors=cq_option_flavors,
            cq_resource_group=cq_resource_group, K=K,
            n_resources=len(resources), fr_resource=fr_resource,
            node_fair_weight=node_fair_weight, node_names=node_names)
        self._spec_key = key
        return self._spec

    def _usage0(self, spec: dict, nodes: list) -> np.ndarray:
        """Unscaled node usage matrix, keyed per (snapshot, vocabulary)."""
        key = (self._snap_mark, tuple(spec["fr_list"]))
        if self._usage_key == key:
            return self._usage_raw
        fr_index = spec["fr_index"]
        usage0 = np.zeros((spec["n_nodes"] + 1, spec["F"]), dtype=np.int64)
        for i, n in enumerate(nodes):
            for fr, v in n.usage.items():
                usage0[i, fr_index[fr]] = v
        self._usage_key = key
        self._usage_raw = usage0
        return usage0

    # -- block maintenance -------------------------------------------------

    def _build_block(self, bk: tuple, infos: list, spec: dict,
                     stamp: tuple) -> _Block:
        old = self._blocks.get(bk)
        cache = self.cache
        cq_id = spec["cq_id"]
        cq_strict = spec["cq_strict"]
        cq_root = spec["cq_root"]
        K, F = spec["K"], spec["F"]
        blk = _Block(bk[0])
        n = len(infos)
        blk.infos = infos
        blk.keys = [i.key for i in infos]
        cids = np.zeros(n, dtype=np.int32)
        rows = []
        cq_set = set()
        for idx, info in enumerate(infos):
            cid = cq_id[info.cluster_queue]
            cids[idx] = cid
            cq_set.add(info.cluster_queue)
            rows.append(cache.row(info, cid, stamp, bool(cq_strict[cid]),
                                  int(cq_root[cid]), K, F))
        blk.cids = cids
        blk.rows = rows
        blk.prio = np.fromiter((r.prio for r in rows), np.int64, n)
        blk.uid = np.fromiter((r.uid for r in rows), np.int64, n)
        blk.raw_ts = np.fromiter((r.raw_ts for r in rows), np.float64, n)
        blk.evicted = np.fromiter((r.evicted for r in rows), bool, n)
        blk.shape_id = np.fromiter((r.shape_id for r in rows), np.int64, n)
        blk.class_tok = np.fromiter((r.class_tok for r in rows),
                                    np.int64, n)
        blk.admit_ts = np.fromiter((r.admit_ts for r in rows),
                                   np.float64, n)
        blk.cq_frs = set()
        if bk[0] == "h":
            for name in cq_set:
                blk.cq_frs |= self._cq_frs(name, cache.spec_gen)
        if bk[0] == "a":
            self._admitted_usage(blk)
        blk._pos = None
        # The queue manager re-wraps a workload in a fresh WorkloadInfo
        # on every update, so content-only churn still fails the
        # membership identity compare. When the key sequence (and CQ
        # assignment) is unchanged, this rebuild is content-only: keep
        # the membership seq stable and log the rows that actually
        # moved, so the scatter path and the delta hint see O(dirty)
        # changed rows instead of a membership change.
        if (old is not None and old.kind == blk.kind and blk.kind != "a"
                and old.keys == blk.keys
                and np.array_equal(old.cids, blk.cids)):
            blk.member_seq = old.member_seq
            diff = ((old.prio != blk.prio) | (old.uid != blk.uid)
                    | (old.raw_ts != blk.raw_ts)
                    | (old.evicted != blk.evicted)
                    | (old.shape_id != blk.shape_id)
                    | (old.class_tok != blk.class_tok)
                    | (old.admit_ts != blk.admit_ts))
            for idx in np.nonzero(diff)[0]:
                self._log.append(blk.keys[idx])
        else:
            self._build_seq += 1
            blk.member_seq = self._build_seq
        blk.log_pos = len(self._log)
        blk.events_mark = cache.events_seen
        self._blocks[bk] = blk
        for k in blk.keys:
            self._key_home[k] = bk
        return blk

    @staticmethod
    def _admitted_usage(blk: _Block) -> None:
        """(Re)build the admitted block's COO usage triplets from its
        cached rows — O(admitted) list walk, no cache.row calls."""
        u_rows, u_fs, u_qs = [], [], []
        for li, r in enumerate(blk.rows):
            if r.usage_fs is not None and r.usage_fs.size:
                u_rows.append(np.full(r.usage_fs.size, li,
                                      dtype=np.int64))
                u_fs.append(r.usage_fs)
                u_qs.append(r.usage_qs)
        blk.u_rows = _concat(u_rows, np.int64)
        blk.u_fs = _concat(u_fs, np.int64)
        blk.u_qs = _concat(u_qs, np.int64)

    def _patch_valid_rows(self, order: list, valid: dict,
                          spec: dict, stamp: tuple) -> None:
        """Bring every membership-valid block current with the dirty
        log in ONE pass over the log tail, routed through
        ``_key_home`` — the per-block scan this replaces probed every
        dirty key against every block, O(blocks x dirty) per export at
        fleet scale. Entries below a block's own log_pos re-apply
        idempotently (the row rebuild reads current cache state), so
        the shared tail needs no per-block slicing. Raises _Restart
        when a row's CQ drifted (that is a membership-level change in
        disguise)."""
        log_len = len(self._log)
        targets = {bk: self._blocks[bk] for bk in order
                   if valid.get(bk) and bk in self._blocks}
        start = min((b.log_pos for b in targets.values()),
                    default=log_len)
        if start >= log_len:
            return
        cache = self.cache
        cq_id = spec["cq_id"]
        cq_strict = spec["cq_strict"]
        cq_root = spec["cq_root"]
        K, F = spec["K"], spec["F"]
        touched_admitted = None
        for key in set(self._log[start:]):
            bk = self._key_home.get(key)
            blk = targets.get(bk)
            if blk is None:
                continue  # gone, or its block rebuilds below anyway
            idx = blk.pos().get(key)
            if idx is None:
                continue
            info = blk.infos[idx]
            cid = cq_id.get(info.cluster_queue)
            if cid is None or cid != blk.cids[idx]:
                del self._blocks[bk]
                raise _Restart
            r = cache.row(info, cid, stamp, bool(cq_strict[cid]),
                          int(cq_root[cid]), K, F)
            blk.rows[idx] = r
            blk.prio[idx] = r.prio
            blk.uid[idx] = r.uid
            blk.raw_ts[idx] = r.raw_ts
            blk.evicted[idx] = r.evicted
            blk.shape_id[idx] = r.shape_id
            blk.class_tok[idx] = r.class_tok
            blk.admit_ts[idx] = r.admit_ts
            if blk.kind == "a":
                touched_admitted = blk
        if touched_admitted is not None:
            self._admitted_usage(touched_admitted)
        for blk in targets.values():
            blk.log_pos = log_len

    # -- export ------------------------------------------------------------

    def export(self, pending, include_admitted: bool = False,
               parked=None, afs=None, now: float = 0.0):
        """Columnar twin of :func:`tensors.export_problem`; returns
        ``None`` to hand the export back to the classic walk."""
        t0 = time.perf_counter()
        cache = self.cache
        store = cache.store
        events = cache.events_seen
        spec_gen = cache.spec_gen

        # Fresh snapshot only when the store moved: the cohort forest
        # and its usage are a pure function of (events, spec).
        if self._snap_mark != (events, spec_gen):
            self._snapshot = build_snapshot(store)
            self._nodes = T.order_nodes(self._snapshot.forest)
            self._snap_mark = (events, spec_gen)
            self._node_frs = None
        forest = self._snapshot.forest
        nodes = self._nodes
        if self._node_frs is None:
            frs: set = set()
            for n in nodes:
                frs.update(n.quotas.keys())
                frs.update(n.usage.keys())
            self._node_frs = frs

        # Section layout in classic walk order: pending, parked,
        # admitted. Each (section, CQ) is one block.
        order: list[tuple] = [("h", name) for name in pending]
        section_infos: dict[tuple, list] = {
            ("h", name): infos for name, infos in pending.items()}
        if parked:
            for name, infos in parked.items():
                order.append(("p", name))
                section_infos[("p", name)] = infos
        if include_admitted:
            order.append(("a",))

        walk_s = 0.0
        for _attempt in range(3):
            # Membership validation + FR vocabulary. A valid block's
            # vocabulary contribution is membership-derived, so its
            # cached expansion set stands in for the per-info walk.
            valid: dict[tuple, bool] = {}
            cq_union = set(self._node_frs)
            for bk in order:
                if bk[0] == "a":
                    blk = self._blocks.get(bk)
                    ok = blk is not None and blk.events_mark == events
                    if blk is not None and not ok:
                        # Row-granular revalidation: any store event
                        # used to retire the whole admitted section
                        # (O(admitted) row rebuild). Membership is a
                        # key/CQ sequence compare against a fresh info
                        # list; when it holds, swap in the fresh infos
                        # (rows rebuild from info content) and let the
                        # dirty log drive O(dirty) row patches instead.
                        infos = [i for i in store.admitted_infos()
                                 if i.cluster_queue in forest.cqs]
                        section_infos[bk] = infos
                        if (len(infos) == len(blk.infos)
                                and all(a is b or (
                                    a.key == b.key
                                    and a.cluster_queue
                                    == b.cluster_queue)
                                    for a, b in zip(infos, blk.infos))):
                            blk.infos = infos
                            blk.events_mark = events
                            ok = True
                    valid[bk] = ok
                    continue
                infos = section_infos[bk]
                blk = self._blocks.get(bk)
                ok = blk is not None and _infos_match(blk.infos, infos)
                valid[bk] = ok
                if bk[0] == "h":
                    if ok:
                        cq_union |= blk.cq_frs
                    else:
                        seen: set = set()
                        for info in infos:
                            name = info.cluster_queue
                            if name not in seen:
                                seen.add(name)
                                cq_union |= self._cq_frs(name, spec_gen)
            fr_list = sorted(cq_union)
            spec = self._spec_state(spec_gen, fr_list, forest, nodes)
            stamp = cache.refresh(fr_list, spec["cq_names"], spec["K"],
                                  spec["F"])
            cache.cq_tables(spec["cq_names"])
            if stamp != self._row_stamp:
                # Every cached row/shape/token was retired by
                # cache.refresh — blocks hold dangling references.
                self._blocks.clear()
                self._key_home.clear()
                self._asms.clear()
                self._row_stamp = stamp
                continue

            tw = time.perf_counter()
            try:
                rebuilt = 0
                self._patch_valid_rows(order, valid, spec, stamp)
                for bk in order:
                    if valid[bk]:
                        continue
                    if bk[0] == "a" and bk not in section_infos:
                        infos = [i for i in store.admitted_infos()
                                 if i.cluster_queue in spec["cq_id"]]
                        section_infos[bk] = infos
                    self._build_block(bk, section_infos[bk], spec,
                                      stamp)
                    rebuilt += 1
            except _Restart:
                walk_s += time.perf_counter() - tw
                continue
            walk_s += time.perf_counter() - tw
            break
        else:
            return self._bailout("retry_exhausted", t0, walk_s)

        # AFS-active exports thread per-LQ decayed penalties through a
        # per-row walk; bail to the classic path (rare, full-drain only).
        if afs is not None and spec["cq_afs_spec"].any():
            return self._bailout("afs_active", t0, walk_s)

        asm = self._asms.get(include_admitted)
        membership_ok = (
            asm is not None and asm.stamp == stamp
            and asm.log_epoch == self._log_epoch
            and asm.order == order
            and all(self._blocks[bk].member_seq == asm.build_seqs[bk]
                    for bk in order))
        mode = None if membership_ok else "assemble"

        if mode is None and asm.log_pos == len(self._log) \
                and asm.snap_mark == self._snap_mark:
            problem = self._refresh_cached(asm, spec)
            if problem is not None:
                self.exports += 1
                problem._columnar_hint = ColumnarHint(
                    asm.seq, asm.seq - 1, False, {}, "cached", asm.W)
                self.last_stats = {
                    "mode": "cached", "walk_s": walk_s,
                    "scatter_s": time.perf_counter() - t0 - walk_s,
                    "dirty_rows": 0, "blocks_rebuilt": 0, "rows": asm.W}
                return problem

        if mode is None:
            problem, changed, rescaled = self._patch_assembly(
                asm, spec, include_admitted)
            self.exports += 1
            # A unit-scale flip rewrites every quantity column, so the
            # changed-row positions no longer cover the diff — the
            # session must fall back to its full content diff.
            problem._columnar_hint = ColumnarHint(
                asm.seq, asm.seq - 1, rescaled, changed, "scatter",
                asm.W)
            self.last_stats = {
                "mode": "scatter", "walk_s": walk_s,
                "scatter_s": time.perf_counter() - t0 - walk_s,
                "dirty_rows": len(changed), "blocks_rebuilt": rebuilt,
                "rows": asm.W}
            return problem

        problem, asm = self._assemble(order, spec, stamp,
                                      include_admitted, afs)
        self.exports += 1
        label = "rebuild" if rebuilt == len(order) and order else "assemble"
        problem._columnar_hint = ColumnarHint(
            asm.seq, asm.seq - 1, True, {}, label, asm.W)
        self.last_stats = {
            "mode": label, "walk_s": walk_s,
            "scatter_s": time.perf_counter() - t0 - walk_s,
            "dirty_rows": 0, "blocks_rebuilt": rebuilt, "rows": asm.W}
        return problem

    def _bailout(self, reason: str, t0: float, walk_s: float):
        """A columnar export that degrades to the classic dict walk is
        a silent megascale regression unless accounted: counted by
        reason and stamped into ``last_stats`` so the engine's export
        phase (cycle ledger ``export_mode``) attributes the slow
        cycle."""
        from kueue_oss_tpu import metrics

        metrics.columnar_bailouts_total.inc(reason)
        self.last_stats = {
            "mode": f"bailout:{reason}", "walk_s": walk_s,
            "scatter_s": time.perf_counter() - t0 - walk_s,
            "dirty_rows": 0, "blocks_rebuilt": 0, "rows": 0}
        return None

    # -- cached path -------------------------------------------------------

    def _refresh_cached(self, asm: _Assembly, spec: dict):
        """Unchanged store: re-issue the cached problem, guarding the
        two pieces of shared interning that another export mode may
        have grown in between (the shape stack feeds the scale gcd; the
        token list is re-emitted verbatim as class_tok_root). Returns
        None when the gcd moved — the caller falls to the scatter path
        for a full rescale."""
        cache = self.cache
        if len(cache._shape_valid) != asm.stack_len:
            scale = self._scale_gcd(spec, asm.ad_usage_raw)
            if scale != asm.scale:
                return None
            asm.stack_len = len(cache._shape_valid)
        if len(cache._tok_root) != asm.tok_len:
            asm.problem = T.dataclasses.replace(
                asm.problem,
                class_tok_root=np.asarray(cache._tok_root,
                                          dtype=np.int32))
            asm.tok_len = len(cache._tok_root)
        asm.seq += 1
        return asm.problem

    # -- shared derivation helpers ----------------------------------------

    def _scale_gcd(self, spec: dict, ad_usage_raw: np.ndarray) -> int:
        usage0 = self._usage0(spec, self._nodes)
        scale = 0
        for arr in (spec["nominal"],
                    spec["borrow_limit"][spec["has_borrow"]],
                    usage0, spec["subtree"], spec["local_quota"],
                    self.cache.shape_matrices()[1], ad_usage_raw):
            flat = np.asarray(arr, dtype=np.int64).ravel()
            if flat.size:
                scale = math.gcd(scale, int(np.gcd.reduce(flat)))
        return max(scale, 1)

    @staticmethod
    def _scaled(a: np.ndarray, scale: int) -> np.ndarray:
        out = a // scale
        if out.size and out.max() >= T.MAX_QUANTITY:
            raise T.UnsupportedProblem(
                "quantities too large for int32 solver tensors")
        return out.astype(np.int32)

    def _class_densify(self, toks: np.ndarray, W: int, n_nodes: int):
        pos = toks >= 0
        if pos.any():
            uniq, inv_c = np.unique(toks[pos], return_inverse=True)
            n_classes = len(uniq)
            wl_class = np.full(W + 1, n_classes, dtype=np.int32)
            wl_class[np.nonzero(pos)[0]] = inv_c
            tok_root = np.asarray(self.cache._tok_root, dtype=np.int32)
            class_root = np.concatenate(
                [tok_root[uniq], [n_nodes]]).astype(np.int32)
        else:
            n_classes = 0
            wl_class = np.zeros(W + 1, dtype=np.int32)
            class_root = np.asarray([n_nodes], dtype=np.int32)
        return wl_class, class_root, n_classes

    def _ts_ranks(self, raw_ts_full: np.ndarray, W: int):
        from kueue_oss_tpu import features
        from kueue_oss_tpu.scheduler.preemption import (
            TIMESTAMP_PREEMPTION_BUFFER_S,
        )

        wl_ts = np.zeros(W + 1, dtype=np.int32)
        wl_ts_buf = np.zeros(W + 1, dtype=np.int32)
        n_ts = 0
        if W:
            raw_ts = raw_ts_full[:W]
            distinct_ts, inv_ts = np.unique(raw_ts, return_inverse=True)
            n_ts = len(distinct_ts)
            wl_ts[:W] = inv_ts
            if features.enabled("SchedulerTimestampPreemptionBuffer"):
                wl_ts_buf[:W] = np.searchsorted(
                    distinct_ts, raw_ts + TIMESTAMP_PREEMPTION_BUFFER_S,
                    side="right") - 1
            else:
                wl_ts_buf[:W] = inv_ts
        return wl_ts, wl_ts_buf, n_ts

    def _node_fields(self, spec: dict, scale: int, usage0: np.ndarray):
        scaled = self._scaled
        return dict(
            nominal=scaled(spec["nominal"], scale),
            subtree=scaled(spec["subtree"], scale),
            local_quota=scaled(spec["local_quota"], scale),
            borrow_limit=np.where(
                spec["has_borrow"],
                scaled(spec["borrow_limit"], scale),
                T.BIG).astype(np.int32),
            usage0=scaled(usage0, scale))

    # -- scatter (patch) path ---------------------------------------------

    def _patch_assembly(self, asm: _Assembly, spec: dict,
                        include_admitted: bool):
        """Membership-stable re-export: copy-on-write only the columns
        whose rows moved, re-derive only the groups whose inputs moved.
        The returned problem aliases every unchanged array of the
        previous one."""
        cache = self.cache
        old = asm.problem
        W = asm.W
        n_nodes = spec["n_nodes"]

        # Changed rows since this assembly = its slice of the dirty
        # log, mapped home. Keys outside this mode's sections (e.g. an
        # admitted workload's event against the lean assembly) fall out
        # here — their effect rides the node usage rebuild below.
        changed: dict[str, int] = {}
        per_block: dict[tuple, list] = {}
        if asm.log_pos < len(self._log):
            for key in set(self._log[asm.log_pos:]):
                bk = self._key_home.get(key)
                if bk is None or bk not in asm.offsets:
                    continue
                blk = self._blocks.get(bk)
                idx = blk.pos().get(key) if blk is not None else None
                if idx is None:
                    continue
                changed[key] = asm.offsets[bk] + idx
                per_block.setdefault(bk, []).append(idx)

        fields: dict = {}
        ts_changed = tok_changed = shape_changed = False
        admit_changed = ad_usage_changed = False
        if changed:
            gpos = np.fromiter(changed.values(), np.int64, len(changed))
            wl_prio = old.wl_prio.copy()
            wl_uid = old.wl_uid.copy()
            wl_evicted0 = old.wl_evicted0.copy()
            wl_raw_ts = old.wl_raw_ts.copy()
            new_toks = asm.toks.copy()
            new_shapes = asm.shape_ids.copy()
            for bk, idxs in per_block.items():
                blk = self._blocks[bk]
                off = asm.offsets[bk]
                li = np.asarray(idxs, dtype=np.int64)
                gi = li + off
                wl_prio[gi] = blk.prio[li]
                wl_uid[gi] = blk.uid[li]
                wl_evicted0[gi] = blk.evicted[li]
                if not ts_changed and np.any(
                        wl_raw_ts[gi] != blk.raw_ts[li]):
                    ts_changed = True
                wl_raw_ts[gi] = blk.raw_ts[li]
                if not tok_changed and np.any(
                        new_toks[gi] != blk.class_tok[li]):
                    tok_changed = True
                new_toks[gi] = blk.class_tok[li]
                if not shape_changed and np.any(
                        new_shapes[gi] != blk.shape_id[li]):
                    shape_changed = True
                new_shapes[gi] = blk.shape_id[li]
            fields.update(wl_prio=wl_prio, wl_uid=wl_uid,
                          wl_evicted0=wl_evicted0, wl_raw_ts=wl_raw_ts)
            asm.toks = new_toks
            asm.shape_ids = new_shapes
            # Admitted rows additionally carry an admission timestamp
            # (ranked below) and an admission-usage row; patch both
            # from the freshly rebuilt block rows.
            wl_raw_admit_ts = old.wl_raw_admit_ts
            for bk, idxs in per_block.items():
                blk = self._blocks[bk]
                if blk.kind != "a":
                    continue
                off = asm.offsets[bk]
                for li in idxs:
                    gi = off + li
                    r = blk.rows[li]
                    if wl_raw_admit_ts[gi] != r.admit_ts:
                        if wl_raw_admit_ts is old.wl_raw_admit_ts:
                            wl_raw_admit_ts = \
                                old.wl_raw_admit_ts.copy()
                        wl_raw_admit_ts[gi] = r.admit_ts
                        admit_changed = True
                    dense = np.zeros(asm.ad_usage_raw.shape[1],
                                     dtype=np.int64)
                    if r.usage_fs is not None and r.usage_fs.size:
                        dense[r.usage_fs] = r.usage_qs
                    if np.any(asm.ad_usage_raw[gi] != dense):
                        asm.ad_usage_raw[gi] = dense
                        ad_usage_changed = True
            if admit_changed:
                raw_admit = wl_raw_admit_ts[asm.n_pending:asm.W]
                distinct_admit, inv_a = np.unique(
                    raw_admit, return_inverse=True)
                wl_admit_rank = old.wl_admit_rank.copy()
                wl_admit_rank[asm.n_pending:asm.W] = inv_a + 1
                asm.n_admit_rank = len(distinct_admit)
                fields.update(
                    wl_raw_admit_ts=wl_raw_admit_ts,
                    wl_admit_rank=wl_admit_rank,
                    admit_rank_base=len(distinct_admit) + 2)
        else:
            wl_raw_ts = old.wl_raw_ts

        # Node usage + unit scale track every store event, changed rows
        # or not (an admitted workload's release shifts usage0 without
        # touching any exported row of a lean problem).
        usage0 = self._usage0(spec, self._nodes)
        scale = self._scale_gcd(spec, asm.ad_usage_raw)
        rescale = scale != asm.scale
        if rescale or self._node_key_moved(asm):
            fields.update(self._node_fields(spec, scale, usage0))

        if shape_changed or rescale:
            stack_valid, stack_req = cache.shape_matrices()
            wl_valid = old.wl_valid.copy()
            wl_req_raw = np.zeros((W + 1, spec["K"], spec["F"]),
                                  dtype=np.int64)
            if W:
                wl_req_raw[:W] = stack_req[asm.shape_ids]
                wl_valid[:W] = stack_valid[asm.shape_ids]
            fields["wl_req"] = self._scaled(wl_req_raw, scale)
            fields["wl_valid"] = wl_valid
        if include_admitted and (rescale or ad_usage_changed):
            fields["ad_usage"] = self._scaled(asm.ad_usage_raw, scale)

        if ts_changed:
            wl_ts, wl_ts_buf, n_ts = self._ts_ranks(wl_raw_ts, W)
            fields.update(wl_ts=wl_ts, wl_ts_buf=wl_ts_buf,
                          ts_evict_base=n_ts + 1)
            asm.n_ts = n_ts
        if tok_changed:
            wl_class, class_root, n_classes = self._class_densify(
                asm.toks, W, n_nodes)
            fields.update(
                wl_class=wl_class, class_root=class_root,
                n_classes=n_classes,
                wl_class_tok=np.concatenate(
                    [asm.toks, [-1]]).astype(np.int64))
            asm.n_classes = n_classes
        if len(cache._tok_root) != asm.tok_len:
            fields["class_tok_root"] = np.asarray(cache._tok_root,
                                                  dtype=np.int32)
            asm.tok_len = len(cache._tok_root)

        if fields:
            asm.problem = T.dataclasses.replace(old, **fields,
                                                scale=scale)
        asm.scale = scale
        asm.stack_len = len(cache._shape_valid)
        asm.snap_mark = self._snap_mark
        asm.log_pos = len(self._log)
        asm.seq += 1
        return asm.problem, changed, rescale

    def _node_key_moved(self, asm: _Assembly) -> bool:
        return asm.snap_mark != self._snap_mark

    # -- assemble path -----------------------------------------------------

    def _assemble(self, order: list, spec: dict, stamp: tuple,
                  include_admitted: bool, afs):
        """Concatenate block columns and run the vectorized tail of the
        classic walk. O(W) numpy, no per-row Python (changed blocks
        were already rebuilt)."""
        cache = self.cache
        blocks = [self._blocks[bk] for bk in order]
        sizes = [len(b.keys) for b in blocks]
        offsets: dict[tuple, int] = {}
        off = 0
        n_heap = n_pending = 0
        for bk, b, sz in zip(order, blocks, sizes):
            offsets[bk] = off
            off += sz
            if b.kind == "h":
                n_heap += sz
            if b.kind in ("h", "p"):
                n_pending += sz
        W = off
        C = spec["C"]
        K, F = spec["K"], spec["F"]
        n_nodes = spec["n_nodes"]

        cids = _concat([b.cids for b in blocks], np.int32)
        ranks = _concat(
            [np.arange(sz, dtype=np.int32) if b.kind == "h"
             else np.full(sz, int(T.BIG), dtype=np.int32)
             for b, sz in zip(blocks, sizes)], np.int32)
        wl_cqid = np.concatenate([cids, [C]]).astype(np.int32)
        wl_rank = np.concatenate([ranks, [T.BIG]]).astype(np.int32)

        wl_prio = np.zeros(W + 1, dtype=np.int32)
        wl_uid = np.zeros(W + 1, dtype=np.int32)
        wl_req = np.zeros((W + 1, K, F), dtype=np.int64)
        wl_valid = np.zeros((W + 1, K), dtype=bool)
        wl_admitted0 = np.zeros(W + 1, dtype=bool)
        wl_admitted0[n_pending:W] = True
        wl_parked0 = np.zeros(W + 1, dtype=bool)
        wl_parked0[n_heap:n_pending] = True
        wl_evicted0 = np.zeros(W + 1, dtype=bool)
        wl_admit_rank = np.zeros(W + 1, dtype=np.int32)
        ad_usage_raw = np.zeros((W + 1, F), dtype=np.int64)

        shape_ids = _concat([b.shape_id for b in blocks], np.int64)
        toks = _concat([b.class_tok for b in blocks], np.int64)
        wl_raw_ts = np.zeros(W + 1, dtype=np.float64)
        wl_raw_admit_ts = np.zeros(W + 1, dtype=np.float64)
        stack_valid, stack_req = cache.shape_matrices()
        if W:
            wl_prio[:W] = _concat([b.prio for b in blocks], np.int64)
            wl_uid[:W] = _concat([b.uid for b in blocks], np.int64)
            wl_evicted0[:W] = _concat([b.evicted for b in blocks], bool)
            wl_valid[:W] = stack_valid[shape_ids]
            wl_req[:W] = stack_req[shape_ids]
            wl_raw_ts[:W] = _concat([b.raw_ts for b in blocks],
                                    np.float64)

        wl_class, class_root, n_classes = self._class_densify(
            toks, W, n_nodes)
        wl_ts, wl_ts_buf, n_ts = self._ts_ranks(wl_raw_ts, W)

        n_admit_rank = 0
        if W > n_pending:
            admitted = [b for b in blocks if b.kind == "a"]
            raw_admit = _concat([b.admit_ts for b in admitted],
                                np.float64)
            wl_raw_admit_ts[n_pending:W] = raw_admit
            distinct_admit, inv_a = np.unique(raw_admit,
                                              return_inverse=True)
            n_admit_rank = len(distinct_admit)
            wl_admit_rank[n_pending:W] = inv_a + 1
            for bk, b in zip(order, blocks):
                if b.kind == "a" and b.u_rows.size:
                    ad_usage_raw[offsets[bk] + b.u_rows, b.u_fs] = b.u_qs

        usage0 = self._usage0(spec, self._nodes)
        scale = self._scale_gcd(spec, ad_usage_raw)
        scaled = self._scaled
        node_fields = self._node_fields(spec, scale, usage0)

        cq_afs = (spec["cq_afs_spec"] if afs is not None
                  else spec["cq_afs_zero"])
        wl_keys: list[str] = []
        for b in blocks:
            wl_keys.extend(b.keys)

        problem = T.SolverProblem(
            parent=spec["parent"],
            depth=spec["depth"],
            height=spec["height"],
            has_parent=spec["has_parent"],
            path=spec["path"],
            nominal=node_fields["nominal"],
            subtree=node_fields["subtree"],
            local_quota=node_fields["local_quota"],
            has_borrow=spec["has_borrow"],
            borrow_limit=node_fields["borrow_limit"],
            usage0=node_fields["usage0"],
            cq_node=spec["cq_node"],
            cq_strict=spec["cq_strict"],
            cq_try_next=spec["cq_try_next"],
            cq_root_height=spec["cq_root_height"],
            cq_nflavors=spec["cq_nflavors"],
            wl_cqid=wl_cqid,
            wl_rank=wl_rank,
            wl_prio=wl_prio,
            wl_ts=wl_ts,
            wl_uid=wl_uid,
            wl_req=scaled(wl_req, scale),
            wl_valid=wl_valid,
            wl_parked0=wl_parked0,
            wl_admitted0=wl_admitted0,
            wl_evicted0=wl_evicted0,
            wl_admit_rank=wl_admit_rank,
            ad_usage=scaled(ad_usage_raw, scale),
            cq_within_policy=spec["cq_within_policy"],
            cq_reclaim_policy=spec["cq_reclaim_policy"],
            cq_bwc_forbidden=spec["cq_bwc_forbidden"],
            cq_bwc_threshold=spec["cq_bwc_threshold"],
            cq_preempt_try_next=spec["cq_preempt_try_next"],
            cq_pref_pob=spec["cq_pref_pob"],
            cq_fair_weight=spec["cq_fair_weight"],
            cq_root=spec["cq_root"],
            cq_opt_group=spec["cq_opt_group"],
            cq_ngroups=spec["cq_ngroups"],
            fr_resource=spec["fr_resource"],
            node_fair_weight=spec["node_fair_weight"],
            wl_class=wl_class,
            class_root=class_root,
            n_classes=n_classes,
            wl_lq=np.zeros(W + 1, dtype=np.int32),
            wl_afs_penalty=np.zeros(W + 1, dtype=np.float32),
            wl_ts_buf=wl_ts_buf,
            lq_penalty0=np.asarray([0.0], dtype=np.float32),
            cq_afs=cq_afs,
            wl_raw_ts=wl_raw_ts,
            wl_raw_admit_ts=wl_raw_admit_ts,
            wl_class_tok=np.concatenate([toks, [-1]]).astype(np.int64),
            class_tok_root=np.asarray(cache._tok_root, dtype=np.int32),
            n_resources=spec["n_resources"],
            ts_evict_base=n_ts + 1,
            admit_rank_base=n_admit_rank + 2,
            fr_list=list(spec["fr_list"]),
            node_names=spec["node_names"],
            cq_names=spec["cq_names"],
            wl_keys=wl_keys,
            cq_option_flavors=spec["cq_option_flavors"],
            cq_resource_group=spec["cq_resource_group"],
            scale=scale,
        )

        prev = self._asms.get(include_admitted)
        asm = _Assembly()
        asm.order = list(order)
        asm.build_seqs = {bk: self._blocks[bk].member_seq for bk in order}
        asm.log_pos = len(self._log)
        asm.log_epoch = self._log_epoch
        asm.stamp = stamp
        asm.snap_mark = self._snap_mark
        asm.stack_len = len(cache._shape_valid)
        asm.tok_len = len(cache._tok_root)
        asm.scale = scale
        asm.problem = problem
        asm.seq = (prev.seq + 1) if prev is not None else 1
        asm.offsets = offsets
        asm.n_heap = n_heap
        asm.n_pending = n_pending
        asm.W = W
        asm.toks = toks
        asm.shape_ids = shape_ids
        asm.ad_usage_raw = ad_usage_raw
        asm.n_ts = n_ts
        asm.n_admit_rank = n_admit_rank
        asm.n_classes = n_classes
        self._asms[include_admitted] = asm
        return problem, asm


def _concat(parts: list, dtype) -> np.ndarray:
    arrs = [p for p in parts if len(p)]
    if not arrs:
        return np.zeros(0, dtype=dtype)
    return np.concatenate(arrs).astype(dtype, copy=False)
