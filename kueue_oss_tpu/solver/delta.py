"""Delta-sync solver sessions: stable row encodings, problem deltas,
and resident device state.

The round-5 numbers showed the remote solve path dominated by the wire:
every drain re-serialized and shipped the full padded 50k x 1k problem
(several MB) over the tunnel and re-uploaded it to the device. Aryl
(arxiv 2202.07896) and CvxCluster (arxiv 2605.01614) both keep the
allocation problem resident and re-solve incrementally; this module is
that move for the export -> upload -> solve -> download cycle:

- ``HostDeltaSession`` re-encodes each padded export into a **stable
  slot space** (a workload keeps its row for the life of the session;
  freed rows are recycled as inert padding) with **order-preserving
  stable ranks** for timestamps/admit-ranks and **stable class tokens**
  — so a churn cycle dirties only the rows whose workloads actually
  changed, not every row behind a dense re-ranking.
- ``compute_delta``/``apply_delta`` diff two consecutive encodings into
  a ``ProblemDelta`` (changed rows + small-array replacements + scalar
  meta updates) and replay it bit-identically on the other side.
- ``state_checksum`` is the cheap content checksum both sides compare
  after every DELTA application: any mismatch forces a full RESYNC
  (counted in metrics, never silently wrong).
- ``DeviceResidentProblem`` pins the padded problem tensors on device
  across drains and applies row deltas with ``.at[rows].set`` scatter
  updates, so neither the sidecar nor the in-process path re-uploads
  the full problem per cycle.

Correctness posture: the delta layer is *content-based* — deltas are
computed by comparing the actual encoded arrays, with the event-driven
dirty sets from ``ExportCache`` serving as statistics and fast-path
hints, so delta-applied state is bit-identical to a fresh full sync by
construction (property-tested in tests/test_solver_delta.py). Anything
the delta cannot express cheaply (shape growth, scale flips, renumber
events, >50% dirty rows) degrades to a full sync, and the engine's
plan-sanity guard still validates every imported plan.

Streaming interplay (scheduler/streaming.py): a sub-cycle
micro-admission is an ordinary store event — it dirties its
ExportCache row, the workload leaves the next export's pending set,
and its session slot recycles like any other departure. The content
diff ships exactly those rows at the next full solve, so resident
device tensors stay valid across arbitrarily many micro-drains with
no session reset and no full re-upload.
"""

from __future__ import annotations

import dataclasses
import io
import json
import zlib
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kueue_oss_tpu.solver.tensors import BIG, SolverProblem, pow2

#: SolverProblem fields that ride the wire as arrays. Host-only decode
#: tables (fr_list, wl_keys, ...) and the raw stable-encoding inputs
#: (wl_raw_ts, ...) stay on the host.
HOST_ONLY_FIELDS = (
    "fr_list", "node_names", "cq_names", "wl_keys", "cq_option_flavors",
    "cq_resource_group", "scale", "n_resources", "ts_evict_base",
    "admit_rank_base", "n_classes",
    "wl_raw_ts", "wl_raw_admit_ts", "wl_class_tok", "class_tok_root",
)
ARRAY_FIELDS = [
    f.name for f in dataclasses.fields(SolverProblem)
    if f.name not in HOST_ONLY_FIELDS
]
META_FIELDS = ["n_resources", "ts_evict_base", "admit_rank_base", "scale"]

#: workload-axis arrays ([W+1] leading dim): delta'd row-wise
W_AXIS_FIELDS = (
    "wl_cqid", "wl_rank", "wl_prio", "wl_ts", "wl_uid", "wl_req",
    "wl_valid", "wl_parked0", "wl_admitted0", "wl_evicted0",
    "wl_admit_rank", "ad_usage", "wl_class", "wl_lq", "wl_afs_penalty",
    "wl_ts_buf",
)
NON_W_FIELDS = tuple(f for f in ARRAY_FIELDS if f not in W_AXIS_FIELDS)

#: a delta dirtying more than this fraction of rows costs more than a
#: full sync saves; degrade (counted as reason="dense_delta")
DENSE_DELTA_FRACTION = 0.5


# ---------------------------------------------------------------------------
# content checksum
# ---------------------------------------------------------------------------


def state_checksum(kwargs: dict, meta: dict) -> int:
    """Cheap content checksum over the wire-visible problem state.

    crc32 chained over every present array's (name, dtype, shape,
    bytes) in canonical field order plus the meta scalars — both sides
    compute it over their own state after every sync/delta, so any
    divergence (a garbled frame that still decoded, an apply bug, a
    version skew) is caught before the next plan is trusted.
    """
    crc = 0
    for name in ARRAY_FIELDS:
        arr = kwargs.get(name)
        if arr is None:
            continue
        arr = np.ascontiguousarray(arr)
        head = f"{name}|{arr.dtype.str}|{arr.shape}".encode()
        crc = zlib.crc32(head, crc)
        crc = zlib.crc32(arr.tobytes(), crc)
    crc = zlib.crc32(json.dumps(
        {k: int(meta[k]) for k in META_FIELDS}, sort_keys=True).encode(),
        crc)
    return crc & 0xFFFFFFFF


def problem_wire_state(problem: SolverProblem) -> tuple[dict, dict]:
    """Split a problem into (array kwargs, meta) in wire form."""
    kwargs = {name: getattr(problem, name) for name in ARRAY_FIELDS}
    meta = {name: int(getattr(problem, name)) for name in META_FIELDS}
    return kwargs, meta


# ---------------------------------------------------------------------------
# ProblemDelta
# ---------------------------------------------------------------------------


@dataclass
class ProblemDelta:
    """Row-sparse diff between two consecutive session epochs."""

    epoch: int
    base_epoch: int
    #: checksum of the FULL post-apply state (not of the delta)
    checksum: int
    #: per W-axis array: (dirty row indices, new content at those rows).
    #: Per-array rows, not a union: one widely-dirty one-byte flag array
    #: (parked bits toggling as capacity-freed wakes ripple) must not
    #: drag every other array's bytes along with it.
    row_updates: dict[str, tuple[np.ndarray, np.ndarray]] = field(
        default_factory=dict)
    #: full replacements for changed non-workload arrays (node/CQ axes
    #: are small; usage/quota updates ride here)
    repl: dict[str, np.ndarray] = field(default_factory=dict)
    #: changed meta scalars (ts_evict_base and friends)
    meta_delta: dict[str, int] = field(default_factory=dict)
    #: emit statistics (dirty workloads/CQs seen, removed keys, ...)
    stats: dict = field(default_factory=dict)

    def payload_bytes(self) -> int:
        n = 0
        for idx, vals in self.row_updates.values():
            n += idx.nbytes + vals.nbytes
        for arr in self.repl.values():
            n += arr.nbytes
        return n


def compute_delta(prev_kwargs: dict, prev_meta: dict,
                  new_kwargs: dict, new_meta: dict,
                  epoch: int, base_epoch: int,
                  checksum: int) -> Optional[ProblemDelta]:
    """Diff two wire states; None means "too different — full sync".

    Incompatible = any array appearing/disappearing, any shape change
    (covers pad growth, vocabulary growth, class-space growth), a scale
    or resource-vocabulary flip (column meaning changes wholesale), or
    a dirty-row fraction above DENSE_DELTA_FRACTION.
    """
    for name in ARRAY_FIELDS:
        a, b = prev_kwargs.get(name), new_kwargs.get(name)
        if (a is None) != (b is None):
            return None
        if a is not None and (a.shape != b.shape or a.dtype != b.dtype):
            return None
    if (prev_meta["scale"] != new_meta["scale"]
            or prev_meta["n_resources"] != new_meta["n_resources"]):
        return None

    W1 = new_kwargs["wl_cqid"].shape[0]
    mask = np.zeros(W1, dtype=bool)
    row_updates: dict[str, tuple[np.ndarray, np.ndarray]] = {}
    for name in W_AXIS_FIELDS:
        a, b = prev_kwargs.get(name), new_kwargs.get(name)
        if a is None:
            continue
        neq = a != b
        if neq.ndim > 1:
            neq = neq.reshape(W1, -1).any(axis=1)
        if neq.any():
            idx = np.nonzero(neq)[0].astype(np.int32)
            row_updates[name] = (idx, np.ascontiguousarray(b[idx]))
            mask |= neq
    if int(mask.sum()) > W1 * DENSE_DELTA_FRACTION:
        return None
    repl = {}
    for name in NON_W_FIELDS:
        a, b = prev_kwargs.get(name), new_kwargs.get(name)
        if a is None:
            continue
        if not np.array_equal(a, b):
            repl[name] = np.ascontiguousarray(b)
    meta_delta = {k: int(new_meta[k]) for k in META_FIELDS
                  if prev_meta[k] != new_meta[k]}
    return ProblemDelta(epoch=epoch, base_epoch=base_epoch,
                        checksum=checksum, row_updates=row_updates,
                        repl=repl, meta_delta=meta_delta)


def apply_delta(kwargs: dict, meta: dict, delta: ProblemDelta) -> None:
    """Replay a delta onto (kwargs, meta) in place — the sidecar's (and
    the tests') reconstruction path. Bit-identical by construction with
    the state compute_delta diffed against; verified via checksum."""
    for name, (idx, vals) in delta.row_updates.items():
        kwargs[name][idx] = vals
    for name, arr in delta.repl.items():
        kwargs[name] = arr
    meta.update(delta.meta_delta)


def serialize_delta(delta: ProblemDelta) -> tuple[dict, bytes]:
    arrays = {}
    for name, (idx, vals) in delta.row_updates.items():
        arrays[f"ri__{name}"] = idx
        arrays[f"rv__{name}"] = vals
    for name, arr in delta.repl.items():
        arrays[f"a__{name}"] = arr
    buf = io.BytesIO()
    # deltas are small and highly structured (runs of consecutive row
    # indices, uniform flag toggles), so deflate pays for itself many
    # times over; the bulk SYNC frame stays uncompressed — it is the
    # once-per-session latency-critical upload
    np.savez_compressed(buf, **arrays)
    header = {"epoch": delta.epoch, "base_epoch": delta.base_epoch,
              "checksum": delta.checksum,
              "meta_delta": {k: int(v)
                             for k, v in delta.meta_delta.items()},
              "stats": delta.stats}
    return header, buf.getvalue()


def deserialize_delta(header: dict, blob: bytes) -> ProblemDelta:
    data = np.load(io.BytesIO(blob))
    row_updates, repl = {}, {}
    for name in data.files:
        if name.startswith("ri__"):
            row_updates[name[4:]] = (data[name], data["rv__" + name[4:]])
        elif name.startswith("a__"):
            repl[name[3:]] = data[name]
    return ProblemDelta(
        epoch=int(header["epoch"]), base_epoch=int(header["base_epoch"]),
        checksum=int(header["checksum"]), row_updates=row_updates,
        repl=repl,
        meta_delta={k: int(v)
                    for k, v in (header.get("meta_delta") or {}).items()},
        stats=dict(header.get("stats") or {}))


# ---------------------------------------------------------------------------
# order-preserving stable ranks
# ---------------------------------------------------------------------------


class StableRanker:
    """Order-preserving integer ranks for a growing set of floats.

    Dense ``np.unique`` ranks shift wholesale when an early value
    leaves the set — one finished workload would dirty every later
    row's timestamp rank. Stable ranks preserve order AND identity:
    once a value has a rank it keeps it; new values get gap midpoints
    (appends, the common churn case, get max+GAP). The kernels only
    compare ranks, so any order-embedding is semantically identical to
    the dense encoding. Gap exhaustion or int32-headroom overflow
    renumbers everything (``renumbers`` counts it; the session turns a
    renumber into a full sync).
    """

    def __init__(self, gap: int = 1 << 10,
                 max_rank: int = 1 << 29) -> None:
        self.gap = gap
        self.max_rank = max_rank
        self._values = np.zeros(0, dtype=np.float64)
        self._ranks = np.zeros(0, dtype=np.int64)
        self.renumbers = 0

    def update(self, values: np.ndarray) -> bool:
        """Register values; True if a renumber changed existing ranks."""
        distinct = np.unique(np.asarray(values, dtype=np.float64))
        if distinct.size == 0:
            return False
        if self._values.size == 0:
            self._values = distinct
            self._ranks = (np.arange(distinct.size, dtype=np.int64)
                           + 1) * self.gap
            return self._maybe_renumber(False)
        idx = np.searchsorted(self._values, distinct)
        present = np.zeros(distinct.size, dtype=bool)
        in_range = idx < self._values.size
        present[in_range] = (
            self._values[idx[in_range]] == distinct[in_range])
        new = distinct[~present]
        if new.size == 0:
            return False
        renumber = False
        tail = new[new > self._values[-1]]
        mid = new[new <= self._values[-1]]
        if mid.size:
            vals = self._values.tolist()
            ranks = self._ranks.tolist()
            for v in mid.tolist():
                i = bisect_left(vals, v)
                lo = ranks[i - 1] if i else 0
                hi = ranks[i]
                r = (lo + hi) // 2
                if r <= lo or r >= hi:
                    renumber = True  # gap exhausted at this position
                    r = lo
                vals.insert(i, v)
                ranks.insert(i, r)
            self._values = np.asarray(vals, dtype=np.float64)
            self._ranks = np.asarray(ranks, dtype=np.int64)
        if tail.size:
            base = int(self._ranks[-1]) if self._ranks.size else 0
            self._values = np.concatenate([self._values, tail])
            self._ranks = np.concatenate([
                self._ranks,
                base + (np.arange(tail.size, dtype=np.int64) + 1)
                * self.gap])
        return self._maybe_renumber(renumber)

    def _maybe_renumber(self, force: bool) -> bool:
        over = self._ranks.size and int(self._ranks[-1]) > self.max_rank
        if not (force or over):
            return False
        gap = self.gap
        while self._values.size * gap > self.max_rank and gap > 1:
            gap //= 2
        self._ranks = (np.arange(self._values.size, dtype=np.int64)
                       + 1) * gap
        self.renumbers += 1
        return True

    def rank(self, values: np.ndarray) -> np.ndarray:
        return self._ranks[np.searchsorted(self._values, values)]

    def rank_before(self, thresholds: np.ndarray) -> np.ndarray:
        """Rank of the largest registered value <= each threshold
        (callers guarantee at least one exists — each row's own value
        is registered)."""
        pos = np.searchsorted(self._values, thresholds, side="right") - 1
        return self._ranks[np.maximum(pos, 0)]

    @property
    def size(self) -> int:
        return int(self._values.size)

    @property
    def max(self) -> int:
        return int(self._ranks[-1]) if self._ranks.size else 0


# ---------------------------------------------------------------------------
# host-side session: slots + stable encodings + delta emission
# ---------------------------------------------------------------------------


@dataclass
class SessionFrame:
    """What one drain ships: a delta when possible, else a full sync."""

    epoch: int
    checksum: int
    delta: Optional[ProblemDelta]  # None => full SYNC required
    full_reason: Optional[str] = None  # why a sync (None when delta)
    stats: dict = field(default_factory=dict)


#: pad_workloads-equivalent inert fill per W-axis array; wl_cqid/wl_rank
#: fills are resolved at slot time (C / BIG). wl_uid fills with BIG so
#: a recycled slot can never alias a legitimate uid-0 workload.
_ROW_FILL = {
    "wl_prio": 0, "wl_ts": 0, "wl_uid": BIG, "wl_req": 0,
    "wl_valid": False, "wl_parked0": False, "wl_admitted0": False,
    "wl_evicted0": False, "wl_admit_rank": 0, "ad_usage": 0,
    "wl_lq": 0, "wl_afs_penalty": 0.0, "wl_ts_buf": 0,
    "wl_raw_ts": 0.0, "wl_raw_admit_ts": 0.0,
}


class HostDeltaSession:
    """Per-kind (lean/full) session state on the scheduler host.

    ``advance(padded_problem)`` returns the slot-stable, rank-stable
    re-encoding of the problem plus the SessionFrame to ship. One
    instance per kernel kind — the lean and full exports differ in
    content, so they are separate sessions on the wire too.
    """

    #: W-axis fields copied straight from the export row in the hint
    #: fast path — everything except the session-stable re-derivations
    #: (wl_ts/wl_ts_buf/wl_admit_rank/wl_class come from the rankers)
    _FAST_DIRECT = (
        "wl_cqid", "wl_rank", "wl_prio", "wl_uid", "wl_req", "wl_valid",
        "wl_parked0", "wl_admitted0", "wl_evicted0", "ad_usage",
        "wl_lq", "wl_afs_penalty")

    def __init__(self, cache=None,
                 neutral_fields: tuple[str, ...] = ()) -> None:
        #: optional ExportCache: per-workload/per-CQ dirty sets feed the
        #: frame stats and the no-change fast path
        self.cache = cache
        #: W-axis arrays this kernel kind never reads (the full kernel
        #: has no wl_rank — FIFO order rides the timestamp ranks), held
        #: at their inert fill so rank churn can't dirty the wire
        self.neutral_fields = tuple(neutral_fields)
        self.epoch = 0
        self._last: Optional[tuple[dict, dict]] = None
        self._last_keys: list[str] = []
        self._slots: dict[str, int] = {}
        self._free: list[int] = []
        self._capacity = -1
        self._ts = StableRanker()
        self._admit = StableRanker()
        self._class_cs = 2  # sticky pow2 class-space (>= max token + 2)
        self._event_mark = 0
        self.full_syncs = 0
        self.delta_syncs = 0
        #: slot->shard interleave width (1 = the classic smallest-slot
        #: policy). With a row-sharded mesh, smallest-slot packs every
        #: churn-era arrival into the low shards while departures
        #: hollow out the high ones — shard_imbalance drifts > 1 on
        #: long-lived sessions. Interleaving assigns new slots round-
        #: robin across the mesh's block shards instead.
        self._interleave = 1
        self._pending_interleave: Optional[int] = None
        #: interleave-change RESYNCs actually taken (epoch migrations)
        self.migrations = 0
        self._rr_cursor = 0
        #: columnar-hint fast path state: the previous slotted problem
        #: (its arrays alias ``_last``'s, so in-place row scatters keep
        #: both views coherent), the last consumed assembly seq, and
        #: the chained cheap checksum
        self._last_slotted: Optional[SolverProblem] = None
        self._hint_seq: Optional[int] = None
        #: when True (engine sets it on the LOCAL path only — no remote
        #: sidecar will recompute state_checksum), fast-path frames
        #: carry a chained checksum over the delta payload instead of
        #: an O(W) crc over the full state
        self.cheap_checksum = False
        self._fast_crc = 0
        self.fast_advances = 0

    # -- slot assignment ---------------------------------------------------

    def set_interleave(self, n_shards: int) -> None:
        """Request slot->shard interleaving over ``n_shards`` block
        shards. A width CHANGE is an epoch migration: the next advance
        re-lays every slot out (one full RESYNC, full_reason
        "interleave_migration", counted in ``migrations``) and resident
        device tensors rebuild once. Width 1 restores the classic
        smallest-slot policy byte-for-byte."""
        n = max(1, int(n_shards))
        if n != self._interleave:
            self._pending_interleave = n

    def _shard_of(self, slot: int) -> int:
        # block sharding over the PADDED axis (capacity + null row),
        # mirroring NamedSharding's layout; the null row rides the last
        # shard
        block = (self._capacity + 1) // self._interleave
        return min(slot // max(1, block), self._interleave - 1)

    def _assign_slots(self, keys: list[str]) -> Optional[np.ndarray]:
        """dst[i] = slot for exported row i (or None on capacity reset)."""
        present = {k for k in keys if k}
        for k in [k for k in self._slots if k not in present]:
            self._free.append(self._slots.pop(k))
        self._free.sort(reverse=True)  # pop() yields the smallest slot
        n = self._interleave
        if n > 1:
            by_shard: list[list[int]] = [[] for _ in range(n)]
            for s in self._free:  # descending, so pop() = smallest
                by_shard[self._shard_of(s)].append(s)
        dst = np.full(len(keys), -1, dtype=np.int64)
        for i, k in enumerate(keys):
            if not k:
                continue
            s = self._slots.get(k)
            if s is None:
                if not self._free:
                    return None  # capacity exhausted: reset + full sync
                if n > 1:
                    # round-robin shard choice; fall through occupied
                    # shards so capacity, not balance, is the only
                    # reset trigger
                    s = None
                    for d in range(n):
                        bucket = by_shard[(self._rr_cursor + d) % n]
                        if bucket:
                            s = bucket.pop()
                            break
                    self._rr_cursor = (self._rr_cursor + 1) % n
                    self._free.remove(s)
                else:
                    s = self._free.pop()
                self._slots[k] = s
            dst[i] = s
        return dst

    def _reset_slots(self, keys: list[str]) -> np.ndarray:
        self._slots = {}
        self._free = []
        dst = np.full(len(keys), -1, dtype=np.int64)
        n = self._interleave
        if n > 1:
            # striped re-layout: row i of the export lands in shard
            # i % n, at that shard's next sequential slot
            block = (len(keys) + 1) // n
            bounds = [min((s + 1) * block, len(keys)) for s in range(n)]
            cursor = [s * block for s in range(n)]
            live = 0
            for i, k in enumerate(keys):
                if not k:
                    continue
                s = None
                for d in range(n):
                    sh = (live + d) % n
                    if cursor[sh] < bounds[sh]:
                        s = cursor[sh]
                        cursor[sh] += 1
                        break
                live += 1
                if s is None:
                    continue  # > capacity: caller's pad guarantees room
                self._slots[k] = s
                dst[i] = s
            taken = set(self._slots.values())
            self._free = sorted(
                (s for s in range(len(keys)) if s not in taken),
                reverse=True)
            return dst
        nxt = 0
        for i, k in enumerate(keys):
            if k:
                self._slots[k] = nxt
                dst[i] = nxt
                nxt += 1
        self._free = list(range(len(keys) - 1, nxt - 1, -1))
        return dst

    # -- the per-drain step ------------------------------------------------

    def advance(self, problem: SolverProblem, hint=None
                ) -> tuple[SolverProblem, SessionFrame]:
        """Re-encode ``problem`` into slot space and emit its frame.

        ``hint`` is the export's ``ColumnarHint`` (solver/columnar.py)
        when the problem came off the columnar scatter/cached path: a
        contiguous-seq hint whose membership did not change lets the
        session scatter just the changed rows into the previous slotted
        encoding — O(dirty) instead of the O(W) permute + content diff.
        Every precondition failure falls back to the classic path,
        which diffs actual array content and is therefore always
        correct regardless of how far the fast path got.
        """
        if hint is not None and not hint.membership_changed:
            fast = self._advance_fast(problem, hint)
            if fast is not None:
                self._hint_seq = hint.seq
                return fast
        out = self._advance_classic(problem)
        self._hint_seq = hint.seq if hint is not None else None
        return out

    def _advance_classic(self, problem: SolverProblem
                         ) -> tuple[SolverProblem, SessionFrame]:
        full_reason = None
        W = problem.n_workloads
        keys = list(problem.wl_keys)
        if W != self._capacity:
            # padded capacity changed => compiled shapes changed anyway
            # (a pending interleave change rides along for free)
            self._capacity = W
            if self._pending_interleave is not None:
                self._interleave = self._pending_interleave
                self._pending_interleave = None
            dst = self._reset_slots(keys)
            full_reason = "shape_change" if self.epoch else "first_sync"
        elif self._pending_interleave is not None:
            # epoch migration: re-lay every slot out under the new
            # interleave width; ONE full RESYNC, resident device
            # tensors rebuild once on the other side
            self._interleave = self._pending_interleave
            self._pending_interleave = None
            self.migrations += 1
            dst = self._reset_slots(keys)
            full_reason = "interleave_migration"
        else:
            dst = self._assign_slots(keys)
            if dst is None:
                dst = self._reset_slots(keys)
                full_reason = "slot_reset"

        # rankers keep every timestamp ever seen so existing ranks never
        # move; once the dead fraction dominates (long-running sessions,
        # finished workloads' timestamps linger), reset them — the
        # wholesale rank change rides the full sync this forces, and the
        # memory/lookup cost stays proportional to the live problem
        active = sum(1 for k in keys if k)
        cap = max(4096, 4 * active)
        if self._ts.size > cap or self._admit.size > cap:
            self._ts = StableRanker()
            self._admit = StableRanker()
            full_reason = full_reason or "ranker_prune"

        slotted = self._permute(problem, dst)
        if self._restamp(slotted):
            full_reason = full_reason or "rank_renumber"

        kwargs, meta = problem_wire_state(slotted)
        checksum = state_checksum(kwargs, meta)
        self.epoch += 1
        stats = self._drain_stats(keys)
        delta = None
        if full_reason is None and self._last is not None:
            delta = compute_delta(self._last[0], self._last[1],
                                  kwargs, meta, epoch=self.epoch,
                                  base_epoch=self.epoch - 1,
                                  checksum=checksum)
            if delta is None:
                full_reason = "dense_delta"
            else:
                delta.stats = stats
        elif full_reason is None:
            full_reason = "first_sync"
        self._last = (kwargs, meta)
        self._last_keys = keys
        self._last_slotted = slotted
        if delta is None:
            self.full_syncs += 1
        else:
            self.delta_syncs += 1
        return slotted, SessionFrame(epoch=self.epoch, checksum=checksum,
                                     delta=delta,
                                     full_reason=full_reason, stats=stats)

    # -- columnar-hint O(dirty) advance ------------------------------------

    def _advance_fast(self, problem: SolverProblem, hint
                      ) -> Optional[tuple[SolverProblem, SessionFrame]]:
        """Scatter the hint's changed rows straight into the previous
        slotted encoding. Returns None when any precondition fails; the
        ranker registrations it may have done before bailing are
        harmless (the classic path re-registers idempotently and diffs
        actual content, so a renumber mid-bail just rides the diff)."""
        from kueue_oss_tpu import features
        from kueue_oss_tpu.scheduler.preemption import (
            TIMESTAMP_PREEMPTION_BUFFER_S,
        )

        prev = self._last_slotted
        if (prev is None or self._last is None or not self.epoch
                or self._hint_seq is None
                or hint.base_seq != self._hint_seq
                or problem.n_workloads != self._capacity
                or self._pending_interleave is not None):
            return None
        active = len(self._slots)
        cap = max(4096, 4 * active)
        if self._ts.size > cap or self._admit.size > cap:
            return None  # classic path prunes the rankers (full sync)
        kwargs, meta = self._last
        if (int(problem.scale) != meta["scale"]
                or int(problem.n_resources) != meta["n_resources"]):
            return None
        ckeys = list(hint.changed)
        slots = np.empty(len(ckeys), dtype=np.int64)
        rows = np.empty(len(ckeys), dtype=np.int64)
        for i, k in enumerate(ckeys):
            s = self._slots.get(k)
            if s is None:
                return None
            slots[i] = s
            rows[i] = hint.changed[k]
        if rows.size and int(rows.max()) >= problem.n_workloads:
            return None

        # new raw timestamps register into the rankers before anything
        # mutates: a renumber moves OTHER rows' ranks, and under the
        # preemption-buffer gate even a plain registry growth can move
        # other rows' buffered ranks — both degrade to classic
        new_raw = np.ascontiguousarray(problem.wl_raw_ts[rows])
        gate = features.enabled("SchedulerTimestampPreemptionBuffer")
        ts_size0 = self._ts.size
        if self._ts.update(new_raw):
            return None
        if gate and active and self._ts.size != ts_size0:
            return None
        new_adm = np.ascontiguousarray(problem.wl_admitted0[rows])
        new_raw_admit = np.ascontiguousarray(
            problem.wl_raw_admit_ts[rows])
        if new_adm.any() and self._admit.update(new_raw_admit[new_adm]):
            return None
        new_tok = np.ascontiguousarray(problem.wl_class_tok[rows])
        root = problem.class_tok_root
        max_tok = int(new_tok.max()) if new_tok.size else -1
        if root is not None:
            max_tok = max(max_tok, len(root) - 1)
        if pow2(max_tok + 2) > self._class_cs:
            return None  # class space must grow: shapes change
        for name in NON_W_FIELDS:
            if name == "class_root":
                continue  # session-derived, handled below
            a, b = kwargs.get(name), getattr(problem, name)
            if (a is None) != (b is None):
                return None
            if a is not None and (a.shape != np.shape(b)
                                  or a.dtype != np.asarray(b).dtype):
                return None

        # -- all preconditions hold; mutate the resident encoding. The
        # kwargs arrays alias the slotted problem's, so one scatter
        # updates the wire state and the returned problem together.
        row_updates: dict[str, tuple[np.ndarray, np.ndarray]] = {}

        def scatter(name: str, new_vals: np.ndarray) -> None:
            arr = kwargs.get(name)
            if arr is None or not slots.size:
                return
            old_vals = arr[slots]
            neq = old_vals != new_vals
            if neq.ndim > 1:
                neq = neq.reshape(len(ckeys), -1).any(axis=1)
            if not neq.any():
                return
            sub = np.nonzero(neq)[0]
            arr[slots[sub]] = new_vals[sub]
            row_updates[name] = (slots[sub].astype(np.int32),
                                 np.ascontiguousarray(new_vals[sub]))

        for name in self._FAST_DIRECT:
            if name in self.neutral_fields:
                continue
            src = getattr(problem, name)
            if src is None:
                continue
            scatter(name, np.ascontiguousarray(src[rows]))

        if slots.size:
            new_ts = self._ts.rank(new_raw).astype(np.int32)
            scatter("wl_ts", new_ts)
            if gate:
                scatter("wl_ts_buf", self._ts.rank_before(
                    new_raw
                    + TIMESTAMP_PREEMPTION_BUFFER_S).astype(np.int32))
            else:
                scatter("wl_ts_buf", new_ts)
            ar = np.zeros(len(ckeys), dtype=np.int32)
            if new_adm.any():
                ar[new_adm] = (self._admit.rank(new_raw_admit[new_adm])
                               + 1).astype(np.int32)
            scatter("wl_admit_rank", ar)
            scatter("wl_class", np.where(
                new_tok >= 0, new_tok,
                self._class_cs - 1).astype(np.int32))
            prev.wl_raw_ts[slots] = new_raw
            prev.wl_raw_admit_ts[slots] = new_raw_admit
            prev.wl_class_tok[slots] = new_tok

        repl: dict[str, np.ndarray] = {}
        cs = self._class_cs
        class_root = np.full(cs, problem.n_nodes, dtype=np.int32)
        if root is not None and len(root):
            class_root[:len(root)] = root
        if not np.array_equal(kwargs["class_root"], class_root):
            repl["class_root"] = class_root
            kwargs["class_root"] = class_root
            prev.class_root = class_root
        for name in NON_W_FIELDS:
            if name == "class_root":
                continue
            a, b = kwargs.get(name), getattr(problem, name)
            if a is None or np.array_equal(a, b):
                continue
            repl[name] = np.ascontiguousarray(b)
            kwargs[name] = repl[name]
            setattr(prev, name, repl[name])
        if root is not None:
            prev.class_tok_root = root

        meta_delta: dict[str, int] = {}
        new_meta = {"n_resources": int(problem.n_resources),
                    "scale": int(problem.scale),
                    "ts_evict_base": self._ts.max + 1,
                    "admit_rank_base": self._admit.max + 2}
        for k in META_FIELDS:
            if meta[k] != new_meta[k]:
                meta_delta[k] = new_meta[k]
                meta[k] = new_meta[k]
        prev.ts_evict_base = new_meta["ts_evict_base"]
        prev.admit_rank_base = new_meta["admit_rank_base"]
        # host-only scalars ride the export (n_classes and friends can
        # move without any wire array changing); the session-derived
        # rank bases above are the only scalars the session owns
        for f in dataclasses.fields(problem):
            if f.name in ("ts_evict_base", "admit_rank_base"):
                continue
            val = getattr(problem, f.name)
            if isinstance(val, (bool, int, float, np.integer,
                                np.floating)):
                setattr(prev, f.name, val)

        self.epoch += 1
        if self.cheap_checksum:
            checksum = self._delta_checksum(row_updates, repl,
                                            meta_delta)
        else:
            checksum = state_checksum(kwargs, meta)
        stats = self._drain_stats_fast(len(ckeys))
        delta = ProblemDelta(epoch=self.epoch, base_epoch=self.epoch - 1,
                             checksum=checksum, row_updates=row_updates,
                             repl=repl, meta_delta=meta_delta,
                             stats=stats)
        self.delta_syncs += 1
        self.fast_advances += 1
        return prev, SessionFrame(epoch=self.epoch, checksum=checksum,
                                  delta=delta, full_reason=None,
                                  stats=stats)

    def _delta_checksum(self, row_updates: dict, repl: dict,
                        meta_delta: dict) -> int:
        """Chained cheap checksum over the delta payload (local-path
        only): NOT comparable with ``state_checksum`` — the engine
        enables it only when no remote sidecar will verify frames, so a
        1M-row session does not pay an O(W) crc per drain."""
        crc = zlib.crc32(f"{self.epoch}|{self._fast_crc}".encode())
        for name in sorted(row_updates):
            idx, vals = row_updates[name]
            crc = zlib.crc32(name.encode(), crc)
            crc = zlib.crc32(np.ascontiguousarray(idx).tobytes(), crc)
            crc = zlib.crc32(np.ascontiguousarray(vals).tobytes(), crc)
        for name in sorted(repl):
            crc = zlib.crc32(name.encode(), crc)
            crc = zlib.crc32(
                np.ascontiguousarray(repl[name]).tobytes(), crc)
        crc = zlib.crc32(json.dumps(
            {k: int(v) for k, v in sorted(meta_delta.items())}).encode(),
            crc)
        self._fast_crc = crc & 0xFFFFFFFF
        return self._fast_crc

    def _drain_stats_fast(self, n_changed: int) -> dict:
        stats = {"removed_keys": 0, "added_keys": 0,
                 "fast_rows": n_changed}
        if self.cache is not None:
            stats["dirty_workloads"] = len(self.cache.dirty_keys)
            stats["dirty_cqs"] = len(self.cache.dirty_cqs)
            stats["events"] = self.cache.events_seen - self._event_mark
            self._event_mark = self.cache.events_seen
            self.cache.consume_dirty()
        return stats

    def last_sync_wire_bytes(self) -> int:
        """Wire payload of the most recent full-sync state — the
        byte-accounting counterpart of ``ProblemDelta.payload_bytes``
        for sync frames, owned here so ledger consumers (engine and
        streaming drains) never reach into ``_last`` internals."""
        if self._last is None:
            return 0
        return sum(int(getattr(a, "nbytes", 0))
                   for a in self._last[0].values())

    def _drain_stats(self, keys: list[str]) -> dict:
        prev = {k for k in self._last_keys if k}
        cur = {k for k in keys if k}
        stats = {"removed_keys": len(prev - cur),
                 "added_keys": len(cur - prev)}
        if self.cache is not None:
            stats["dirty_workloads"] = len(self.cache.dirty_keys)
            stats["dirty_cqs"] = len(self.cache.dirty_cqs)
            stats["events"] = self.cache.events_seen - self._event_mark
            self._event_mark = self.cache.events_seen
            self.cache.consume_dirty()
        return stats

    def _permute(self, problem: SolverProblem,
                 dst: np.ndarray) -> SolverProblem:
        """Rewrite the workload axis into slot space: out[slot] = row,
        free slots filled with the pad_workloads inert row."""
        W = problem.n_workloads
        C = problem.n_cqs
        occupied = dst >= 0
        src = np.nonzero(occupied)[0]
        slots = dst[occupied]
        updates: dict = {}
        for name in W_AXIS_FIELDS + ("wl_raw_ts", "wl_raw_admit_ts",
                                     "wl_class_tok"):
            arr = getattr(problem, name)
            if arr is None:
                continue
            if name == "wl_cqid":
                fill = C
            elif name == "wl_rank":
                fill = BIG
            elif name == "wl_class":
                fill = problem.n_classes
            elif name == "wl_class_tok":
                fill = -1
            else:
                fill = _ROW_FILL[name]
            out = np.full_like(arr, fill)
            if name not in self.neutral_fields:
                out[-1] = arr[-1]  # the null row stays last
                out[slots] = arr[src]
            updates[name] = out
        out_keys = [""] * W
        for i, s in zip(src, slots):
            out_keys[s] = problem.wl_keys[i]
        updates["wl_keys"] = out_keys
        return dataclasses.replace(problem, **updates)

    def _restamp(self, p: SolverProblem) -> bool:
        """Replace the dense per-export encodings (timestamp ranks,
        admit ranks, scheduling-class ids) with session-stable ones, in
        place on the slotted problem. Returns True when a ranker
        renumber invalidated previous ranks (forces a full sync).

        The kernels only *compare* these values (entry ordering, the
        newer-equal preemption test, candidate recency), so any
        order-preserving embedding is behaviorally identical to the
        dense ``np.unique`` ranks export_problem produces.
        """
        from kueue_oss_tpu import features
        from kueue_oss_tpu.scheduler.preemption import (
            TIMESTAMP_PREEMPTION_BUFFER_S,
        )

        W = p.n_workloads
        occ = p.wl_cqid[:W] < p.n_cqs
        renumbered = False
        raw_ts = p.wl_raw_ts[:W][occ]
        renumbered |= self._ts.update(raw_ts)
        p.wl_ts[:W][occ] = self._ts.rank(raw_ts).astype(np.int32)
        p.wl_ts[:W][~occ] = 0
        if features.enabled("SchedulerTimestampPreemptionBuffer"):
            p.wl_ts_buf[:W][occ] = self._ts.rank_before(
                raw_ts + TIMESTAMP_PREEMPTION_BUFFER_S).astype(np.int32)
        else:
            p.wl_ts_buf[:W][occ] = p.wl_ts[:W][occ]
        p.wl_ts_buf[:W][~occ] = 0
        p.ts_evict_base = self._ts.max + 1

        adm = occ & p.wl_admitted0[:W]
        if adm.any():
            raw_admit = p.wl_raw_admit_ts[:W][adm]
            renumbered |= self._admit.update(raw_admit)
            p.wl_admit_rank[:W] = 0
            p.wl_admit_rank[:W][adm] = (
                self._admit.rank(raw_admit) + 1).astype(np.int32)
        else:
            p.wl_admit_rank[:W] = 0
        p.admit_rank_base = self._admit.max + 2

        # stable scheduling-equivalence classes: raw interned tokens in
        # a sticky pow2 class space (sentinel = CS-1, shared by strict
        # and gate-off rows exactly like the dense sentinel n_classes)
        toks = p.wl_class_tok[:W]
        max_tok = int(toks.max()) if toks.size else -1
        if p.class_tok_root is not None:
            max_tok = max(max_tok, len(p.class_tok_root) - 1)
        self._class_cs = max(self._class_cs, pow2(max_tok + 2))
        cs = self._class_cs
        wl_class = np.full(W + 1, cs - 1, dtype=np.int32)
        pos = toks >= 0
        wl_class[:W][pos] = toks[pos]
        p.wl_class = wl_class
        class_root = np.full(cs, p.n_nodes, dtype=np.int32)
        if p.class_tok_root is not None and len(p.class_tok_root):
            class_root[:len(p.class_tok_root)] = p.class_tok_root
        p.class_root = class_root
        return bool(renumbered)


# ---------------------------------------------------------------------------
# resident device tensors (shared by the sidecar and the local path)
# ---------------------------------------------------------------------------

#: problem W-axis field -> ProblemTensors field (lean kernel)
_LEAN_ROW_TENSORS = {n: n for n in (
    "wl_cqid", "wl_rank", "wl_prio", "wl_ts", "wl_uid", "wl_req",
    "wl_valid")}
#: problem W-axis field -> FullTensors field
_FULL_ROW_TENSORS = {
    "wl_cqid": "wl_cqid", "wl_prio": "wl_prio", "wl_ts": "wl_ts0",
    "wl_uid": "wl_uid", "wl_req": "wl_req", "wl_valid": "wl_valid",
    "wl_parked0": "wl_parked0", "wl_admitted0": "wl_admitted0",
    "wl_evicted0": "wl_evicted0", "wl_admit_rank": "wl_admit_rank0",
    "ad_usage": "ad_usage", "wl_class": "wl_class", "wl_lq": "wl_lq",
    "wl_afs_penalty": "wl_afs_penalty", "wl_ts_buf": "wl_ts_buf",
}


def _tree_nbytes(t) -> int:
    return sum(int(getattr(a, "nbytes", 0)) for a in t)


class DeviceResidentProblem:
    """Padded problem tensors pinned on device across drains.

    A full sync uploads everything once; each delta epoch then updates
    only the dirty rows with a **donated** ``.at[rows].set`` scatter
    (plus the small node/CQ replacement arrays), so steady-state drains
    ship a few KB to the device instead of the whole padded problem —
    and the scatter itself reuses the resident buffer (XLA input/output
    aliasing) instead of materializing a second full padded copy.

    With a ``mesh``, BOTH kernels' workload-axis tensors live
    block-sharded over the mesh's ``wl`` axis (tree/CQ state
    replicated) whenever the padded axis divides evenly; donated
    scatters preserve the placement, so delta rows land directly on
    their owning shard. The full kernel additionally lane-shards its
    victim searches inside the solve — row and lane sharding compose
    (full_kernels._run_searches).
    """

    def __init__(self, mesh=None, axis: str = "wl") -> None:
        self.mesh = mesh
        self.axis = axis
        #: problems narrower than this stay unsharded even with a mesh
        #: (the mesh is the large-backlog path; callers set it from
        #: their mesh_min_workloads policy)
        self.mesh_min_rows = 0
        self.kind: Optional[str] = None
        self.epoch = -1
        self.tensors = None
        self.full_uploads = 0
        self.delta_updates = 0
        #: whether the CURRENT resident tensors are mesh-placed
        self.mesh_placed = False
        #: donated-scatter accounting for bench/diagnostics: bytes
        #: actually shipped by row updates vs the full-problem bytes a
        #: per-drain re-upload (or a non-donated scatter's output copy)
        #: would have materialized
        self.donated_update_bytes = 0
        self.avoided_copy_bytes = 0
        self.full_upload_bytes = 0
        #: full syncs that reused (donated) the previous epoch's
        #: resident buffers instead of allocating a second full set —
        #: forced-resync storms stop double-allocating device memory
        self.donated_full_syncs = 0
        #: _apply faults healed by a fresh full upload (never silent —
        #: the engine's mesh-fault accounting reads this)
        self.apply_faults = 0
        self._scatter_cache: dict = {}

    def resident_bytes(self) -> int:
        """Bytes of problem state currently pinned on device — the
        portable HBM-watermark bookkeeping obs/devtel.py gauges when
        the backend exposes no allocator stats (0 = nothing resident)."""
        return _tree_nbytes(self.tensors) if self.tensors is not None \
            else 0

    def update(self, problem: SolverProblem, frame: Optional[SessionFrame],
               full: bool):
        kind = "full" if full else "lean"
        delta = frame.delta if frame is not None else None
        if (delta is None or self.tensors is None or self.kind != kind
                or delta.base_epoch != self.epoch):
            self.tensors = self._full_upload(problem, full)
        else:
            try:
                self._apply(problem, delta, full)
            except Exception:
                # a partially-applied donated update leaves consumed
                # buffers behind; drop the resident state (so the heal
                # can never donate FROM consumed buffers) and re-seed
                # from the authoritative host problem
                self.apply_faults += 1
                self.tensors = None
                self.tensors = self._full_upload(problem, full)
        self.kind = kind
        self.epoch = frame.epoch if frame is not None else self.epoch + 1
        return self.tensors

    def _full_upload(self, problem: SolverProblem, full: bool):
        import jax
        import jax.numpy as jnp

        if full:
            from kueue_oss_tpu.solver.full_kernels import host_tensors_full

            host = host_tensors_full(problem)
        else:
            from kueue_oss_tpu.solver.kernels import host_tensors

            host = host_tensors(problem)
        kind = "full" if full else "lean"
        prev = self.tensors if self.kind == kind else None
        if prev is not None and self._donation_compatible(prev, host):
            # ROADMAP open item: a forced resync (shape-stable session
            # reset, checksum heal, chaos storm) used to allocate a
            # SECOND full set of resident buffers while the previous
            # epoch's set was still live. Donating the old buffers
            # rewrites them in place — same placement, no double
            # allocation — and rides the existing donated/avoided-copy
            # accounting. mesh_placed is preserved: identical shapes
            # keep the divisibility the original placement required.
            du, ac = self.donated_update_bytes, self.avoided_copy_bytes
            try:
                t = self._donated_overwrite(prev, host)
            except Exception:
                # roll back the per-buffer byte accounting of a
                # donation that did not complete, then re-seed fresh
                self.donated_update_bytes = du
                self.avoided_copy_bytes = ac
                self.apply_faults += 1
                self.mesh_placed = False
                t = jax.tree_util.tree_map(jnp.asarray, host)
            else:
                self.donated_full_syncs += 1
                self.full_uploads += 1
                self.full_upload_bytes += _tree_nbytes(t)
                return t
        else:
            self.mesh_placed = False
            t = jax.tree_util.tree_map(jnp.asarray, host)
        if self.mesh is not None:
            if full:
                from kueue_oss_tpu.solver.sharded import maybe_place_full

                t, self.mesh_placed = maybe_place_full(
                    t, problem, self.mesh, self.mesh_min_rows, self.axis)
            else:
                from kueue_oss_tpu.solver.sharded import maybe_place_lean

                t, self.mesh_placed = maybe_place_lean(
                    t, problem, self.mesh, self.mesh_min_rows, self.axis)
        self.full_uploads += 1
        self.full_upload_bytes += _tree_nbytes(t)
        return t

    @staticmethod
    def _donation_compatible(prev, host) -> bool:
        """Every resident buffer must match its replacement's shape and
        dtype exactly — XLA aliases donated inputs to outputs only then,
        and a mismatch means the compiled shapes changed anyway."""
        import numpy as np

        for old, new in zip(prev, host):
            new = np.asarray(new)
            if (tuple(old.shape) != tuple(new.shape)
                    or old.dtype != new.dtype):
                return False
        return True

    def _donated_overwrite(self, prev, host):
        """Rewrite every resident buffer in place with the new epoch's
        content (donated whole-array set; output aliases the donated
        input, preserving each buffer's sharding)."""
        import jax
        import numpy as np

        out = []
        for old, new in zip(prev, host):
            new = np.ascontiguousarray(new)
            self.donated_update_bytes += int(new.nbytes)
            self.avoided_copy_bytes += int(old.nbytes)
            sharding = getattr(old, "sharding", None)
            key = ("overwrite", old.shape, str(old.dtype), sharding)
            fn = self._scatter_cache.get(key)
            if fn is None:
                kw = {}
                if self.mesh_placed and sharding is not None:
                    kw["out_shardings"] = sharding
                fn = jax.jit(lambda b, v: b.at[...].set(v),
                             donate_argnums=0, **kw)
                self._scatter_cache[key] = fn
            out.append(fn(old, new))
        return type(prev)(*out)

    def _replicated(self, arr: np.ndarray):
        """Place a small replacement array consistently with the
        resident tensors (replicated over the mesh when sharded)."""
        import jax
        import jax.numpy as jnp

        if not self.mesh_placed:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(
            np.ascontiguousarray(arr),
            NamedSharding(self.mesh, PartitionSpec()))

    def _scatter(self, buf, idx: np.ndarray, vals: np.ndarray):
        """Donated row scatter: out aliases ``buf``, so no full padded
        copy is materialized per delta epoch. The dirty-row count is
        bucketed to a power of two (padded with idempotent repeats of
        the last row) so one jitted program per (shape, dtype, bucket,
        sharding) serves every epoch."""
        import jax

        self.donated_update_bytes += int(idx.nbytes) + int(vals.nbytes)
        self.avoided_copy_bytes += int(buf.nbytes)
        n = idx.shape[0]
        cap = pow2(max(1, n))
        if cap != n:
            idx = np.concatenate([idx, np.repeat(idx[-1:], cap - n)])
            vals = np.concatenate(
                [vals, np.repeat(vals[-1:], cap - n, axis=0)])
        sharding = getattr(buf, "sharding", None)
        key = (buf.shape, str(buf.dtype), cap, sharding)
        fn = self._scatter_cache.get(key)
        if fn is None:
            kw = {}
            if self.mesh_placed and sharding is not None:
                kw["out_shardings"] = sharding
            fn = jax.jit(lambda b, i, v: b.at[i].set(v),
                         donate_argnums=0, **kw)
            self._scatter_cache[key] = fn
        return fn(buf, idx, vals)

    def _apply(self, problem: SolverProblem, delta: ProblemDelta,
               full: bool) -> None:
        import jax.numpy as jnp

        t = self.tensors
        tensor_fields = set(t._fields)
        row_map = _FULL_ROW_TENSORS if full else _LEAN_ROW_TENSORS
        updates: dict = {}
        for name, (idx, vals) in delta.row_updates.items():
            tname = row_map.get(name)
            if tname is None:
                continue
            updates[tname] = self._scatter(
                getattr(t, tname), np.asarray(idx),
                np.ascontiguousarray(vals))
        for name, arr in delta.repl.items():
            if name in tensor_fields:
                updates[name] = self._replicated(arr)
        # derived fields whose inputs changed
        if "cq_node" in delta.repl or "parent" in delta.repl:
            is_cq = np.zeros(problem.parent.shape[0], dtype=bool)
            is_cq[problem.cq_node] = True
            updates["is_cq"] = self._replicated(is_cq)
        if full:
            if "cq_opt_group" in delta.repl:
                C, K = problem.cq_opt_group.shape
                opt_pos = np.zeros((C, K), dtype=np.int32)
                for c in range(C):
                    counts: dict[int, int] = {}
                    for k in range(K):
                        g = int(problem.cq_opt_group[c, k])
                        if g < 0:
                            continue
                        opt_pos[c, k] = counts.get(g, 0)
                        counts[g] = counts.get(g, 0) + 1
                updates["cq_opt_pos"] = jnp.asarray(opt_pos)
            if "fr_resource" in delta.repl:
                updates["res_onehot"] = jnp.asarray(np.eye(
                    problem.n_resources,
                    dtype=np.int32)[problem.fr_resource])
            if "ts_evict_base" in delta.meta_delta:
                updates["ts_evict_base"] = jnp.asarray(
                    problem.ts_evict_base, dtype=jnp.int32)
            if "admit_rank_base" in delta.meta_delta:
                updates["admit_rank_base"] = jnp.asarray(
                    problem.admit_rank_base, dtype=jnp.int32)
        if updates:
            self.tensors = t._replace(**updates)
        self.delta_updates += 1
