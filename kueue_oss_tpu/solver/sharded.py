"""Multi-chip SPMD drain: workloads sharded over a device mesh.

Scaling model: the workload axis (the dimension that grows — pending
backlogs of 10^5-10^7 entries) is sharded across the mesh's ``wl`` axis;
the node/quota state (10^3 nodes) is replicated. Each round needs three
small collectives, all riding ICI:

  1. per-CQ head rank:   pmin over a [C]-vector of local segment minima
  2. per-CQ head index:  pmin over a [C]-vector (two-pass argmin, int32)
  3. candidate payload:  psum of [C,K,F] request rows + [C] metadata
                         (each head lives on exactly one shard)

The nomination + admission scan then runs replicated (identical on every
device — it only touches [C]- and [N,F]-sized state), and each device
updates the admitted/parked/option/round plan state for its own workload
shard. This keeps per-round collective volume at ~C*K*F ints regardless
of backlog size.

The drain is the PRODUCTION lean path, not a dry-run harness: it
returns the full ``solve_backlog`` contract — (admitted, opt,
admit_round, parked, rounds, usage) — bit-identical to the single-chip
kernel on the same padded problem, so `SolverEngine` and the sidecar
route large backlogs here without changing a byte of the apply path
(engine mesh routing: solver/engine.py; placement + resident state:
solver/delta.py DeviceResidentProblem; detection: solver/meshutil.py).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kueue_oss_tpu.solver.kernels import (
    M_NOFIT,
    ProblemTensors,
    _round_scan,
    available_all,
    nominate,
    potential_available_all,
    refresh_cohort_usage,
)
from kueue_oss_tpu.solver.meshutil import pvary, shard_map
from kueue_oss_tpu.solver.tensors import BIG, SolverProblem

#: NamedSharding specs for the lean ProblemTensors: workload axis
#: sharded, node/CQ state replicated. Shared by the engine's resident
#: device state and the ad-hoc solve path below.
LEAN_WL_FIELDS = ("wl_cqid", "wl_rank", "wl_prio", "wl_ts", "wl_uid",
                  "wl_req", "wl_valid")


def pad_workloads(p: SolverProblem, multiple: int) -> SolverProblem:
    """Pad the workload axis so (W+1) divides evenly across the mesh.

    Padding rows replicate the null-workload row (rank BIG, null CQ id,
    no options), so they are never selected as heads. Fills must not
    alias real rows: ``wl_uid`` pads with BIG (a real uid-0 row must
    stay distinguishable from padding), every flag with its inert
    value.
    """
    W1 = p.wl_cqid.shape[0]
    target = ((W1 + multiple - 1) // multiple) * multiple
    pad = target - W1
    if pad == 0:
        return p
    C = p.cq_node.shape[0]

    def pad1(a, fill):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths, constant_values=fill)

    return dataclasses.replace(
        p,
        wl_cqid=pad1(p.wl_cqid, C),
        wl_rank=pad1(p.wl_rank, BIG),
        wl_prio=pad1(p.wl_prio, 0),
        wl_ts=pad1(p.wl_ts, 0),
        wl_uid=pad1(p.wl_uid, BIG),
        wl_req=pad1(p.wl_req, 0),
        wl_valid=pad1(p.wl_valid, False),
    )


def lean_shardings(mesh: Mesh, axis: str = "wl") -> dict:
    """field -> NamedSharding for mesh-placing lean problem tensors."""
    row = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    return {f: (row if f in LEAN_WL_FIELDS else rep)
            for f in ProblemTensors._fields}


def place_lean_tensors(t: ProblemTensors, mesh: Mesh,
                       axis: str = "wl") -> ProblemTensors:
    """Mesh-place lean tensors: workload rows block-sharded over the
    ``wl`` axis, tree/CQ state replicated. Requires an evenly divisible
    padded axis (meshutil.align_pad_target)."""
    n_dev = mesh.shape[axis]
    W1 = t.wl_cqid.shape[0]
    if W1 % n_dev != 0:
        raise ValueError(
            f"workload axis of {W1} rows does not shard over {n_dev} "
            "devices; pad with meshutil.align_pad_target first")
    sh = lean_shardings(mesh, axis)
    return t._replace(**{
        f: jax.device_put(getattr(t, f), sh[f])
        for f in ProblemTensors._fields})


def maybe_place_lean(t: ProblemTensors, problem: SolverProblem, mesh,
                     min_rows: int = 0,
                     axis: str = "wl") -> tuple[ProblemTensors, bool]:
    """Mesh-place lean tensors when the policy allows: a mesh exists,
    the padded axis divides evenly, and the LIVE row count clears
    ``min_rows``. One placement policy, shared by the resident device
    state and the engine's sessionless path. Returns (tensors,
    placed)."""
    from kueue_oss_tpu.solver.meshutil import live_rows, mesh_divisible

    if (mesh is None
            or not mesh_divisible(mesh, problem.wl_cqid.shape[0])
            or live_rows(problem.wl_cqid, problem.n_cqs) < min_rows):
        return t, False
    return place_lean_tensors(t, mesh, axis), True


def _local_heads(t_local, C, w_offset, admitted, parked):
    """Per-CQ (min rank, head index) over this device's workload shard."""
    W_loc = t_local.wl_rank.shape[0]
    pending = ~admitted & ~parked
    rank_eff = jnp.where(pending, t_local.wl_rank, BIG)
    min_rank = jax.ops.segment_min(
        rank_eff, t_local.wl_cqid, num_segments=C + 1)[:C]
    w_global = jnp.arange(W_loc, dtype=jnp.int32) + w_offset
    is_head = rank_eff == min_rank[jnp.minimum(t_local.wl_cqid, C)]
    head_w = jax.ops.segment_min(
        jnp.where(is_head & pending, w_global, BIG), t_local.wl_cqid,
        num_segments=C + 1)[:C]
    return min_rank, head_w


def make_sharded_drain(mesh: Mesh, axis: str = "wl"):
    """Build the sharded PRODUCTION drain for a mesh.

    Call with mesh-placed (or host) tensors whose padded workload axis
    divides evenly; returns the full solve_backlog tuple (admitted,
    opt, admit_round, parked, rounds, usage), bit-identical to the
    single-chip kernel on the same padded problem.
    """

    n_dev = mesh.shape[axis]

    def drain(t: ProblemTensors):
        C = t.cq_node.shape[0]
        W1 = t.wl_rank.shape[0]
        K = t.wl_req.shape[1]
        F = t.wl_req.shape[2]
        shard = W1 // n_dev

        node_specs = ProblemTensors(
            parent=P(), depth=P(), height=P(), has_parent=P(), is_cq=P(),
            path=P(), subtree=P(), local_quota=P(), nominal=P(),
            has_borrow=P(), borrow_limit=P(), usage0=P(), cq_node=P(),
            cq_strict=P(), cq_try_next=P(), cq_nflavors=P(),
            wl_cqid=P(axis), wl_rank=P(axis), wl_prio=P(axis),
            wl_ts=P(axis), wl_uid=P(axis), wl_req=P(axis), wl_valid=P(axis),
        )

        @partial(
            shard_map, mesh=mesh,
            in_specs=(node_specs,),
            out_specs=(P(axis), P(axis), P(axis), P(axis), P(), P()),
        )
        def run(tl: ProblemTensors):
            my = jax.lax.axis_index(axis)
            w_offset = (my * shard).astype(jnp.int32)
            pot = potential_available_all(tl)

            def cond(state):
                return state[-2] & (state[-1] < W1 + C + 2)

            def body(state):
                (usage, admitted, parked, opt, admit_round, cursor_c,
                 prev_head, _, rounds) = state

                # --- head selection across shards (2x pmin over ICI) ---
                min_rank_l, head_w_l = _local_heads(
                    tl, C, w_offset, admitted, parked)
                min_rank = jax.lax.pmin(min_rank_l, axis)
                head_valid_l = min_rank_l == min_rank
                head_w = jax.lax.pmin(
                    jnp.where(head_valid_l, head_w_l, BIG), axis)
                has_head = min_rank < BIG

                # --- candidate payload: psum of one-hot rows -----------
                local_w = head_w - w_offset
                mine = has_head & (local_w >= 0) & (local_w < shard)
                lw = jnp.clip(local_w, 0, shard - 1)
                payload_req = jnp.where(
                    mine[:, None, None], tl.wl_req[lw], 0)
                payload_valid = jnp.where(mine[:, None], tl.wl_valid[lw],
                                          False)
                payload_prio = jnp.where(mine, tl.wl_prio[lw], 0)
                payload_ts = jnp.where(mine, tl.wl_ts[lw], 0)
                payload_uid = jnp.where(mine, tl.wl_uid[lw], 0)
                req_c = jax.lax.psum(payload_req, axis)
                valid_c = jax.lax.psum(payload_valid.astype(jnp.int32),
                                       axis) > 0
                prio_c = jax.lax.psum(payload_prio, axis)
                ts_c = jax.lax.psum(payload_ts, axis)
                uid_c = jax.lax.psum(payload_uid, axis)

                # --- replicated nomination + scan over candidate rows --
                # Build a candidate-indexed pseudo problem: candidates map
                # 1:1 to CQ slots; reuse the single-chip kernels by
                # substituting gathered arrays.
                t_cand = tl._replace(
                    wl_cqid=jnp.concatenate(
                        [jnp.arange(C, dtype=jnp.int32), jnp.array([C])]),
                    wl_rank=jnp.concatenate(
                        [jnp.where(has_head, min_rank, BIG),
                         jnp.array([BIG], dtype=jnp.int32)]),
                    wl_prio=jnp.concatenate(
                        [prio_c, jnp.array([0], dtype=jnp.int32)]),
                    wl_ts=jnp.concatenate(
                        [ts_c, jnp.array([0], dtype=ts_c.dtype)]),
                    wl_uid=jnp.concatenate(
                        [uid_c, jnp.array([0], dtype=jnp.int32)]),
                    wl_req=jnp.concatenate([req_c, jnp.zeros(
                        (1, K, F), dtype=req_c.dtype)]),
                    wl_valid=jnp.concatenate([valid_c, jnp.zeros(
                        (1, K), dtype=bool)]),
                )
                cand_idx = jnp.where(has_head, jnp.arange(C), C)
                # The flavor cursor belongs to a workload: reset it when a
                # CQ's head changed since last round.
                same_head = head_w == prev_head
                cursor_eff = jnp.concatenate(
                    [jnp.where(same_head, cursor_c[:C], 0),
                     jnp.zeros((1,), dtype=jnp.int32)])
                avail = available_all(tl, usage)
                mode, k_chosen, borrow, next_cursor = nominate(
                    t_cand, usage, avail, pot, cand_idx.astype(jnp.int32),
                    cursor_eff)

                is_head = has_head
                strict_head = tl.cq_strict & is_head
                park_now = is_head & (mode == M_NOFIT) & ~strict_head

                adm_c = jnp.zeros(C + 1, dtype=bool)
                park_c = jnp.zeros(C + 1, dtype=bool)
                park_c = park_c.at[cand_idx].set(park_now)
                cq_usage, adm_c, park_c, any_admitted = _round_scan(
                    t_cand, usage, usage, adm_c, park_c,
                    cand_idx.astype(jnp.int32), mode, k_chosen, borrow)
                usage = refresh_cohort_usage(tl, cq_usage)

                # --- scatter results back to the local shard -----------
                adm_slot = adm_c[:C]
                park_slot = park_c[:C]
                # Scatter-or / scatter-max (duplicate clipped indices
                # from non-owned slots must not clobber owned writes; a
                # row is admitted at most once, so max with the inert
                # fill is exact).
                newly = mine & adm_slot
                admitted = admitted.at[lw].max(newly)
                parked = parked.at[lw].max(mine & park_slot)
                opt = opt.at[lw].max(jnp.where(newly, k_chosen, 0))
                admit_round = admit_round.at[lw].max(
                    jnp.where(newly, rounds, -1))
                keep = is_head & ~adm_slot
                cursor_next = jnp.where(keep, next_cursor, 0)
                cursor_changed = jnp.any(
                    is_head & (cursor_next != cursor_eff[:C]))
                cursor_c = cursor_c.at[:C].set(cursor_next)

                # Progress must be computed from values replicated across
                # devices (heads are never already-parked, so any park
                # this round shows up in park_slot & is_head).
                progress = (any_admitted
                            | jnp.any(park_slot & is_head)
                            | cursor_changed)
                return (usage, admitted, parked, opt, admit_round,
                        cursor_c, head_w, progress, rounds + 1)

            init = (
                tl.usage0,
                # admitted/parked/opt/admit_round are per-shard plan
                # state: mark them varying over the mesh axis so the
                # carry types line up.
                pvary(jnp.zeros((shard,), dtype=bool), axis),
                pvary(jnp.zeros((shard,), dtype=bool), axis),
                pvary(jnp.zeros((shard,), dtype=jnp.int32), axis),
                pvary(jnp.full((shard,), -1, dtype=jnp.int32), axis),
                jnp.zeros((C + 1,), dtype=jnp.int32),
                jnp.full((C,), BIG, dtype=jnp.int32),
                jnp.ones((), dtype=bool),
                jnp.zeros((), dtype=jnp.int32),
            )
            (usage, admitted, parked, opt, admit_round, _, _, _,
             rounds) = jax.lax.while_loop(cond, body, init)
            return admitted, opt, admit_round, parked, rounds, usage

        return run(t)

    return drain


def make_sharded_relax_lp(mesh: Mesh, iters: int, axis: str = "wl"):
    """Mesh-sharded projected-gradient iterations of the relaxed
    admission LP (solver/relax.py).

    The workload-axis inputs (requests, scores, liveness, CQ ids, and
    the fractional iterate x) block-shard over ``axis``; the node/CQ
    pricing state replicates. Each iteration's only collective is ONE
    psum of the [C, F] per-CQ load matrix — per-iteration ICI volume is
    independent of the backlog size, the same scaling shape as the
    exact sharded drain above. Results are bit-identical to the
    single-chip LP up to float summation order (the repair pass is
    exact either way, so plan fidelity never rides on this).
    """
    from kueue_oss_tpu.solver.relax import RelaxLP, lp_loop

    specs = RelaxLP(
        r=P(axis), s=P(axis), live=P(axis), wl_cqid=P(axis),
        cq_node=P(), path_cq=P(), parent=P(), depth=P(),
        slack=P(), scale=P())

    @partial(shard_map, mesh=mesh, in_specs=(specs,),
             out_specs=P(axis))
    def run(lp):
        return lp_loop(lp, iters, psum_axis=axis)

    return jax.jit(run)


def full_shardings(mesh: Mesh, axis: str = "wl") -> dict:
    """field -> NamedSharding for mesh-placing FULL problem tensors:
    the [W+1] workload-axis fields (full_kernels.FULL_WL_FIELDS)
    block-shard; tree/CQ/flavor state replicates."""
    from kueue_oss_tpu.solver.full_kernels import (
        FULL_WL_FIELDS,
        FullTensors,
    )

    row = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    return {f: (row if f in FULL_WL_FIELDS else rep)
            for f in FullTensors._fields}


def place_full_tensors(t, mesh: Mesh, axis: str = "wl"):
    """Mesh-place FULL tensors: workload rows block-sharded over the
    ``wl`` axis (cross-shard victim gathers/psums are inserted by the
    partitioner), everything else replicated. Requires an evenly
    divisible padded axis (meshutil.align_pad_target /
    tensors.pad_workloads)."""
    n_dev = mesh.shape[axis]
    W1 = t.wl_cqid.shape[0]
    if W1 % n_dev != 0:
        raise ValueError(
            f"workload axis of {W1} rows does not shard over {n_dev} "
            "devices; pad with meshutil.align_pad_target first")
    sh = full_shardings(mesh, axis)
    return t._replace(**{
        f: jax.device_put(getattr(t, f), sh[f])
        for f in type(t)._fields})


def maybe_place_full(t, problem: SolverProblem, mesh,
                     min_rows: int = 0, axis: str = "wl"):
    """Mesh-place FULL tensors when the policy allows — the same
    gate as maybe_place_lean (mesh present, divisible padded axis,
    live rows clear the floor), shared by the resident device state
    and the engine's sessionless full path. Returns (tensors,
    placed)."""
    from kueue_oss_tpu.solver.meshutil import live_rows, mesh_divisible

    if (mesh is None
            or not mesh_divisible(mesh, problem.wl_cqid.shape[0])
            or live_rows(problem.wl_cqid, problem.n_cqs) < min_rows):
        return t, False
    return place_full_tensors(t, mesh, axis), True


def solve_backlog_full_sharded(problem: SolverProblem, mesh: Mesh,
                               g_max: int, h_max: int = 32,
                               p_max: int = 128, fs_enabled: bool = False,
                               axis: str = "wl", round_cap: int = 0):
    """Multi-chip PREEMPTION-capable drain, row- AND lane-sharded.

    Scaling model: the workload axis block-shards over the mesh with
    NamedSharding (same placement as the lean drain — backlogs of
    10^5-10^7 rows are the growing dimension), and the partitioner
    inserts the cross-shard victim-candidate gathers/psums the round's
    bookkeeping needs. The victim searches — the round's dominant cost
    — additionally shard their LANE axis inside
    full_kernels._run_searches; lane sharding composes with row
    sharding (the search re-gathers the rows it scans), it does not
    replace it. Under a multi-host mesh
    (meshutil.bootstrap_distributed) the same program spans every
    process's devices.

    Padding inserts inert null-row replicas BEFORE the final null row
    (tensors.pad_workloads), so W_null keeps pointing at the real null
    row and every dump scatter lands exactly where the single-chip
    kernel puts it: results match solve_backlog_full bit-for-bit,
    including uneven caller row counts (W+1 not divisible by the
    mesh).
    """
    from kueue_oss_tpu.solver.full_kernels import (
        make_full_solver,
        to_device_full,
    )
    from kueue_oss_tpu.solver.meshutil import host_replicated
    from kueue_oss_tpu.solver.tensors import pad_workloads as _pad_rows

    n_dev = mesh.shape[axis]
    W1 = problem.wl_cqid.shape[0]
    target_w = W1 - 1 + ((-W1) % n_dev)
    padded = _pad_rows(problem, target_w)
    t = place_full_tensors(to_device_full(padded), mesh, axis)
    solver = make_full_solver(g_max, h_max, p_max, fs_enabled,
                              round_cap=round_cap, mesh=mesh, axis=axis)
    out = host_replicated(solver(t))
    if target_w + 1 == W1:
        return out

    def unpad(a):
        # real rows kept their indices; the null row moved to the end
        return np.concatenate([a[: W1 - 1], a[-1:]])

    admitted, opt, admit_round, parked, rounds, usage, wl_usage, vr = out
    return (unpad(admitted), unpad(opt), unpad(admit_round),
            unpad(parked), rounds, usage, unpad(wl_usage), unpad(vr))


def solve_backlog_sharded(problem: SolverProblem, mesh: Mesh,
                          axis: str = "wl"):
    """Shard, place, and drain a problem over the mesh.

    Returns the full plan on host: (admitted [W+1] bool, opt [W+1]
    int32, admit_round [W+1] int32, parked [W+1] bool, rounds int,
    usage [N+1, F]) — the same contract as ``solve_backlog``, sliced
    back to the caller's row count.
    """
    from kueue_oss_tpu.solver.kernels import to_device
    from kueue_oss_tpu.solver.meshutil import (host_replicated,
                                               lean_mesh_solver)

    n_dev = mesh.shape[axis]
    padded = pad_workloads(problem, n_dev)
    t = place_lean_tensors(to_device(padded), mesh, axis)
    # host_replicated is the identity on single-process runs; on a
    # multi-host (pod) mesh it allgathers the cross-process shards so
    # every process slices the same full plan below
    admitted, opt, admit_round, parked, rounds, usage = host_replicated(
        lean_mesh_solver(mesh, axis)(t))
    W1 = problem.wl_cqid.shape[0]
    admitted = np.asarray(admitted)[:W1].copy()
    parked = np.asarray(parked)[:W1].copy()
    opt = np.asarray(opt)[:W1].copy()
    admit_round = np.asarray(admit_round)[:W1].copy()
    admitted[-1] = False
    parked[-1] = False
    return (admitted, opt, admit_round, parked, int(np.asarray(rounds)),
            np.asarray(usage))
