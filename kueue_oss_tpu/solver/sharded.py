"""Multi-chip SPMD drain: workloads sharded over a device mesh.

Scaling model: the workload axis (the dimension that grows — pending
backlogs of 10^5-10^7 entries) is sharded across the mesh's ``wl`` axis;
the node/quota state (10^3 nodes) is replicated. Each round needs three
small collectives, all riding ICI:

  1. per-CQ head rank:   pmin over a [C]-vector of local segment minima
  2. per-CQ head index:  pmin over a [C]-vector (two-pass argmin, int32)
  3. candidate payload:  psum of [C,K,F] request rows + [C] metadata
                         (each head lives on exactly one shard)

The nomination + admission scan then runs replicated (identical on every
device — it only touches [C]- and [N,F]-sized state), and each device
updates the admitted/parked flags for its own workload shard. This keeps
per-round collective volume at ~C*K*F ints regardless of backlog size.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kueue_oss_tpu.solver.kernels import (
    M_NOFIT,
    ProblemTensors,
    _round_scan,
    available_all,
    nominate,
    potential_available_all,
    refresh_cohort_usage,
)
from kueue_oss_tpu.solver.tensors import BIG, SolverProblem


def pad_workloads(p: SolverProblem, multiple: int) -> SolverProblem:
    """Pad the workload axis so (W+1) divides evenly across the mesh.

    Padding rows replicate the null-workload row (rank BIG, no options),
    so they are never selected as heads.
    """
    import dataclasses

    W1 = p.wl_cqid.shape[0]
    target = ((W1 + multiple - 1) // multiple) * multiple
    pad = target - W1
    if pad == 0:
        return p
    C = p.cq_node.shape[0]

    def pad1(a, fill):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths, constant_values=fill)

    return dataclasses.replace(
        p,
        wl_cqid=pad1(p.wl_cqid, C),
        wl_rank=pad1(p.wl_rank, BIG),
        wl_prio=pad1(p.wl_prio, 0),
        wl_ts=pad1(p.wl_ts, 0),
        wl_uid=pad1(p.wl_uid, 0),
        wl_req=pad1(p.wl_req, 0),
        wl_valid=pad1(p.wl_valid, False),
    )


def _local_heads(t_local, C, w_offset, admitted, parked):
    """Per-CQ (min rank, head index) over this device's workload shard."""
    W_loc = t_local.wl_rank.shape[0]
    pending = ~admitted & ~parked
    rank_eff = jnp.where(pending, t_local.wl_rank, BIG)
    min_rank = jax.ops.segment_min(
        rank_eff, t_local.wl_cqid, num_segments=C + 1)[:C]
    w_global = jnp.arange(W_loc, dtype=jnp.int32) + w_offset
    is_head = rank_eff == min_rank[jnp.minimum(t_local.wl_cqid, C)]
    head_w = jax.ops.segment_min(
        jnp.where(is_head & pending, w_global, BIG), t_local.wl_cqid,
        num_segments=C + 1)[:C]
    return min_rank, head_w


def make_sharded_drain(mesh: Mesh, axis: str = "wl"):
    """Build the sharded drain fn for a mesh; call with sharded tensors."""

    n_dev = mesh.shape[axis]

    def drain(t: ProblemTensors):
        C = t.cq_node.shape[0]
        W1 = t.wl_rank.shape[0]
        K = t.wl_req.shape[1]
        F = t.wl_req.shape[2]
        W_null = W1 - 1
        shard = W1 // n_dev

        node_specs = ProblemTensors(
            parent=P(), depth=P(), height=P(), has_parent=P(), is_cq=P(),
            path=P(), subtree=P(), local_quota=P(), nominal=P(),
            has_borrow=P(), borrow_limit=P(), usage0=P(), cq_node=P(),
            cq_strict=P(), cq_try_next=P(), cq_nflavors=P(),
            wl_cqid=P(axis), wl_rank=P(axis), wl_prio=P(axis),
            wl_ts=P(axis), wl_uid=P(axis), wl_req=P(axis), wl_valid=P(axis),
        )

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(node_specs,),
            out_specs=(P(axis), P(axis), P(), P()),
        )
        def run(tl: ProblemTensors):
            my = jax.lax.axis_index(axis)
            w_offset = (my * shard).astype(jnp.int32)
            pot = potential_available_all(tl)

            def cond(state):
                return state[-2] & (state[-1] < W1 + C + 2)

            def body(state):
                usage, admitted, parked, cursor_c, prev_head, _, rounds = state

                # --- head selection across shards (2x pmin over ICI) ---
                min_rank_l, head_w_l = _local_heads(
                    tl, C, w_offset, admitted, parked)
                min_rank = jax.lax.pmin(min_rank_l, axis)
                head_valid_l = min_rank_l == min_rank
                head_w = jax.lax.pmin(
                    jnp.where(head_valid_l, head_w_l, BIG), axis)
                has_head = min_rank < BIG

                # --- candidate payload: psum of one-hot rows -----------
                local_w = head_w - w_offset
                mine = has_head & (local_w >= 0) & (local_w < shard)
                lw = jnp.clip(local_w, 0, shard - 1)
                payload_req = jnp.where(
                    mine[:, None, None], tl.wl_req[lw], 0)
                payload_valid = jnp.where(mine[:, None], tl.wl_valid[lw],
                                          False)
                payload_prio = jnp.where(mine, tl.wl_prio[lw], 0)
                payload_ts = jnp.where(mine, tl.wl_ts[lw], 0)
                payload_uid = jnp.where(mine, tl.wl_uid[lw], 0)
                req_c = jax.lax.psum(payload_req, axis)
                valid_c = jax.lax.psum(payload_valid.astype(jnp.int32),
                                       axis) > 0
                prio_c = jax.lax.psum(payload_prio, axis)
                ts_c = jax.lax.psum(payload_ts, axis)
                uid_c = jax.lax.psum(payload_uid, axis)

                # --- replicated nomination + scan over candidate rows --
                # Build a candidate-indexed pseudo problem: candidates map
                # 1:1 to CQ slots; reuse the single-chip kernels by
                # substituting gathered arrays.
                t_cand = tl._replace(
                    wl_cqid=jnp.concatenate(
                        [jnp.arange(C, dtype=jnp.int32), jnp.array([C])]),
                    wl_rank=jnp.concatenate(
                        [jnp.where(has_head, min_rank, BIG),
                         jnp.array([BIG], dtype=jnp.int32)]),
                    wl_prio=jnp.concatenate(
                        [prio_c, jnp.array([0], dtype=jnp.int32)]),
                    wl_ts=jnp.concatenate(
                        [ts_c, jnp.array([0], dtype=ts_c.dtype)]),
                    wl_uid=jnp.concatenate(
                        [uid_c, jnp.array([0], dtype=jnp.int32)]),
                    wl_req=jnp.concatenate([req_c, jnp.zeros(
                        (1, K, F), dtype=req_c.dtype)]),
                    wl_valid=jnp.concatenate([valid_c, jnp.zeros(
                        (1, K), dtype=bool)]),
                )
                cand_idx = jnp.where(has_head, jnp.arange(C), C)
                # The flavor cursor belongs to a workload: reset it when a
                # CQ's head changed since last round.
                same_head = head_w == prev_head
                cursor_eff = jnp.concatenate(
                    [jnp.where(same_head, cursor_c[:C], 0),
                     jnp.zeros((1,), dtype=jnp.int32)])
                avail = available_all(tl, usage)
                mode, k_chosen, borrow, next_cursor = nominate(
                    t_cand, usage, avail, pot, cand_idx.astype(jnp.int32),
                    cursor_eff)

                is_head = has_head
                strict_head = tl.cq_strict & is_head
                park_now = is_head & (mode == M_NOFIT) & ~strict_head

                adm_c = jnp.zeros(C + 1, dtype=bool)
                park_c = jnp.zeros(C + 1, dtype=bool)
                park_c = park_c.at[cand_idx].set(park_now)
                cq_usage, adm_c, park_c, any_admitted = _round_scan(
                    t_cand, usage, usage, adm_c, park_c,
                    cand_idx.astype(jnp.int32), mode, k_chosen, borrow)
                usage = refresh_cohort_usage(tl, cq_usage)

                # --- scatter results back to the local shard -----------
                adm_slot = adm_c[:C]
                park_slot = park_c[:C]
                # Scatter-or (duplicate clipped indices from non-owned
                # slots must not clobber owned writes).
                admitted = admitted.at[lw].max(mine & adm_slot)
                parked = parked.at[lw].max(mine & park_slot)
                keep = is_head & ~adm_slot
                cursor_next = jnp.where(keep, next_cursor, 0)
                cursor_changed = jnp.any(
                    is_head & (cursor_next != cursor_eff[:C]))
                cursor_c = cursor_c.at[:C].set(cursor_next)

                # Progress must be computed from values replicated across
                # devices (heads are never already-parked, so any park
                # this round shows up in park_slot & is_head).
                progress = (any_admitted
                            | jnp.any(park_slot & is_head)
                            | cursor_changed)
                return (usage, admitted, parked, cursor_c, head_w,
                        progress, rounds + 1)

            init = (
                tl.usage0,
                # admitted/parked are per-shard state: mark them varying
                # over the mesh axis so the carry types line up.
                jax.lax.pcast(jnp.zeros((shard,), dtype=bool), (axis,), to='varying'),
                jax.lax.pcast(jnp.zeros((shard,), dtype=bool), (axis,), to='varying'),
                jnp.zeros((C + 1,), dtype=jnp.int32),
                jnp.full((C,), BIG, dtype=jnp.int32),
                jnp.ones((), dtype=bool),
                jnp.zeros((), dtype=jnp.int32),
            )
            usage, admitted, parked, _, _, _, rounds = jax.lax.while_loop(
                cond, body, init)
            return admitted, parked, rounds, usage

        return run(t)

    return drain


def solve_backlog_full_sharded(problem: SolverProblem, mesh: Mesh,
                               g_max: int, h_max: int = 32,
                               p_max: int = 128, fs_enabled: bool = False,
                               axis: str = "wl", round_cap: int = 0):
    """Multi-chip PREEMPTION-capable drain.

    Scaling model (complementary to the fit-only workload-axis shard
    below): the full kernel's per-round cost is dominated by the
    victim searches — h_max x K independent candidate scans over the
    whole workload axis — so those LANES shard across the mesh
    (full_kernels._run_searches) while the cohort-tree state stays
    replicated. Per-round ICI volume is the gathered lane results
    (lanes x p_max victim slots); admission/eviction bookkeeping is
    identical on every device. Results match the single-chip
    solve_backlog_full bit-for-bit.
    """
    from kueue_oss_tpu.solver.full_kernels import (
        make_full_solver,
        to_device_full,
    )

    t = to_device_full(problem)
    solver = make_full_solver(g_max, h_max, p_max, fs_enabled,
                              round_cap=round_cap, mesh=mesh, axis=axis)
    return solver(t)


def solve_backlog_sharded(problem: SolverProblem, mesh: Mesh,
                          axis: str = "wl"):
    """Shard, place, and drain a problem over the mesh. Returns
    (admitted [W+1] bool on host, parked, rounds, usage)."""
    from kueue_oss_tpu.solver.kernels import to_device

    n_dev = mesh.shape[axis]
    padded = pad_workloads(problem, n_dev)
    t = to_device(padded)
    sharding = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    t = t._replace(
        wl_cqid=jax.device_put(t.wl_cqid, sharding),
        wl_rank=jax.device_put(t.wl_rank, sharding),
        wl_prio=jax.device_put(t.wl_prio, sharding),
        wl_ts=jax.device_put(t.wl_ts, sharding),
        wl_uid=jax.device_put(t.wl_uid, sharding),
        wl_req=jax.device_put(t.wl_req, sharding),
        wl_valid=jax.device_put(t.wl_valid, sharding),
        usage0=jax.device_put(t.usage0, rep),
    )
    drain = jax.jit(make_sharded_drain(mesh, axis))
    admitted, parked, rounds, usage = drain(t)
    W1 = problem.wl_cqid.shape[0]
    admitted = np.asarray(admitted)[:W1].copy()
    parked = np.asarray(parked)[:W1].copy()
    admitted[-1] = False
    parked[-1] = False
    return admitted, parked, int(np.asarray(rounds)), np.asarray(usage)
