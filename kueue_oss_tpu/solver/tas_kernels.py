"""TAS placement on device: dense per-level capacity tensors.

The topology tree (block -> rack -> host) becomes one dense array per
level: `parents[l][d]` indexes level l-1; leaf capacities arrive as a
[D_leaf, R] resource matrix. Placement for one podset runs entirely in
jitted JAX:

  phase 1 (fillInCounts, tas_flavor_snapshot.go:1568):
    leaf state = floor-min over resources of capacity / per-pod request;
    upper levels = one segment_sum per level.

  phase 2 (findLevelWithFitDomains + updateCountsToMinimumGeneric,
  :1236-1469), BestFit profile: at the requested level pick the
  smallest single domain that fits the whole count (ties -> first in
  lexicographic order); preferred requests fall back upward level by
  level, then place greedily (state desc) at the top level taking full
  domains until the remainder fits a single domain, which is then
  chosen best-fit — a sort + prefix-sum + two segment reductions.
  The descent applies the same rule per sibling group at every level.

Scope: the base placer (make_placer) covers single podsets under the
BestFit / LeastFreeCapacity profiles; the extended placer
(make_placer_ext) adds single-layer podset slices and a count-1 leader
podset, both parity-tested against the host tree
(tests/test_tas_kernel.py, tests/test_tas_kernel_ext.py). Still
host-only by design: balanced placement (its selectOptimalDomainSetToFit
is a dict-memoized DP over (leaders, capacity) states whose tie-breaks
resist an exact dense-tensor port — tas_balanced_placement.go:1-382) and
nested multi-layer slice constraints.

Reference parity: pkg/cache/scheduler/tas_flavor_snapshot.go (two-phase
algorithm); SURVEY.md §7 step 6 calls this the most TPU-friendly
subproblem.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

BIG = np.int32(1 << 30)


@dataclass
class TASLevels:
    """Dense tree: level l has D_l domains ordered lexicographically by
    their level values; parents[l] maps into level l-1 (parents[0]=0)."""

    parents: list[np.ndarray]          # per level: [D_l] int32
    leaf_capacity: np.ndarray          # [D_leaf, R] int32
    leaf_names: list[tuple[str, ...]]  # decode table
    resources: list[str]


def build_levels(snapshot) -> TASLevels:
    """Flatten a host TASFlavorSnapshot's domain tree (lex order per
    level, matching buildAssignment's sort)."""
    levels = []
    for l in range(len(snapshot.levels)):
        doms = sorted(snapshot.domains_per_level[l].values(),
                      key=lambda d: d.level_values)
        levels.append(doms)
    index = [{d.id: i for i, d in enumerate(doms)} for doms in levels]
    parents = []
    for l, doms in enumerate(levels):
        if l == 0:
            parents.append(np.zeros(len(doms), dtype=np.int32))
        else:
            parents.append(np.asarray(
                [index[l - 1][d.id[:-1]] for d in doms], dtype=np.int32))
    resources = sorted({r for d in levels[-1] for r in d.free_capacity})
    cap = np.zeros((len(levels[-1]), max(1, len(resources))),
                   dtype=np.int64)
    for i, d in enumerate(levels[-1]):
        for j, r in enumerate(resources):
            cap[i, j] = max(0, d.free_capacity.get(r, 0)
                            - d.tas_usage.get(r, 0))
    return TASLevels(
        parents=parents,
        leaf_capacity=np.minimum(cap, BIG).astype(np.int32),
        leaf_names=[d.id for d in levels[-1]],
        resources=resources,
    )


def fill_counts(parents, leaf_capacity, per_pod):
    """Phase 1: per-level fit counts, leaves up (segment sums)."""
    nz = per_pod > 0
    per_dom = jnp.where(nz[None, :],
                        leaf_capacity // jnp.maximum(per_pod, 1)[None, :],
                        BIG)
    state = jnp.min(per_dom, axis=1)               # [D_leaf]
    states = [state]
    for l in range(len(parents) - 1, 0, -1):
        n_up = parents[l - 1].shape[0]
        state = jax.ops.segment_sum(state, parents[l], num_segments=n_up)
        states.append(state)
    states.reverse()                                # states[l] = [D_l]
    return states


def _greedy_segment(state, seg, need_of_seg, n_seg, least_free=False):
    """Minimize-domains assignment within each segment (sibling group).

    `state` [D], `seg` [D] segment id, `need_of_seg` [S] pods each
    segment must place (0 = inactive). Take full domains in (state desc,
    index asc) order until the remainder fits one domain, then give the
    remainder to the smallest sufficient domain at or after the
    crossing (updateCountsToMinimumGeneric + findBestFitDomainBy).

    `least_free` (traced bool) flips to the LeastFreeCapacity profile
    (unconstrained podsets under TASProfileMixed,
    tas_flavor_snapshot.go sortedDomains ascending): fill (state asc,
    index asc). In ascending order the best-fit refinement below is a
    no-op — the crossing domain IS the smallest sufficient one — so the
    same formula reproduces the host's sequential consume loop.
    Returns assignment [D].
    """
    D = state.shape[0]
    idx = jnp.arange(D, dtype=jnp.int32)
    sort_state = jnp.where(least_free, state, -state)
    order = jnp.lexsort((idx, sort_state, seg))
    take_sorted = _consume_in_order(state[order], seg[order], need_of_seg,
                                    n_seg, least_free)
    return jnp.zeros_like(state).at[order].set(take_sorted)


def make_placer(parents_np: list[np.ndarray]):
    """Build a jitted placement fn for one tree shape."""
    parents = [jnp.asarray(p) for p in parents_np]
    n_levels = len(parents)

    @jax.jit
    def place(leaf_capacity, per_pod, count, requested_level,
              required, unconstrained, least_free=False):
        states = fill_counts(parents, leaf_capacity, per_pod)

        def single_best(l):
            s = states[l]
            fits = s >= count
            key = jnp.where(fits, s, BIG)
            return jnp.any(fits), jnp.argmin(key).astype(jnp.int32)

        # ---- choose the start level + single-fit domain ---------------
        # preference: requested level first, then upward (preferred
        # requests only) — scan levels deepest-first
        chosen_level = jnp.asarray(-1, dtype=jnp.int32)
        chosen_dom = jnp.asarray(0, dtype=jnp.int32)
        for l in range(n_levels - 1, -1, -1):
            ok, d = single_best(l)
            allowed = jnp.where(
                required | unconstrained, l == requested_level,
                l <= requested_level)
            hit = ok & allowed & (chosen_level < 0) & (
                l <= requested_level)
            chosen_level = jnp.where(hit, l, chosen_level)
            chosen_dom = jnp.where(hit & (chosen_level == l), d,
                                   chosen_dom)
        single_fit = chosen_level >= 0

        # ---- seed the start level ------------------------------------
        sel = [jnp.zeros_like(s) for s in states]
        feasible = jnp.zeros((), dtype=bool)
        # greedy fallback level: top (0) for preferred, requested for
        # unconstrained; required never falls back
        greedy_level = jnp.where(unconstrained, requested_level, 0)
        for l in range(n_levels):
            is_single = single_fit & (chosen_level == l)
            one_hot = (jnp.arange(states[l].shape[0],
                                  dtype=jnp.int32) == chosen_dom)
            seed_single = jnp.where(one_hot, count, 0)
            seg = jnp.zeros_like(states[l])        # one global segment
            g = _greedy_segment(
                states[l], seg,
                jnp.full((1,), count, dtype=states[l].dtype), 1,
                least_free=least_free)
            g_ok = jnp.sum(states[l]) >= count
            use_greedy = (~single_fit) & (greedy_level == l) & ~required
            sel[l] = jnp.where(is_single, seed_single,
                               jnp.where(use_greedy & g_ok, g, sel[l]))
            feasible = feasible | is_single | (use_greedy & g_ok)
        start = jnp.where(single_fit, chosen_level, greedy_level)

        # ---- descend ---------------------------------------------------
        for l in range(n_levels - 1):
            par = parents[l + 1]
            n_par = states[l].shape[0]
            computed = _greedy_segment(states[l + 1], par, sel[l], n_par,
                                       least_free=least_free)
            # best-fit single-child shortcut per sibling group (the
            # least-free profile consumes sequentially without it,
            # _consume_minimum's ascending loop)
            need = sel[l][par]
            fits_whole = (states[l + 1] >= need) & (need > 0) & ~least_free
            key = jnp.where(fits_whole, states[l + 1], BIG)
            m = jax.ops.segment_min(key, par, num_segments=n_par)
            has_single = (m < BIG)[par] & (need > 0) & ~least_free
            cidx = jnp.arange(par.shape[0], dtype=jnp.int32)
            is_best = fits_whole & (states[l + 1] == m[par])
            first_best = jax.ops.segment_min(
                jnp.where(is_best, cidx, BIG), par, num_segments=n_par)
            single_take = jnp.where(
                (cidx == first_best[par]) & has_single, need, 0)
            next_sel = jnp.where(has_single, single_take, computed)
            # levels at or above the start keep their seeded values
            sel[l + 1] = jnp.where(jnp.asarray(l + 1) <= start,
                                   sel[l + 1], next_sel)

        leaf_sel = sel[n_levels - 1]
        feasible = feasible & (jnp.sum(leaf_sel) == count)
        return leaf_sel, feasible

    return place


def make_sequential_placer(parents_np: list[np.ndarray]):
    """Jitted DRAIN of a whole TAS backlog on device: place M podsets
    one after another with the leaf-capacity carry updated in between
    (the perf-shape workload: 15k sequential admissions against one
    640-node tree, configs/tas/generator.yaml). One lax.scan step per
    workload; everything stays on the accelerator.

    Inputs: per-workload arrays [M] — per_pod [M,R], count [M],
    requested level [M], required/unconstrained/least_free flags [M].
    Returns (leaf_sel [M, D_leaf], feasible [M], leaf_capacity_after).
    """
    place = make_placer(parents_np)

    @jax.jit
    def place_all(leaf_capacity, per_pod, count, level, required,
                  unconstrained, least_free):
        def step(cap, xs):
            pp, ct, lv, rq, un, lf = xs
            sel, ok = place(cap, pp, ct, lv, rq, un, lf)
            take = jnp.where(ok, sel, 0)
            cap = cap - take[:, None] * pp[None, :]
            return cap, (sel * ok.astype(sel.dtype), ok)

        cap_after, (sels, oks) = jax.lax.scan(
            step, leaf_capacity,
            (per_pod, count, level, required, unconstrained, least_free))
        return sels, oks, cap_after

    return place_all


def make_sequential_placer_ext(parents_np: list[np.ndarray]):
    """Sequential on-device drain through the slice/leader-capable
    placer: per-workload slice_size/slice_level and an optional count-1
    leader (``has_leader`` [M] bool — explicit, so a leader podset with
    all-zero requests places identically to place_podset_ext). The
    capacity carry subtracts worker pods AND the leader's row."""
    place = make_placer_ext(parents_np)

    @jax.jit
    def place_all(leaf_capacity, per_pod, count, level, required,
                  unconstrained, least_free, slice_size, slice_level,
                  leader_per_pod, has_leader):
        def step(cap, xs):
            pp, ct, lv, rq, un, lf, ss, sl, lpp, hl = xs
            sel, lead_leaf, ok = place(cap, pp, ct, lv, rq, un, lf,
                                       ss, sl, lpp, hl)
            take = jnp.where(ok, sel, 0)
            cap = cap - take[:, None] * pp[None, :]
            lead_onehot = (jnp.arange(cap.shape[0], dtype=jnp.int32)
                           == lead_leaf) & ok & hl
            cap = cap - jnp.where(lead_onehot[:, None], lpp[None, :], 0)
            return cap, (sel * ok.astype(sel.dtype),
                         jnp.where(ok, lead_leaf, -1), ok)

        cap_after, (sels, leads, oks) = jax.lax.scan(
            step, leaf_capacity,
            (per_pod, count, level, required, unconstrained, least_free,
             slice_size, slice_level, leader_per_pod, has_leader))
        return sels, leads, oks, cap_after

    return place_all


# ---------------------------------------------------------------------------
# extended placer: slices + leaders (tas_flavor_snapshot.go:867-1060,
# 1348-1469)
# ---------------------------------------------------------------------------


def fill_counts_ext(parents, leaf_capacity, per_pod, leader_per_pod,
                    has_leader, slice_size, slice_level):
    """Phase 1 with slice and leader states (fillInCounts +
    fillInCountsHelper, tas_flavor_snapshot.go:1568-1719).

    Returns per level l: dict with st (pods), swl (pods with the leader
    hosted somewhere below), ls (leader capacity 0/1), ss (slices),
    sswl (slices with leader). ``slice_level``/``slice_size`` are traced
    scalars; levels are a static Python loop.
    """
    from kueue_oss_tpu.solver import pallas_tas

    n_levels = len(parents)
    if (pallas_tas.use_pallas()
            and leaf_capacity.shape[1] <= 128):
        # the fused Pallas leaf pass (one tile read for st/swl/ls);
        # non-TPU backends run the same kernel in interpret mode
        st, swl, ls = pallas_tas.leaf_states(
            leaf_capacity, per_pod, leader_per_pod, has_leader,
            interpret=pallas_tas.interpret_mode())
    else:
        st, swl, ls = pallas_tas.leaf_states_reference(
            leaf_capacity, per_pod, leader_per_pod, has_leader)

    leaf_l = n_levels - 1
    at_sl = leaf_l == slice_level
    ss = jnp.where(at_sl, st // jnp.maximum(slice_size, 1), 0)
    sswl = jnp.where(at_sl, swl // jnp.maximum(slice_size, 1), 0)
    out = {leaf_l: dict(st=st, swl=swl, ls=ls, ss=ss, sswl=sswl)}

    for l in range(n_levels - 1, 0, -1):
        n_up = parents[l - 1].shape[0]
        seg = parents[l]
        c = out[l]
        total = jax.ops.segment_sum(c["st"], seg, num_segments=n_up)
        slice_total = jax.ops.segment_sum(c["ss"], seg, num_segments=n_up)
        # leader contributors: children able to host the leader (or no
        # leader requested at all)
        contrib = ~has_leader | (c["ls"] > 0)
        any_contrib = jax.ops.segment_max(
            contrib.astype(jnp.int32), seg, num_segments=n_up) > 0
        state_diff = jnp.where(contrib, c["st"] - c["swl"], BIG)
        slice_diff = jnp.where(contrib, c["ss"] - c["sswl"], BIG)
        min_sd = jax.ops.segment_min(state_diff, seg, num_segments=n_up)
        min_ssd = jax.ops.segment_min(slice_diff, seg, num_segments=n_up)
        ls_up = jax.ops.segment_max(c["ls"], seg, num_segments=n_up)
        swl_up = jnp.where(any_contrib, total - min_sd, 0)
        sswl_up = jnp.where(any_contrib, slice_total - min_ssd, 0)
        at_sl = (l - 1) == slice_level
        ss_up = jnp.where(at_sl, total // jnp.maximum(slice_size, 1),
                          slice_total)
        sswl_up = jnp.where(at_sl, swl_up // jnp.maximum(slice_size, 1),
                            sswl_up)
        out[l - 1] = dict(st=total, swl=swl_up, ls=ls_up, ss=ss_up,
                          sswl=sswl_up)
    return out


def _unit_views(c, l, slice_level):
    """Unit-space (u_state, u_swl) at level l: slices at or above the
    slice level, pods below. The sort keys always use the slice arrays
    (zero below the slice level), mirroring _sorted/_sorted_with_leader
    keying on slice_state at every level."""
    in_slices = jnp.asarray(l, dtype=jnp.int32) <= slice_level
    u_state = jnp.where(in_slices, c["ss"], c["st"])
    u_swl = jnp.where(in_slices, c["sswl"], c["swl"])
    return u_state, u_swl


def _greedy_segment_lead(c, l, slice_level, seg, need_of_seg, lead_of_seg,
                         n_seg, least_free):
    """Per sibling group: route the (0/1) leader, then minimize domains
    (updateCountsToMinimumGeneric + consumeWithLeadersGeneric,
    tas_flavor_snapshot.go:1348-1469). ``need_of_seg`` is in the level's
    units. Returns (take [D] units, lead_take [D] bool).
    """
    u_state, u_swl = _unit_views(c, l, slice_level)
    ss_key = c["ss"]
    st_key = c["st"]
    ls = c["ls"]
    D = u_state.shape[0]
    idx = jnp.arange(D, dtype=jnp.int32)
    need = need_of_seg[seg]
    lead_here = lead_of_seg[seg]                      # [D] bool

    # ---- leader domain (sortedDomainsWithLeader order) ----------------
    # keys: (-leader_state, ±slice_swl, state_swl, idx); only segments
    # with a leader to place participate.
    sswl_key = jnp.where(least_free, c["sswl"], -c["sswl"])
    # lexicographic min via segment reductions
    k1 = -ls
    m1 = jax.ops.segment_min(jnp.where(lead_here, k1, BIG), seg,
                             num_segments=n_seg)
    c1 = lead_here & (k1 == m1[seg])
    m2 = jax.ops.segment_min(jnp.where(c1, sswl_key, BIG), seg,
                             num_segments=n_seg)
    c2 = c1 & (sswl_key == m2[seg])
    m3 = jax.ops.segment_min(jnp.where(c2, c["swl"], BIG), seg,
                             num_segments=n_seg)
    c3 = c2 & (c["swl"] == m3[seg])
    top_lead = jax.ops.segment_min(jnp.where(c3, idx, BIG), seg,
                                   num_segments=n_seg)  # [S]
    top_of = top_lead[seg]
    top_fits = (u_swl[jnp.minimum(top_of, D - 1)] >= need) & (
        ls[jnp.minimum(top_of, D - 1)] > 0)
    # best-fit swap (findBestFitDomainBy over u_swl) when the top fits
    # everything and we are not least-free
    elig_bf = lead_here & (ls > 0) & (u_swl >= need) & top_fits & (
        ~least_free)
    bf_min = jax.ops.segment_min(jnp.where(elig_bf, u_swl, BIG), seg,
                                 num_segments=n_seg)
    is_bf = elig_bf & (u_swl == bf_min[seg])
    bf_first = jax.ops.segment_min(jnp.where(is_bf, idx, BIG), seg,
                                   num_segments=n_seg)
    # least_free keeps the sorted-with-leader top (no best-fit swap)
    lead_dom = jnp.where(bf_first < BIG, bf_first, top_lead)  # [S]
    has_lead_dom = (lead_dom < BIG) & lead_of_seg & (
        jax.ops.segment_max(ls, seg, num_segments=n_seg) > 0)
    lead_dom_c = jnp.minimum(lead_dom, D - 1).astype(jnp.int32)
    is_lead = (idx == lead_dom_c[seg]) & has_lead_dom[seg]
    lead_take_units = jnp.where(is_lead, jnp.minimum(u_swl, need), 0)

    # ---- the rest: normal greedy on remaining need --------------------
    taken = jax.ops.segment_sum(lead_take_units, seg, num_segments=n_seg)
    rest_need = jnp.maximum(need_of_seg - taken, 0)
    state_rest = jnp.where(is_lead, 0, u_state)
    # ordering: (±slice_state, state, idx); leader domain excluded
    ss_sort = jnp.where(least_free, ss_key, -ss_key)
    key = jnp.where(is_lead, BIG, 0)
    order = jnp.lexsort((idx, st_key, ss_sort, key, seg))
    take_sorted = _consume_in_order(state_rest[order], seg[order],
                                    rest_need, n_seg, least_free)
    take = jnp.zeros_like(u_state).at[order].set(take_sorted)
    return take + lead_take_units, is_lead


def _consume_in_order(s_sorted, seg_sorted, need_of_seg, n_seg,
                      least_free):
    """updateCountsToMinimumGeneric on a pre-sorted domain sequence:
    take full domains until the remainder fits one, then best-fit the
    remainder (no-op refinement under least-free ascending order)."""
    D = s_sorted.shape[0]
    idx = jnp.arange(D, dtype=jnp.int32)
    need = need_of_seg[seg_sorted]
    csum = jnp.cumsum(s_sorted)
    is_start = jnp.concatenate([jnp.ones(1, dtype=bool),
                                seg_sorted[1:] != seg_sorted[:-1]])
    base = jnp.where(is_start, csum - s_sorted, 0)
    base = jax.lax.associative_scan(jnp.maximum,
                                    jnp.where(is_start, base, -1))
    prefix_excl = csum - s_sorted - base
    remaining = jnp.maximum(need - prefix_excl, 0)
    covers = (s_sorted >= remaining) & (remaining > 0)
    pos_cover = jnp.where(covers, idx, BIG)
    q = jax.ops.segment_min(pos_cover, seg_sorted, num_segments=n_seg)
    q_of = q[seg_sorted]
    full_take = jnp.where((idx < q_of) & (remaining > 0), s_sorted, 0)
    rem_at_q = jnp.where(idx == q_of, remaining, 0)
    rem_of_seg = jax.ops.segment_max(rem_at_q, seg_sorted,
                                     num_segments=n_seg)
    r = rem_of_seg[seg_sorted]
    elig = (idx >= q_of) & (s_sorted >= r) & (r > 0)
    s_min = jax.ops.segment_min(jnp.where(elig, s_sorted, BIG),
                                seg_sorted, num_segments=n_seg)
    is_best = elig & (s_sorted == s_min[seg_sorted])
    first_best = jax.ops.segment_min(jnp.where(is_best, idx, BIG),
                                     seg_sorted, num_segments=n_seg)
    bf_take = jnp.where(idx == first_best[seg_sorted], r, 0)
    return full_take + bf_take


def make_placer_ext(parents_np: list[np.ndarray]):
    """Jitted placer with slice + leader support for one tree shape.

    ``place(leaf_capacity, per_pod, count, requested_level, required,
    unconstrained, least_free, slice_size, slice_level, leader_per_pod,
    has_leader)`` returns (worker_leaf_sel [D_leaf] pods,
    leader_leaf int32 (-1 when none), feasible bool). Covers
    findTopologyAssignment for single-layer slices and a count-1 leader
    podset (tas_flavor_snapshot.go:804-999); nested slice layers and
    balanced placement stay on the host tree.
    """
    parents = [jnp.asarray(p) for p in parents_np]
    n_levels = len(parents)

    @jax.jit
    def place(leaf_capacity, per_pod, count, requested_level, required,
              unconstrained, least_free, slice_size, slice_level,
              leader_per_pod, has_leader):
        cs = fill_counts_ext(parents, leaf_capacity, per_pod,
                             leader_per_pod, has_leader, slice_size,
                             slice_level)
        slice_count = count // jnp.maximum(slice_size, 1)

        def units_at(l):
            # placement units at level l (need conversions cross SL)
            return jnp.where(jnp.asarray(l, jnp.int32) <= slice_level,
                             slice_count, count)

        # ---- findLevelWithFitDomains at the requested level, walking
        # up for preferred requests ------------------------------------
        chosen_level = jnp.asarray(-1, dtype=jnp.int32)
        chosen_dom = jnp.asarray(0, dtype=jnp.int32)
        for l in range(n_levels - 1, -1, -1):
            c = cs[l]
            u_state, u_swl = _unit_views(c, l, slice_level)
            nd = units_at(l)
            ok_lead = (c["ls"] > 0) | ~has_leader
            # least-free (host: first sorted domain with slice_state >=
            # need) still must hold the leader when one exists — the
            # host's own least-free walk skips that check only because
            # mixed-profile unconstrained podsets never carry leaders;
            # without it the sequential drain's capacity carry would go
            # negative on the leader row
            fits = jnp.where(least_free & ~has_leader, u_state >= nd,
                             (u_swl >= nd) & ok_lead)
            # least-free: first in (-ls, sswl, swl, idx) order with
            # slice_state >= need; normal: best-fit by u_swl
            key_lf = jnp.where(fits, jnp.arange(u_state.shape[0]), BIG)
            key_bf = jnp.where(fits, u_swl, BIG)
            d_lf = jnp.argmin(key_lf).astype(jnp.int32)
            d_bf = jnp.argmin(key_bf).astype(jnp.int32)
            d = jnp.where(least_free, d_lf, d_bf)
            okl = jnp.any(fits)
            allowed = jnp.where(
                required | unconstrained, l == requested_level,
                l <= requested_level)
            hit = okl & allowed & (chosen_level < 0) & (
                l <= requested_level)
            chosen_level = jnp.where(hit, l, chosen_level)
            chosen_dom = jnp.where(hit & (chosen_level == l), d,
                                   chosen_dom)
        single_fit = chosen_level >= 0

        # ---- seed: single domain, or greedy multi-domain -------------
        sel = [jnp.zeros_like(cs[l]["st"]) for l in range(n_levels)]
        lead = [jnp.zeros(cs[l]["st"].shape, dtype=bool)
                for l in range(n_levels)]
        feasible = jnp.zeros((), dtype=bool)
        greedy_level = jnp.where(unconstrained, requested_level, 0)
        for l in range(n_levels):
            c = cs[l]
            is_single = single_fit & (chosen_level == l)
            one_hot = (jnp.arange(c["st"].shape[0],
                                  dtype=jnp.int32) == chosen_dom)
            seed_single = jnp.where(one_hot, units_at(l), 0)
            seed_lead = one_hot & has_leader
            seg = jnp.zeros_like(c["st"])
            g, gl = _greedy_segment_lead(
                c, l, slice_level, seg,
                jnp.full((1,), units_at(l), dtype=c["st"].dtype),
                jnp.full((1,), True) & has_leader, 1, least_free)
            u_state, u_swl = _unit_views(c, l, slice_level)
            cap_ok = jnp.where(
                has_leader,
                (jnp.sum(jnp.where(gl, u_swl, u_state)) >= units_at(l))
                & (jnp.any(gl) | ~has_leader),
                jnp.sum(u_state) >= units_at(l))
            use_greedy = (~single_fit) & (greedy_level == l) & ~required
            sel[l] = jnp.where(is_single, seed_single,
                               jnp.where(use_greedy & cap_ok, g, sel[l]))
            lead[l] = jnp.where(is_single, seed_lead & has_leader,
                                jnp.where(use_greedy & cap_ok,
                                          gl & has_leader, lead[l]))
            feasible = feasible | is_single | (use_greedy & cap_ok)
        start = jnp.where(single_fit, chosen_level, greedy_level)

        # ---- descend --------------------------------------------------
        for l in range(n_levels - 1):
            par = parents[l + 1]
            n_par = cs[l]["st"].shape[0]
            # need conversion when crossing the slice level: parents at
            # or above SL hold slices, children below hold pods
            below_sl = jnp.asarray(l + 1, jnp.int32) > slice_level
            need_par = jnp.where(
                below_sl & (jnp.asarray(l, jnp.int32) <= slice_level),
                sel[l] * jnp.maximum(slice_size, 1), sel[l])
            computed, comp_lead = _greedy_segment_lead(
                cs[l + 1], l + 1, slice_level, par, need_par, lead[l],
                n_par, least_free)
            keep = jnp.asarray(l + 1) <= start
            sel[l + 1] = jnp.where(keep, sel[l + 1], computed)
            lead[l + 1] = jnp.where(keep, lead[l + 1], comp_lead)

        leaf = n_levels - 1
        # leaf units -> pods
        leaf_pods = jnp.where(
            jnp.asarray(leaf, jnp.int32) <= slice_level,
            sel[leaf] * jnp.maximum(slice_size, 1), sel[leaf])
        total_ok = jnp.sum(leaf_pods) == count
        feasible = feasible & total_ok & (
            ~has_leader | jnp.any(lead[leaf]))
        leader_leaf = jnp.where(
            has_leader & feasible,
            jnp.argmax(lead[leaf]).astype(jnp.int32), -1)
        return leaf_pods, leader_leaf, feasible

    return place


_placer_cache: dict = {}


def place_podset(snapshot, per_pod: dict, count: int,
                 requested_level_idx: int, required: bool = False,
                 unconstrained: bool = False):
    """Host wrapper: flatten the tree, run the kernel, decode leaves.
    Returns {leaf domain id: count} or None when infeasible."""
    levels = build_levels(snapshot)
    key = tuple(tuple(p.tolist()) for p in levels.parents)
    placer = _placer_cache.get(key)
    if placer is None:
        placer = make_placer(levels.parents)
        _placer_cache[key] = placer
    req = np.zeros(max(1, len(levels.resources)), dtype=np.int32)
    for j, r in enumerate(levels.resources):
        req[j] = per_pod.get(r, 0)
    least_free = unconstrained and getattr(snapshot, "profile_mixed", False)
    leaf_sel, feasible = placer(
        jnp.asarray(levels.leaf_capacity), jnp.asarray(req),
        jnp.asarray(count, dtype=jnp.int32),
        jnp.asarray(requested_level_idx, dtype=jnp.int32),
        jnp.asarray(required), jnp.asarray(unconstrained),
        jnp.asarray(least_free))
    if not bool(feasible):
        return None
    leaf_sel = np.asarray(leaf_sel)
    return {levels.leaf_names[i]: int(leaf_sel[i])
            for i in range(len(levels.leaf_names)) if leaf_sel[i] > 0}


_placer_ext_cache: dict = {}


def place_podset_ext(snapshot, per_pod: dict, count: int,
                     requested_level_idx: int, required: bool = False,
                     unconstrained: bool = False, slice_size: int = 1,
                     slice_level_idx: int | None = None,
                     leader_per_pod: dict | None = None):
    """Host wrapper for the slice/leader-capable placer.

    Returns (worker {leaf id: pods}, leader leaf id or None) or None
    when infeasible. Single slice layer + count-1 leader podset; nested
    slice layers and balanced placement stay on the host tree
    (tas_flavor_snapshot.go:804-999 scope notes in make_placer_ext).
    """
    levels = build_levels(snapshot)
    key = tuple(tuple(p.tolist()) for p in levels.parents)
    placer = _placer_ext_cache.get(key)
    if placer is None:
        placer = make_placer_ext(levels.parents)
        _placer_ext_cache[key] = placer
    R = max(1, len(levels.resources))
    req = np.zeros(R, dtype=np.int32)
    for j, r in enumerate(levels.resources):
        req[j] = per_pod.get(r, 0)
    lead = np.zeros(R, dtype=np.int32)
    has_leader = leader_per_pod is not None
    if has_leader:
        for j, r in enumerate(levels.resources):
            lead[j] = leader_per_pod.get(r, 0)
    if slice_level_idx is None:
        slice_level_idx = len(levels.parents) - 1
    if count % max(slice_size, 1) != 0:
        return None
    least_free = unconstrained and getattr(snapshot, "profile_mixed", False)
    worker_sel, leader_leaf, feasible = placer(
        jnp.asarray(levels.leaf_capacity), jnp.asarray(req),
        jnp.asarray(count, dtype=jnp.int32),
        jnp.asarray(requested_level_idx, dtype=jnp.int32),
        jnp.asarray(required), jnp.asarray(unconstrained),
        jnp.asarray(least_free),
        jnp.asarray(max(slice_size, 1), dtype=jnp.int32),
        jnp.asarray(slice_level_idx, dtype=jnp.int32),
        jnp.asarray(lead), jnp.asarray(has_leader))
    if not bool(feasible):
        return None
    worker_sel = np.asarray(worker_sel)
    workers = {levels.leaf_names[i]: int(worker_sel[i])
               for i in range(len(levels.leaf_names)) if worker_sel[i] > 0}
    leader = (levels.leaf_names[int(leader_leaf)]
              if has_leader and int(leader_leaf) >= 0 else None)
    return workers, leader
