"""TAS placement on device: dense per-level capacity tensors.

The topology tree (block -> rack -> host) becomes one dense array per
level: `parents[l][d]` indexes level l-1; leaf capacities arrive as a
[D_leaf, R] resource matrix. Placement for one podset runs entirely in
jitted JAX:

  phase 1 (fillInCounts, tas_flavor_snapshot.go:1568):
    leaf state = floor-min over resources of capacity / per-pod request;
    upper levels = one segment_sum per level.

  phase 2 (findLevelWithFitDomains + updateCountsToMinimumGeneric,
  :1236-1469), BestFit profile: at the requested level pick the
  smallest single domain that fits the whole count (ties -> first in
  lexicographic order); preferred requests fall back upward level by
  level, then place greedily (state desc) at the top level taking full
  domains until the remainder fits a single domain, which is then
  chosen best-fit — a sort + prefix-sum + two segment reductions.
  The descent applies the same rule per sibling group at every level.

Scope: single podset, BestFit profile, no slices/leaders (the host tree
handles those shapes). Parity-tested against tas/snapshot.py in
tests/test_tas_kernel.py.

Reference parity: pkg/cache/scheduler/tas_flavor_snapshot.go (two-phase
algorithm); SURVEY.md §7 step 6 calls this the most TPU-friendly
subproblem.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

BIG = np.int32(1 << 30)


@dataclass
class TASLevels:
    """Dense tree: level l has D_l domains ordered lexicographically by
    their level values; parents[l] maps into level l-1 (parents[0]=0)."""

    parents: list[np.ndarray]          # per level: [D_l] int32
    leaf_capacity: np.ndarray          # [D_leaf, R] int32
    leaf_names: list[tuple[str, ...]]  # decode table
    resources: list[str]


def build_levels(snapshot) -> TASLevels:
    """Flatten a host TASFlavorSnapshot's domain tree (lex order per
    level, matching buildAssignment's sort)."""
    levels = []
    for l in range(len(snapshot.levels)):
        doms = sorted(snapshot.domains_per_level[l].values(),
                      key=lambda d: d.level_values)
        levels.append(doms)
    index = [{d.id: i for i, d in enumerate(doms)} for doms in levels]
    parents = []
    for l, doms in enumerate(levels):
        if l == 0:
            parents.append(np.zeros(len(doms), dtype=np.int32))
        else:
            parents.append(np.asarray(
                [index[l - 1][d.id[:-1]] for d in doms], dtype=np.int32))
    resources = sorted({r for d in levels[-1] for r in d.free_capacity})
    cap = np.zeros((len(levels[-1]), max(1, len(resources))),
                   dtype=np.int64)
    for i, d in enumerate(levels[-1]):
        for j, r in enumerate(resources):
            cap[i, j] = max(0, d.free_capacity.get(r, 0)
                            - d.tas_usage.get(r, 0))
    return TASLevels(
        parents=parents,
        leaf_capacity=np.minimum(cap, BIG).astype(np.int32),
        leaf_names=[d.id for d in levels[-1]],
        resources=resources,
    )


def fill_counts(parents, leaf_capacity, per_pod):
    """Phase 1: per-level fit counts, leaves up (segment sums)."""
    nz = per_pod > 0
    per_dom = jnp.where(nz[None, :],
                        leaf_capacity // jnp.maximum(per_pod, 1)[None, :],
                        BIG)
    state = jnp.min(per_dom, axis=1)               # [D_leaf]
    states = [state]
    for l in range(len(parents) - 1, 0, -1):
        n_up = parents[l - 1].shape[0]
        state = jax.ops.segment_sum(state, parents[l], num_segments=n_up)
        states.append(state)
    states.reverse()                                # states[l] = [D_l]
    return states


def _greedy_segment(state, seg, need_of_seg, n_seg, least_free=False):
    """Minimize-domains assignment within each segment (sibling group).

    `state` [D], `seg` [D] segment id, `need_of_seg` [S] pods each
    segment must place (0 = inactive). Take full domains in (state desc,
    index asc) order until the remainder fits one domain, then give the
    remainder to the smallest sufficient domain at or after the
    crossing (updateCountsToMinimumGeneric + findBestFitDomainBy).

    `least_free` (traced bool) flips to the LeastFreeCapacity profile
    (unconstrained podsets under TASProfileMixed,
    tas_flavor_snapshot.go sortedDomains ascending): fill (state asc,
    index asc). In ascending order the best-fit refinement below is a
    no-op — the crossing domain IS the smallest sufficient one — so the
    same formula reproduces the host's sequential consume loop.
    Returns assignment [D].
    """
    D = state.shape[0]
    idx = jnp.arange(D, dtype=jnp.int32)
    sort_state = jnp.where(least_free, state, -state)
    order = jnp.lexsort((idx, sort_state, seg))
    s_sorted = state[order]
    seg_sorted = seg[order]
    need = need_of_seg[seg_sorted]                 # [D]

    csum = jnp.cumsum(s_sorted)
    # exclusive prefix within segment: subtract the csum at segment start
    is_start = jnp.concatenate([jnp.ones(1, dtype=bool),
                                seg_sorted[1:] != seg_sorted[:-1]])
    base = jnp.where(is_start, csum - s_sorted, 0)
    base = jax.lax.associative_scan(jnp.maximum, jnp.where(
        is_start, base, -1))
    prefix_excl = csum - s_sorted - base
    remaining = jnp.maximum(need - prefix_excl, 0)  # pods left before me

    # crossing: first position (per segment) whose state covers the
    # remaining count -> the best-fit switch point
    covers = (s_sorted >= remaining) & (remaining > 0)
    pos_cover = jnp.where(covers, idx, BIG)
    q = jax.ops.segment_min(pos_cover, seg_sorted, num_segments=n_seg)
    q_of = q[seg_sorted]
    full_take = jnp.where((idx < q_of) & (remaining > 0), s_sorted, 0)
    rem_at_q = jnp.where(idx == q_of, remaining, 0)
    rem_of_seg = jax.ops.segment_max(rem_at_q, seg_sorted,
                                     num_segments=n_seg)
    r = rem_of_seg[seg_sorted]
    # best-fit among positions >= q with state >= r: smallest such
    # state, ties -> first position
    elig = (idx >= q_of) & (s_sorted >= r) & (r > 0)
    s_min = jax.ops.segment_min(jnp.where(elig, s_sorted, BIG),
                                seg_sorted, num_segments=n_seg)
    is_best = elig & (s_sorted == s_min[seg_sorted])
    first_best = jax.ops.segment_min(jnp.where(is_best, idx, BIG),
                                     seg_sorted, num_segments=n_seg)
    bf_take = jnp.where(idx == first_best[seg_sorted], r, 0)
    take_sorted = full_take + bf_take
    return jnp.zeros_like(state).at[order].set(take_sorted)


def make_placer(parents_np: list[np.ndarray]):
    """Build a jitted placement fn for one tree shape."""
    parents = [jnp.asarray(p) for p in parents_np]
    n_levels = len(parents)

    @jax.jit
    def place(leaf_capacity, per_pod, count, requested_level,
              required, unconstrained, least_free=False):
        states = fill_counts(parents, leaf_capacity, per_pod)

        def single_best(l):
            s = states[l]
            fits = s >= count
            key = jnp.where(fits, s, BIG)
            return jnp.any(fits), jnp.argmin(key).astype(jnp.int32)

        # ---- choose the start level + single-fit domain ---------------
        # preference: requested level first, then upward (preferred
        # requests only) — scan levels deepest-first
        chosen_level = jnp.asarray(-1, dtype=jnp.int32)
        chosen_dom = jnp.asarray(0, dtype=jnp.int32)
        for l in range(n_levels - 1, -1, -1):
            ok, d = single_best(l)
            allowed = jnp.where(
                required | unconstrained, l == requested_level,
                l <= requested_level)
            hit = ok & allowed & (chosen_level < 0) & (
                l <= requested_level)
            chosen_level = jnp.where(hit, l, chosen_level)
            chosen_dom = jnp.where(hit & (chosen_level == l), d,
                                   chosen_dom)
        single_fit = chosen_level >= 0

        # ---- seed the start level ------------------------------------
        sel = [jnp.zeros_like(s) for s in states]
        feasible = jnp.zeros((), dtype=bool)
        # greedy fallback level: top (0) for preferred, requested for
        # unconstrained; required never falls back
        greedy_level = jnp.where(unconstrained, requested_level, 0)
        for l in range(n_levels):
            is_single = single_fit & (chosen_level == l)
            one_hot = (jnp.arange(states[l].shape[0],
                                  dtype=jnp.int32) == chosen_dom)
            seed_single = jnp.where(one_hot, count, 0)
            seg = jnp.zeros_like(states[l])        # one global segment
            g = _greedy_segment(
                states[l], seg,
                jnp.full((1,), count, dtype=states[l].dtype), 1,
                least_free=least_free)
            g_ok = jnp.sum(states[l]) >= count
            use_greedy = (~single_fit) & (greedy_level == l) & ~required
            sel[l] = jnp.where(is_single, seed_single,
                               jnp.where(use_greedy & g_ok, g, sel[l]))
            feasible = feasible | is_single | (use_greedy & g_ok)
        start = jnp.where(single_fit, chosen_level, greedy_level)

        # ---- descend ---------------------------------------------------
        for l in range(n_levels - 1):
            par = parents[l + 1]
            n_par = states[l].shape[0]
            computed = _greedy_segment(states[l + 1], par, sel[l], n_par,
                                       least_free=least_free)
            # best-fit single-child shortcut per sibling group (the
            # least-free profile consumes sequentially without it,
            # _consume_minimum's ascending loop)
            need = sel[l][par]
            fits_whole = (states[l + 1] >= need) & (need > 0) & ~least_free
            key = jnp.where(fits_whole, states[l + 1], BIG)
            m = jax.ops.segment_min(key, par, num_segments=n_par)
            has_single = (m < BIG)[par] & (need > 0) & ~least_free
            cidx = jnp.arange(par.shape[0], dtype=jnp.int32)
            is_best = fits_whole & (states[l + 1] == m[par])
            first_best = jax.ops.segment_min(
                jnp.where(is_best, cidx, BIG), par, num_segments=n_par)
            single_take = jnp.where(
                (cidx == first_best[par]) & has_single, need, 0)
            next_sel = jnp.where(has_single, single_take, computed)
            # levels at or above the start keep their seeded values
            sel[l + 1] = jnp.where(jnp.asarray(l + 1) <= start,
                                   sel[l + 1], next_sel)

        leaf_sel = sel[n_levels - 1]
        feasible = feasible & (jnp.sum(leaf_sel) == count)
        return leaf_sel, feasible

    return place


def make_sequential_placer(parents_np: list[np.ndarray]):
    """Jitted DRAIN of a whole TAS backlog on device: place M podsets
    one after another with the leaf-capacity carry updated in between
    (the perf-shape workload: 15k sequential admissions against one
    640-node tree, configs/tas/generator.yaml). One lax.scan step per
    workload; everything stays on the accelerator.

    Inputs: per-workload arrays [M] — per_pod [M,R], count [M],
    requested level [M], required/unconstrained/least_free flags [M].
    Returns (leaf_sel [M, D_leaf], feasible [M], leaf_capacity_after).
    """
    place = make_placer(parents_np)

    @jax.jit
    def place_all(leaf_capacity, per_pod, count, level, required,
                  unconstrained, least_free):
        def step(cap, xs):
            pp, ct, lv, rq, un, lf = xs
            sel, ok = place(cap, pp, ct, lv, rq, un, lf)
            take = jnp.where(ok, sel, 0)
            cap = cap - take[:, None] * pp[None, :]
            return cap, (sel * ok.astype(sel.dtype), ok)

        cap_after, (sels, oks) = jax.lax.scan(
            step, leaf_capacity,
            (per_pod, count, level, required, unconstrained, least_free))
        return sels, oks, cap_after

    return place_all


_placer_cache: dict = {}


def place_podset(snapshot, per_pod: dict, count: int,
                 requested_level_idx: int, required: bool = False,
                 unconstrained: bool = False):
    """Host wrapper: flatten the tree, run the kernel, decode leaves.
    Returns {leaf domain id: count} or None when infeasible."""
    levels = build_levels(snapshot)
    key = tuple(tuple(p.tolist()) for p in levels.parents)
    placer = _placer_cache.get(key)
    if placer is None:
        placer = make_placer(levels.parents)
        _placer_cache[key] = placer
    req = np.zeros(max(1, len(levels.resources)), dtype=np.int32)
    for j, r in enumerate(levels.resources):
        req[j] = per_pod.get(r, 0)
    least_free = unconstrained and getattr(snapshot, "profile_mixed", False)
    leaf_sel, feasible = placer(
        jnp.asarray(levels.leaf_capacity), jnp.asarray(req),
        jnp.asarray(count, dtype=jnp.int32),
        jnp.asarray(requested_level_idx, dtype=jnp.int32),
        jnp.asarray(required), jnp.asarray(unconstrained),
        jnp.asarray(least_free))
    if not bool(feasible):
        return None
    leaf_sel = np.asarray(leaf_sel)
    return {levels.leaf_names[i]: int(leaf_sel[i])
            for i in range(len(levels.leaf_names)) if leaf_sel[i] > 0}
