"""Solver engine: export → jitted drain → apply plan to the store.

The engine is the TPU-native replacement for running the reference's Go
scheduler loop cycle-by-cycle: one invocation computes the admission plan
for the whole backlog. Each admission can optionally be re-verified against
the scalar oracle before committing (mirrors the safety pattern of
verifying solver plans before assuming, SURVEY.md §7 step 4).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kueue_oss_tpu.api.types import (
    Admission,
    PodSetAssignment,
    PreemptionPolicyValue,
    TopologyAssignment,
    WorkloadConditionType,
)
from kueue_oss_tpu.core.queue_manager import QueueManager
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.core.workload_info import WorkloadInfo
from kueue_oss_tpu import metrics, obs, resilience
from kueue_oss_tpu.obs import devtel
from kueue_oss_tpu.solver.delta import (
    DeviceResidentProblem,
    HostDeltaSession,
)
from kueue_oss_tpu.solver.kernels import solve_backlog, to_device
from kueue_oss_tpu.solver.resilience import SolverHealth, SolverUnavailable
from kueue_oss_tpu.solver.tensors import (
    ExportCache,
    SolverProblem,
    UnsupportedProblem,
    export_problem,
    pad_workloads,
    pow2,
)
from kueue_oss_tpu.persist import hooks as persist_hooks


@dataclass
class DrainResult:
    admitted: int = 0
    evicted: int = 0
    rounds: int = 0
    solver_time_s: float = 0.0
    apply_time_s: float = 0.0
    #: workload keys admitted, in (round, entry-order) sequence
    admitted_keys: list[str] = field(default_factory=list)
    #: initially-admitted workload keys preempted by the drain
    evicted_keys: list[str] = field(default_factory=list)


class SolverEngine:
    """Drains pending backlogs through the jitted TPU kernel."""

    def __init__(self, store: Store, queues: QueueManager,
                 scheduler=None, enable_fair_sharing: bool = False,
                 remote=None, health: Optional[SolverHealth] = None,
                 mesh_mode: Optional[str] = None) -> None:
        self.store = store
        self.queues = queues
        #: host scheduler whose eviction state machine applies the plan's
        #: preemptions (metrics/backoff parity); built lazily if absent
        self.scheduler = scheduler
        #: fair-sharing mode (KEP-1714): DRS tournament entry ordering +
        #: fair preemption strategies, on-device via
        #: solver/fair_kernels.py. Mirrors Scheduler(enable_fair_sharing).
        self.enable_fair_sharing = enable_fair_sharing
        #: optional solver/service.SolverClient — the solve runs in a
        #: separate sidecar process (SURVEY §2.4); export, verify, and
        #: commit stay in this process
        self.remote = remote
        #: circuit breaker over the remote backend: a tripped breaker
        #: short-circuits drains into SolverUnavailable (host-cycle
        #: fallback) instead of re-probing a dead sidecar every pass
        self.health = health if health is not None else SolverHealth()
        #: pad the workload axis to at least this size before solving.
        #: Callers that drain repeatedly while the backlog grows (the
        #: scheduler serve loop, the perf Simulator) set it to the
        #: expected peak so every drain reuses ONE compiled program
        #: instead of recompiling at each power-of-two crossing.
        self.pad_to = 0
        #: cross-drain export memo (event-invalidated); repeated drains
        #: assemble the problem with vectorized gathers instead of
        #: per-workload Python loops
        self.export_cache = ExportCache(store)
        #: (spec_gen, ceilings) memo backing flavor_witness()
        self._flavor_witness_cache: Optional[tuple[int, dict]] = None
        #: sticky pad high-water mark: the padded workload axis never
        #: shrinks, so a backlog oscillating around a power-of-two
        #: boundary (pending + admitted crossing pad_to) can't flap
        #: between two compiled programs — recompiles are monotone
        #: crossings only
        self._pad_hwm = 0
        #: production device-TAS path: TAS CQs whose backlog shapes the
        #: extended placer supports drain through the quota kernel and
        #: place on device (solver/tas_engine.py); set False to force
        #: the pre-round-5 host-only TAS behavior
        self.device_tas = True
        self._tas_placer = None
        #: TAS CQs admitted to the device path for the CURRENT drain
        #: (computed by pending_backlog, read by the apply path)
        self._drain_tas_ready: set[str] = set()
        #: victim-search lanes per round, throughput mode: lanes sized
        #: to the CQ count (host-cycle parity — no head deferral) up to
        #: this cap. Narrow lanes lower per-round latency, wide lanes
        #: cut round counts ~10x on park-heavy shapes (see _size_caps).
        self.h_max_cap = 1024
        #: per-round search-work budget in lane-option-group units
        #: (each lane runs K x g victim searches): on an accelerator
        #: the lanes vectorize so the budget is generous; on the CPU
        #: fallback they serialize, so multi-flavor/multi-group shapes
        #: trade lanes for rounds at roughly constant work. None =
        #: choose by backend at first drain.
        self.h_work_budget = None
        #: debugger.Tracer for drain spans; when unset, the scheduler's
        #: attached tracer (attach_to_scheduler) is used, so host cycle
        #: spans and solver/sidecar spans land in ONE Chrome trace
        self.tracer = None
        #: total drains started; the obs cycle id for engines used
        #: standalone (no scheduler whose cycle_count anchors the drain)
        self.drain_count = 0
        #: cycle id tagged on this drain's DecisionEvents and spans — the
        #: host cycle the drain serves (scheduler.cycle_count + 1), so a
        #: merged trace groups the drain with the cycle it replaced
        self._drain_cycle = 0
        #: delta-sync sessions (docs/SOLVER_PROTOCOL.md): successive
        #: drains re-encode the padded problem into a stable slot space
        #: and ship only the dirty-row delta; the sidecar (remote) or
        #: the resident device buffers (in-process) hold the rest. One
        #: session per kernel kind — lean and full exports differ.
        import os as _os

        self.use_sessions = _os.environ.get(
            "KUEUE_SOLVER_SESSIONS") != "0"
        self._delta_sessions: dict[str, HostDeltaSession] = {}
        #: in-process resident device tensors keyed by session epoch, so
        #: the non-remote path stops re-uploading the full problem too
        self._device_states: dict[str, DeviceResidentProblem] = {}
        #: single worker for pipelined drain dispatch: the remote solve
        #: round-trip overlaps host-side apply prework
        self._solve_pool = None
        #: apply prework computed during the overlap window (consumed
        #: and cleared by the apply paths)
        self._prework: Optional[dict] = None
        #: mesh-sharded drains (solver/sharded.py): mesh mode string
        #: from SolverBackendConfig.mesh / KUEUE_SOLVER_MESH — "auto"
        #: (default; mesh when jax.device_count() > 1), "off", or an
        #: explicit device count. The mesh itself resolves lazily.
        self.mesh_mode = mesh_mode
        self._mesh_obj = None
        self._mesh_resolved = False
        #: chaos/device-loss cap on mesh width (refresh_mesh)
        self._mesh_max_devices = 0
        #: a mesh drain fault (device loss, compile failure) raises the
        #: ``mesh_broken`` condition on the degradation controller;
        #: drains degrade to single-chip until refresh_mesh() re-probes
        #: or the retry cooldown elapses (timed half-open, owned by the
        #: controller's unified CooldownPolicy — a transient fault must
        #: not disable the mesh for the process lifetime). The
        #: _mesh_broken/_mesh_broken_at names survive as properties
        #: over the controller state.
        self._mesh_broken = False
        self.mesh_retry_cooldown_s = 300.0
        #: backlogs below this stay single-chip: the mesh is the
        #: LARGE-backlog path — tiny problems would pay per-shape SPMD
        #: compiles for collectives they cannot amortize
        self.mesh_min_workloads = 1024
        #: pin drains to the mesh arm regardless of cost estimates
        #: (bench measurement + parity tests; never set in production —
        #: the whole point of the EMA router is measured routing)
        self.mesh_force = False
        #: adaptive arm routing: measured solve wall PER EXPORTED
        #: WORKLOAD by (kernel kind, arm in {"single", "mesh"}); the
        #: mesh arm engages only while its measured wall beats the
        #: single-chip arm's (each arm is probed once, the losing arm
        #: decays so a regressing winner gets re-measured). The HOST arm
        #: of the triple lives in the scheduler's _drain_cost_ema /
        #: _host_s_per_adm gate, which prices whatever arm ran here
        #: against host cycles.
        self._arm_ema: dict[tuple[str, str], float] = {}
        #: arms whose compile-tainted first sample was discarded: the
        #: probe drain pays one-time SPMD compilation + the full
        #: resident upload, which would inflate the EMA ~100x and latch
        #: the router against the arm; only warm samples are recorded
        self._arm_warm: set[tuple[str, str]] = set()
        #: chaos injection point: called with the arm name ("mesh" /
        #: "single" / "relax") right before each local solve; raising
        #: simulates a device loss on that arm (kueue_oss_tpu/chaos
        #: MeshFaultInjector)
        self.solve_fault_hook = None
        #: arm that served the most recent local solve (diagnostics)
        self.last_drain_arm: Optional[str] = None
        #: convex-relaxation fast-path arm (solver/relax.py,
        #: docs/SOLVER_PROTOCOL.md "Relaxed fast-path arm"): a
        #: projected-gradient LP relaxation + exact rounding-and-repair
        #: through the lean kernel. Lean (fit-only) in-process drains
        #: only; the fourth arm of the cost-EMA router beside
        #: host/single-chip/mesh. Knobs mirror SolverBackendConfig.
        self.relax_enabled = True
        #: backlogs below this stay on the exact arms (the LP's win is
        #: amortizing the round loop over HUGE contended backlogs)
        self.relax_min_workloads = 4096
        #: every Nth relax-served drain ALSO runs the exact kernel and
        #: compares plans; divergence demotes the arm (0 = never audit)
        self.relax_audit_every = 8
        #: fixed projected-gradient iteration count (determinism)
        self.relax_iters = 32
        #: rounding threshold on the fractional admit vector
        self.relax_support_threshold = 0.5
        #: demoted-arm cooldown before one re-probe (timed half-open,
        #: mirroring the mesh breaker)
        self.relax_retry_cooldown_s = 300.0
        #: pin lean drains to the relax arm (bench/tests only)
        self.relax_force = False
        self._relax_broken = False
        self._relax_drains = 0
        #: sticky pow2 pad target for the repair subproblem's support
        #: axis, so steady-state relax drains reuse ONE compiled repair
        self._relax_pad_hwm = 0
        #: stats of the most recent relaxed solve (bench/diagnostics)
        self.last_relax_stats = None
        #: result of the most recent disagreement audit (None = the
        #: last relax drain was not audited)
        self.last_relax_audit: Optional[bool] = None
        #: streaming micro-batch admitter (scheduler/streaming.py);
        #: every completed full drain re-arms its fences — a full
        #: solve is the oracle-parity baseline boundary
        self.streaming = None

    def _tracer(self):
        if self.tracer is not None:
            return self.tracer
        return getattr(self.scheduler, "tracer", None)

    def supported(self) -> bool:
        """Whether the drain can run on-device.

        The full kernel covers classical preemption, multiple resource
        groups, fair sharing (DRS tournament + S2-a/S2-b), and
        admission fair sharing (KEP-4136: penalty-ordered head
        selection with entry penalties charged on admission). TAS
        shapes are rejected at export (UnsupportedProblem).
        """
        return True

    def needs_full_kernel(
            self,
            pending: Optional[dict[str, list[WorkloadInfo]]] = None,
    ) -> bool:
        """Preemption, multi-RG, fair-sharing, or AFS shapes run the
        unified-axis kernel; the lean fit-only kernel stays for the
        uncontended classical case.

        With `pending` (the drain's backlog), only CQs that are
        actually ADMITTING this drain are consulted: preemption is
        initiated by the admitting CQ under its own policies, so idle
        preemption-enabled CQs elsewhere in the store must not route an
        uncontended flood off the lean fast path (round-4 verdict: the
        store-global check cost uncontended backlogs ~3x)."""
        if self.enable_fair_sharing:
            return True
        if pending is not None:
            cqs = [self.store.cluster_queues[name]
                   for name in pending
                   if name in self.store.cluster_queues]
        else:
            cqs = list(self.store.cluster_queues.values())
        for cq in cqs:
            if cq.preemption.any_enabled:
                return True
            if len(cq.resource_groups) > 1:
                return True
            if (cq.admission_scope is not None
                    and self.queues.afs is not None):
                return True
        return False

    def _is_tas_cq(self, cq_name: str) -> bool:
        """Any flavor with a Topology makes admissions TAS-placed (explicit
        or implied requests — flavor_assigner workload_topology_requests);
        those need the host tree, so the solver leaves them pending."""
        spec = self.store.cluster_queues.get(cq_name)
        if spec is None:
            return False
        for rg in spec.resource_groups:
            for fq in rg.flavors:
                fl = self.store.resource_flavors.get(fq.name)
                if fl is not None and fl.topology_name is not None:
                    return True
        return False

    def flavor_witness(self) -> dict[str, dict]:
        """Per-CQ static flavor-option capacity ceilings for the
        streaming flavor-pick witness, cached by ``ExportCache.spec_gen``
        (any quota/flavor/cohort edit invalidates it together with the
        export tensors it mirrors). The streaming admitter combines
        these with the post-solve window snapshot to decide whether a
        multi-flavor pick could be flipped by a capacity event
        (tensors.flavor_option_ceilings, scheduler/streaming.py)."""
        gen = self.export_cache.spec_gen
        cached = self._flavor_witness_cache
        if cached is not None and cached[0] == gen:
            return cached[1]
        from kueue_oss_tpu.solver.tensors import flavor_option_ceilings

        witness = flavor_option_ceilings(self.store)
        self._flavor_witness_cache = (gen, witness)
        return witness

    def pending_backlog(self) -> dict[str, list[WorkloadInfo]]:
        """Current heap contents per CQ in rank (pop) order, plus stale
        parked entries owed a retry (lazy capacity-freed flushes merge
        into the backlog virtually instead of re-heaping — the rank
        order is the same _order_key sort a physical flush produces).

        TAS-shaped workloads (explicit topology requests, podset groups,
        or any CQ whose flavors carry a Topology) are excluded: the
        kernel admits without computing topology assignments, so those
        stay in their heaps for the host scheduler's mop-up cycles
        (Scheduler.run_until_quiet after _solver_drain), which run the
        full TAS machinery. Stale TAS entries are materialized back into
        their heaps for the same host path."""
        from kueue_oss_tpu.core.queue_manager import _order_key

        out: dict[str, list[WorkloadInfo]] = {}
        self._drain_tas_ready = set()
        for name, q in self.queues.queues.items():
            if not q.active:
                continue
            if self._is_tas_cq(name):
                if not self._tas_device_ready(name, q):
                    q.materialize_stale()
                    continue
                # device-TAS path: quota through the kernel, placement
                # through the sequential device placer at apply time
                self._drain_tas_ready.add(name)
                stale = q.stale_infos() if q._stale else []
                infos = q.snapshot_order()
                if stale:
                    infos = sorted(infos + stale, key=_order_key)
                if infos:
                    out[name] = infos
                continue
            stale = q.stale_infos() if q._stale else []
            if stale and any(ps.topology_request is not None
                             for i in stale for ps in i.obj.podsets):
                # hand topology-requesting stale entries (and their
                # queue-mates, to keep one rank order) to the host path
                q.materialize_stale()
                stale = []
            infos = q.snapshot_order()
            if stale:
                infos = sorted(infos + stale, key=_order_key)
            infos = [i for i in infos
                     if all(ps.topology_request is None
                            for ps in i.obj.podsets)]
            if infos:
                out[name] = infos
        return out

    def _tas_device_ready(self, name: str, q) -> bool:
        """Whether this TAS CQ's ENTIRE backlog (heap + parked) is
        device-placeable. All-or-nothing per CQ keeps StrictFIFO head
        order exact: exporting followers around an unsupported head
        would let the kernel admit past a blocked head."""
        if not self.device_tas:
            return False
        spec = self.store.cluster_queues.get(name)
        if spec is None:
            return False
        from kueue_oss_tpu.solver.tas_engine import device_tas_supported

        for info in list(q._in_heap.values()) + list(
                q.inadmissible.values()):
            if not device_tas_supported(info, self.store, spec):
                return False
        return True

    def _compute_tas_assignments(self, candidates, snapshot=None):
        """Device-place admitted TAS candidates in admission order.

        Returns (kept_candidates, topology_by_workload_key); candidates
        whose placement failed are dropped — they stay in their heaps
        for the host mop-up cycles after the drain. ``snapshot`` is the
        pipelined-dispatch prework (lean drains only — the full path's
        evictions invalidate a pre-built snapshot)."""
        tas_items = []
        for cand in candidates:
            _wl, cq_name, flavor_of, info, _usage = cand
            if cq_name in self._drain_tas_ready and flavor_of:
                flavor = (next(iter(flavor_of.values()))
                          if isinstance(flavor_of, dict) else flavor_of)
                tas_items.append((info, flavor))
        if not tas_items:
            return candidates, {}
        from kueue_oss_tpu.core.snapshot import build_snapshot
        from kueue_oss_tpu.solver.tas_engine import DeviceTASPlacer

        if self._tas_placer is None:
            self._tas_placer = DeviceTASPlacer(self.store)
        if snapshot is None:
            snapshot = build_snapshot(self.store)
        placements = self._tas_placer.place_batch(snapshot, tas_items)
        # only candidates actually submitted for placement can fail out
        # of the plan; a TAS-CQ candidate with no flavored resources has
        # no TAS request at all (workload_topology_requests skips empty
        # psa.flavors) and commits without an assignment — host parity
        submitted = {info.key for info, _ in tas_items}
        kept = []
        topo_of: dict[str, TopologyAssignment] = {}
        for cand in candidates:
            _wl, cq_name, _f, info, _usage = cand
            if cq_name in self._drain_tas_ready and info.key in submitted:
                ta = placements.get(info.key)
                if ta is None:
                    metrics.solver_plan_fallbacks_total.inc()
                    obs.recorder.record(
                        obs.SOLVER_FALLBACK, info.key,
                        cycle=self._drain_cycle, cluster_queue=cq_name,
                        path=obs.SOLVER,
                        reason="device TAS placement failed; workload "
                               "stays queued for the host mop-up cycle",
                        reason_slug="tas_place_failed")
                    continue  # host mop-up places (or rejects) it
                topo_of[info.key] = ta
            kept.append(cand)
        return kept, topo_of

    def export(
            self,
            pending: Optional[dict[str, list[WorkloadInfo]]] = None,
    ) -> tuple[SolverProblem, dict[str, list[WorkloadInfo]]]:
        if pending is None:
            pending = self.pending_backlog()
        problem = export_problem(self.store, pending,
                                 cache=self.export_cache)
        return problem, pending

    def drain(self, now: float = 0.0, verify: bool = False) -> DrainResult:
        """Solve the whole backlog on-device and commit the plan.

        Preemption-capable and multi-resource-group stores route through
        the full kernel (solve_backlog_full) so preemption shapes are
        never silently solved fit-only; the lean kernel keeps the
        uncontended fast path.
        """
        if not self.supported():
            raise UnsupportedProblem(
                "admission-scope or weighted fair-sharing CQs present")
        self.drain_count += 1
        self._drain_cycle = (self.scheduler.cycle_count + 1
                             if self.scheduler is not None
                             else self.drain_count)
        if self.remote is not None and not self.health.allow():
            # open breaker: refuse without touching the socket so the
            # admission round proceeds on the host path immediately
            metrics.solver_fallback_total.inc("breaker_open")
            obs.recorder.record(
                obs.SOLVER_FALLBACK, obs.CYCLE_SCOPE,
                cycle=self._drain_cycle, path=obs.SOLVER,
                reason="solver backend breaker is open (cooling down); "
                       "admissions degrade to the host cycle",
                reason_slug="breaker_open")
            raise SolverUnavailable(
                "solver backend breaker is open (cooling down)")
        tracer = self._tracer()
        with (tracer.span("solver_drain", cycle=self._drain_cycle)
              if tracer is not None else contextlib.nullcontext()):
            completed = False
            if self.streaming is not None:
                # mark which fences this solve's export can cover:
                # events landing mid-solve keep their subtree fenced
                # past note_full_solve (the solve never saw them)
                self.streaming.note_solve_begin()
            try:
                result = self._drain(now, verify)
                completed = True
                return result
            finally:
                # prework computed for a drain that failed before its
                # apply must never leak into the next drain (stale
                # workload refs would bypass the store lookups)
                self._prework = None
                # durability barrier: a drain's plan applications are
                # group-committed before the scheduler builds on them
                persistence = getattr(self.store, "persistence", None)
                if persistence is not None:
                    persistence.flush()
                if self.streaming is not None:
                    # full-solve boundary: the streaming fences reset
                    # against the post-solve store (a failed drain
                    # keeps them down — host fallback cycles are not
                    # a parity baseline — but must stop attributing
                    # events to the dead solve)
                    if completed:
                        self.streaming.note_full_solve()
                    else:
                        self.streaming.note_solve_abort()

    def _drain(self, now: float, verify: bool) -> DrainResult:
        pending = self.pending_backlog()
        if self.needs_full_kernel(pending):
            return self._drain_full(now, verify=verify, pending=pending)
        result = DrainResult()
        self._drain_phases = {}
        te = time.monotonic()
        problem, pending = self.export(pending)
        self._note_export_phase(time.monotonic() - te)
        if problem.n_workloads == 0:
            return result
        # pad_workloads rebuilds the dataclass, so the columnar hint
        # must be captured off the unpadded export (real-row positions
        # survive padding; the hint's row indices stay valid)
        hint = getattr(problem, "_columnar_hint", None)
        n_live = problem.n_workloads
        self._pad_hwm = max(self._pad_hwm,
                            pow2(max(problem.n_workloads, self.pad_to)))
        problem = pad_workloads(problem, self._pad_target())
        problem, frame = self._session_encode("lean", problem, hint=hint)
        dev0 = self._device_totals()

        t0 = time.monotonic()
        if self.remote is not None:
            (admitted, opt, admit_round, parked, rounds,
             _usage) = self._dispatch_remote(
                problem, 6, frame, "lean", verify, full=False)
        else:
            (admitted, opt, admit_round, parked, rounds,
             _usage) = self._local_solve(problem, frame, full=False,
                                         n_live=n_live)
        admitted = np.asarray(admitted)
        opt = np.asarray(opt)
        admit_round = np.asarray(admit_round)
        parked = np.asarray(parked)
        if self.remote is not None:
            # guard IMPORTED plans only: the in-process kernel is
            # trusted (a local bug should fail tests loudly, not
            # silently degrade), and the local hot path stays free of
            # the O(W) validation passes
            self._check_plan(problem, admitted, opt, admit_round,
                             parked, rounds=rounds, full=False)
        result.rounds = int(rounds)
        result.solver_time_s = time.monotonic() - t0
        metrics.solver_cycle_duration_seconds.observe(
            "solve", value=result.solver_time_s)

        t1 = time.monotonic()
        self._apply_plan(problem, admitted, opt, admit_round, parked, now,
                         result, verify=verify)
        result.apply_time_s = time.monotonic() - t1
        metrics.solver_cycle_duration_seconds.observe(
            "apply", value=result.apply_time_s)
        self._ledger_record(
            result, frame, "lean", dev0,
            parked_n=int(np.asarray(
                parked[:problem.n_workloads]).astype(bool).sum()))
        return result

    # -- cycle ledger (obs/ledger.py) --------------------------------------

    def _device_totals(self) -> dict:
        """Cumulative donated-buffer accounting across every resident
        device state (both arms); the ledger records per-drain DELTAS
        of these."""
        totals = {"donated_update_bytes": 0, "avoided_copy_bytes": 0,
                  "full_upload_bytes": 0, "donated_full_syncs": 0}
        for dev in self._device_states.values():
            for k in totals:
                totals[k] += int(getattr(dev, k, 0))
        return totals

    def _resident_bytes(self) -> int:
        """Problem bytes pinned on device right now, summed over every
        resident state (both kernels, both arms) — devtel's portable
        HBM watermark."""
        return sum(int(dev.resident_bytes())
                   for dev in self._device_states.values()
                   if hasattr(dev, "resident_bytes"))

    def _ledger_record(self, result: DrainResult, frame, kind: str,
                       dev0: dict, parked_n: int) -> None:
        """One solver ledger row per drain, keyed by the same cycle id
        the recorder's DecisionEvents carry — solver routing, session
        wire kind/bytes, resident-buffer churn, and (devtel) the
        drain's transfer/HBM/compile/grant-wait telemetry in one
        record."""
        ledger = obs.cycle_ledger
        dev1 = self._device_totals()
        device = {k: dev1[k] - dev0.get(k, 0)
                  for k in dev1 if dev1[k] - dev0.get(k, 0)}
        arm = ("remote" if self.remote is not None
               else (self.last_drain_arm or "single"))
        tenant = getattr(self.remote, "tenant", "")
        dtl = devtel.collector
        if dtl.enabled:
            # unified transfer family + per-drain HBM watermark; the
            # gauges/counters flow even with the ledger disabled (the
            # bench twin's off arm disables the ledger, not devtel)
            dtl.note_transfers(arm, tenant, device)
            device.update(dtl.sample_residency(self._resident_bytes()))
            events = dtl.compiles.drain_events()
            if events:
                device["compiles"] = len(events)
                device["compile_events"] = events
            dtl.on_drain()
        if not ledger.enabled:
            return
        frame_kind, frame_bytes, frame_reason, session = "legacy", 0, "", {}
        if frame is not None:
            session = dict(frame.stats or {})
            if frame.delta is not None:
                frame_kind = "delta"
                frame_bytes = int(frame.delta.payload_bytes())
            else:
                frame_kind = "sync"
                frame_reason = frame.full_reason or ""
                sess_obj = self._delta_sessions.get(kind)
                if sess_obj is not None:
                    frame_bytes = sess_obj.last_sync_wire_bytes()
        phases = {"solve": round(result.solver_time_s, 6),
                  "apply": round(result.apply_time_s, 6)}
        # export/encode/device_put walls + the columnar walk/scatter
        # split, accumulated by _note_export_phase/_session_encode/
        # _local_tensors over this drain
        for k, v in (getattr(self, "_drain_phases", None) or {}).items():
            phases[k] = round(v, 6)
        session.update(getattr(self, "_export_stats", None) or {})
        # farm tenancy attribution (docs/FEDERATION.md): ledger rows
        # from a control plane sharing a multi-tenant solver farm carry
        # the tenant id its frames were billed under
        if tenant:
            session["tenant"] = tenant
        # the farm's DRR grant-wait for this drain's solve request,
        # echoed back by the sidecar (0 = dedicated / host / farm idle)
        grant_wait_ms = float(getattr(self.remote, "last_grant_wait_ms",
                                      0.0) or 0.0)
        ledger.record(
            self._drain_cycle, obs.SOLVER_DRAIN,
            breaker=obs.breaker_state_name(),
            duration_s=result.solver_time_s + result.apply_time_s,
            phases=phases,
            admitted=result.admitted, evicted=result.evicted,
            parked=parked_n, rounds=result.rounds, solver_arm=arm,
            frame_kind=frame_kind, frame_bytes=frame_bytes,
            frame_reason=frame_reason, session=session,
            grant_wait_ms=grant_wait_ms, device=device)

    # -- mesh routing (solver/meshutil.py, solver/sharded.py) --------------

    # The mesh/relax breaker state lives on the process-wide
    # DegradationController (resilience package) — one ladder, one
    # cooldown policy, observable levels. These properties keep the
    # historical private names working for tests and diagnostics.

    @property
    def _mesh_broken(self) -> bool:
        return resilience.controller.active(resilience.SOLVER,
                                            "mesh_broken")

    @_mesh_broken.setter
    def _mesh_broken(self, v: bool) -> None:
        resilience.controller.report(
            resilience.SOLVER, "mesh_broken", bool(v),
            cycle=self._drain_cycle,
            reason=("mesh arm tripped" if v
                    else "mesh re-probed; arm restored"))

    @property
    def _mesh_broken_at(self) -> float:
        return (resilience.controller.cooldowns.stamp(
            (resilience.SOLVER, "mesh_broken")) or 0.0)

    @_mesh_broken_at.setter
    def _mesh_broken_at(self, t: float) -> None:
        resilience.controller.cooldowns.set_stamp(
            (resilience.SOLVER, "mesh_broken"), float(t))

    @property
    def _relax_broken(self) -> bool:
        return resilience.controller.active(resilience.SOLVER,
                                            "relax_broken")

    @_relax_broken.setter
    def _relax_broken(self, v: bool) -> None:
        resilience.controller.report(
            resilience.SOLVER, "relax_broken", bool(v),
            cycle=self._drain_cycle,
            reason=("relaxed arm demoted" if v
                    else "relaxed arm re-probed; arm restored"))

    @property
    def _relax_broken_at(self) -> float:
        return (resilience.controller.cooldowns.stamp(
            (resilience.SOLVER, "relax_broken")) or 0.0)

    @_relax_broken_at.setter
    def _relax_broken_at(self, t: float) -> None:
        resilience.controller.cooldowns.set_stamp(
            (resilience.SOLVER, "relax_broken"), float(t))

    def _mesh(self):
        """The resolved solver mesh, or None (single device / off /
        tripped by a mesh fault). A tripped mesh self-heals after
        ``mesh_retry_cooldown_s`` (timed half-open via the degradation
        controller's cooldown policy: ONE probe drain re-measures,
        concurrent drains stay single-chip; another fault re-trips and
        restarts the clock)."""
        if self._mesh_broken:
            if not resilience.controller.begin_probe(
                    resilience.SOLVER, "mesh_broken",
                    self.mesh_retry_cooldown_s):
                return None
            self.refresh_mesh(self._mesh_max_devices)
        if not self._mesh_resolved:
            from kueue_oss_tpu.solver import meshutil

            try:
                self._mesh_obj = meshutil.detect_mesh(
                    self.mesh_mode, self._mesh_max_devices)
            except Exception:
                self._mesh_obj = None  # backend init failure != crash
            self._mesh_resolved = True
        return self._mesh_obj

    def refresh_mesh(self, max_devices: int = 0) -> int:
        """Re-detect the mesh (recovery probe, or the chaos harness's
        mesh-shrink: ``max_devices`` caps the width the way a lost
        device shrinks the usable slice). Drops mesh-resident device
        state and the mesh arm's cost estimate so the new topology is
        re-measured from scratch. Returns the new device count."""
        from kueue_oss_tpu.solver import meshutil

        self._mesh_max_devices = max_devices
        self._mesh_broken = False
        self._mesh_resolved = False
        for kind in ("lean", "full"):
            self._device_states.pop(kind + "-mesh", None)
            self._arm_ema.pop((kind, "mesh"), None)
            self._arm_warm.discard((kind, "mesh"))
            devtel.collector.forget(kind, "mesh")
        return meshutil.mesh_devices(self._mesh())

    def _pick_mesh_arm(self, kind: str, n_workloads: int):
        """The mesh to drain on, or None for single-chip — cost-EMA
        routing with one probe per arm."""
        mesh = self._mesh()
        if mesh is None:
            return None
        if self.mesh_force:
            return mesh
        if n_workloads < self.mesh_min_workloads:
            return None
        e_mesh = self._arm_ema.get((kind, "mesh"))
        e_single = self._arm_ema.get((kind, "single"))
        if e_mesh is None:
            return mesh          # probe the mesh arm first
        if e_single is None:
            return None          # then the single-chip arm
        if e_mesh <= e_single:
            # decay the skipped arm so an out-of-date estimate erodes
            # and the loser eventually re-probes (same rationale as the
            # scheduler's _drain_cost_ema decay)
            self._arm_ema[(kind, "single")] = e_single * 0.98
            return mesh
        self._arm_ema[(kind, "mesh")] = e_mesh * 0.98
        return None

    def _note_arm_wall(self, kind: str, arm: str, wall_s: float,
                       n_workloads: int) -> None:
        key = (kind, arm)
        dtl = devtel.collector
        if dtl.enabled and dtl.compile_enabled:
            # devtel's per-(kernel, arm, shape-bucket) verdict replaces
            # the legacy one-shot warm set: a warm arm re-solving at a
            # new padded width is caught (its compile-tainted wall
            # stays out of the EMA), and a warm arm's first sample is
            # no longer wasted
            if dtl.observe_solve(kind, arm, n_workloads, wall_s):
                return
        elif key not in self._arm_warm:
            # compile-tainted probe sample: discard it (the arm stays
            # unmeasured, so the router probes it once more, warm)
            self._arm_warm.add(key)
            return
        per_wl = wall_s / max(1, n_workloads)
        prev = self._arm_ema.get(key)
        self._arm_ema[key] = (
            per_wl if prev is None else 0.7 * prev + 0.3 * per_wl)

    def _clear_device_error(self) -> None:
        """A local solve landed: the accelerator works again, so the
        device_error rung (host-only) recovers on the ladder."""
        ctl = resilience.controller
        if ctl.active(resilience.SOLVER, "device_error"):
            ctl.report(resilience.SOLVER, "device_error", False,
                       cycle=self._drain_cycle,
                       reason="local solve succeeded; device healthy")

    def _note_mesh_failure(self, e: BaseException, kind: str) -> None:
        """A mesh drain fault (device loss / compile abort / injected):
        count it, drop the possibly-corrupt mesh-resident state, and
        degrade to single-chip until refresh_mesh() or the retry
        cooldown re-probes. Never silent — metered AND journaled."""
        resilience.controller.report(
            resilience.SOLVER, "mesh_broken", True,
            cycle=self._drain_cycle,
            reason=f"mesh drain failed ({e!r}); degrading to the "
                   "single-chip solver arm")
        self._arm_warm.discard((kind, "mesh"))
        devtel.collector.forget(kind, "mesh")
        self._device_states.pop(kind + "-mesh", None)
        metrics.solver_fallback_total.inc("mesh_error")
        metrics.solver_mesh_devices.set(value=0)
        obs.recorder.record(
            obs.SOLVER_FALLBACK, obs.CYCLE_SCOPE, cycle=self._drain_cycle,
            path=obs.SOLVER,
            reason=f"mesh drain failed ({e!r}); degrading to the "
                   "single-chip solver arm",
            reason_slug="mesh_error")

    def _local_solve(self, problem: SolverProblem, frame, *, full: bool,
                     n_live: Optional[int] = None, **caps):
        """In-process solve: relax -> mesh -> single-chip fallback chain.

        The relaxed fast-path arm (solver/relax.py) is tried first for
        lean drains the router picks it for; any relax fault or audit
        divergence falls through to the exact chain below — the full
        degradation ladder is relax -> mesh -> single-chip -> host,
        every hop metered (solver_fallback_total{relax_*/mesh_error/
        device_error}).
        """
        if n_live is None:
            from kueue_oss_tpu.solver import meshutil

            n_live = meshutil.live_rows(problem.wl_cqid, problem.n_cqs)
        if not full and self._pick_relax_arm(n_live):
            out = self._relax_solve(problem, frame, n_live)
            if out is not None:
                return out
        return self._solve_exact(problem, frame, full=full,
                                 n_live=n_live, **caps)

    # -- relaxed fast-path arm (solver/relax.py) ---------------------------

    def _relax_available(self) -> bool:
        if not self.relax_enabled:
            return False
        if self._relax_broken:
            # timed half-open via the degradation controller: one probe
            # drain re-measures once the cooldown elapses; another
            # fault or divergence re-demotes and restarts the clock
            if not resilience.controller.begin_probe(
                    resilience.SOLVER, "relax_broken",
                    self.relax_retry_cooldown_s):
                return False
            self._relax_broken = False
            self._arm_warm.discard(("lean", "relax"))
            devtel.collector.forget("lean", "relax")
        return True

    def _pick_relax_arm(self, n_live: int) -> bool:
        """Whether this lean drain should try the relaxed arm — the
        cost-EMA router's fourth arm: probe once above the backlog
        floor, then engage only while its measured per-workload wall
        beats the best exact arm's (the loser decays so a regressing
        winner gets re-measured, exactly like the mesh arm)."""
        if not self._relax_available():
            return False
        if self.relax_force:
            return True
        if n_live < self.relax_min_workloads:
            return False
        e_relax = self._arm_ema.get(("lean", "relax"))
        exact = [e for e in (self._arm_ema.get(("lean", "single")),
                             self._arm_ema.get(("lean", "mesh")))
                 if e is not None]
        if e_relax is None:
            # probe only once an exact baseline exists: the first
            # drains of a flood must establish the reference cost the
            # audit and the router compare against
            return bool(exact)
        if not exact:
            return True
        if e_relax <= min(exact):
            return True
        self._arm_ema[("lean", "relax")] = e_relax * 0.98
        return False

    def _note_relax_failure(self, e: Optional[BaseException],
                            slug: str) -> None:
        """Demote the relaxed arm (fault or audit divergence): counted,
        journaled, cooled down — never silent, never wedged open."""
        reason = ("relaxed-arm plan diverged from the exact kernel on "
                  "an audited drain; arm demoted (exact plan emitted)"
                  if slug == "relax_disagreement" else
                  f"relaxed solver arm fault ({e!r}); falling back to "
                  "the exact arms")
        resilience.controller.report(
            resilience.SOLVER, "relax_broken", True,
            cycle=self._drain_cycle, reason=reason)
        self._arm_ema.pop(("lean", "relax"), None)
        self._arm_warm.discard(("lean", "relax"))
        devtel.collector.forget("lean", "relax")
        metrics.solver_fallback_total.inc(slug)
        obs.recorder.record(
            obs.SOLVER_FALLBACK, obs.CYCLE_SCOPE,
            cycle=self._drain_cycle, path=obs.SOLVER,
            reason=reason, reason_slug=slug)

    def _relax_solve(self, problem: SolverProblem, frame, n_live: int):
        """One relaxed-arm attempt. Returns the plan tuple, or None to
        fall through to the exact chain (arm fault). Audited drains
        ALSO run the exact chain and emit ITS plan — identical
        decisions when the audit passes, and the authoritative plan
        when it does not (plan fidelity never rides on the LP)."""
        import time as _time

        from kueue_oss_tpu.solver import relax

        self._relax_drains += 1
        audit = (self.relax_audit_every > 0
                 and (self._relax_drains == 1
                      or self._relax_drains % self.relax_audit_every
                      == 0))
        self.last_relax_audit = None
        try:
            if self.solve_fault_hook is not None:
                self.solve_fault_hook("relax")
            t0 = _time.monotonic()
            # solve_relaxed itself falls back to the single-chip LP
            # when the padded axis does not shard evenly
            mesh = self._mesh()
            out, stats = relax.solve_relaxed(
                problem, iters=self.relax_iters,
                threshold=self.relax_support_threshold, mesh=mesh,
                pad_to=self._relax_pad_hwm)
            wall = _time.monotonic() - t0
        except Exception as e:
            self._note_relax_failure(e, "relax_error")
            metrics.solver_relax_drains_total.inc("error")
            return None
        self._relax_pad_hwm = max(self._relax_pad_hwm,
                                  stats.support_padded)
        self.last_relax_stats = stats
        if stats.live:
            metrics.solver_relax_support_fraction.observe(
                value=stats.support / stats.live)
        self._note_arm_wall("lean", "relax", wall, n_live)
        if audit:
            exact = self._solve_exact(problem, frame, full=False,
                                      n_live=n_live)
            agree = relax.plans_agree(out, exact, problem.n_workloads)
            self.last_relax_audit = agree
            if agree:
                metrics.solver_relax_drains_total.inc("audit_match")
            else:
                metrics.solver_relax_drains_total.inc("audit_diverged")
                self._note_relax_failure(None, "relax_disagreement")
            return exact
        # relax-SERVED drain (no audit ran the exact chain): keep any
        # EXISTING exact-arm resident device states current by applying
        # the frame's delta scatter now. Dropping it would leave them
        # epoch-stuck, forcing the next exact/audit solve into a full
        # padded re-upload charged to the exact arm's cost EMA (biasing
        # the router toward relax) and defeating the delta-session
        # residency while the relax arm serves. Audited drains skip
        # this — their _solve_exact applies the frame itself.
        if frame is not None:
            for key in ("lean", "lean-mesh"):
                dev = self._device_states.get(key)
                if dev is None:
                    continue
                try:
                    dev.update(problem, frame, False)
                except Exception:
                    # a failed scatter must not fault the drain; the
                    # next exact solve re-seeds from the host problem
                    self._device_states.pop(key, None)
        metrics.solver_relax_drains_total.inc("served")
        self.last_drain_arm = "relax"
        return out

    def _solve_exact(self, problem: SolverProblem, frame, *, full: bool,
                     n_live: Optional[int] = None, **caps):
        """In-process EXACT solve with the mesh -> single-chip fallback
        chain.

        The mesh arm (when routed) drains the resident mesh-placed
        state through the sharded SPMD program; any fault there is
        counted and the SAME drain re-runs on the single-chip arm. A
        single-chip fault escalates to SolverUnavailable so the
        scheduler completes the admission round on host cycles — the
        full chain is mesh -> single-chip -> host, every hop metered.
        Outputs are materialized to numpy INSIDE each arm's window so
        device faults surface here, not mid-apply.
        """
        import time as _time

        from kueue_oss_tpu.solver import meshutil

        kind = "full" if full else "lean"
        # arm routing keys off the LIVE backlog, not the padded
        # capacity: the sticky pad high-water mark must not keep a
        # 3-workload trickle on the mesh arm after one large flood
        if n_live is None:
            n_live = meshutil.live_rows(problem.wl_cqid, problem.n_cqs)
        W = n_live
        mesh = self._pick_mesh_arm(kind, W)
        if mesh is not None:
            try:
                # ONLY the fault-prone device work lives in the guarded
                # block: bookkeeping below must not turn a metrics
                # hiccup into a discarded plan + tripped mesh
                if self.solve_fault_hook is not None:
                    self.solve_fault_hook("mesh")
                t0 = _time.monotonic()
                tensors = self._local_tensors(problem, frame, full=full,
                                              mesh=mesh)
                if full:
                    from kueue_oss_tpu.solver.full_kernels import (
                        solve_backlog_full,
                    )

                    out = solve_backlog_full(tensors, mesh=mesh, **caps)
                else:
                    out = meshutil.lean_mesh_solver(mesh)(tensors)
                out = tuple(np.asarray(a) for a in out)
                wall = _time.monotonic() - t0
            except Exception as e:
                self._note_mesh_failure(e, kind)
            else:
                self._note_arm_wall(kind, "mesh", wall, W)
                self.last_drain_arm = "mesh"
                metrics.solver_mesh_devices.set(
                    value=meshutil.mesh_devices(mesh))
                # both drains row-shard the workload axis now (the
                # full kernel composes lane sharding on top), so both
                # observe block-shard skew
                metrics.solver_shard_imbalance.observe(
                    value=meshutil.shard_imbalance(
                        problem.wl_cqid, problem.n_cqs, mesh))
                self._clear_device_error()
                return out
        try:
            if self.solve_fault_hook is not None:
                self.solve_fault_hook("single")
            t0 = _time.monotonic()
            tensors = self._local_tensors(problem, frame, full=full)
            if full:
                from kueue_oss_tpu.solver.full_kernels import (
                    solve_backlog_full,
                )

                out = solve_backlog_full(tensors, **caps)
            else:
                out = solve_backlog(tensors)
            out = tuple(np.asarray(a) for a in out)
        except Exception as e:
            # the single-chip arm died too (whole accelerator gone):
            # degrade the round to host cycles, counted, never silent
            self._device_states.pop(kind, None)
            metrics.solver_fallback_total.inc("device_error")
            metrics.solver_mesh_devices.set(value=0)
            resilience.controller.report(
                resilience.SOLVER, "device_error", True,
                cycle=self._drain_cycle,
                reason=f"local solver backend fault ({e!r}); admissions "
                       "degrade to the host cycle")
            obs.recorder.record(
                obs.SOLVER_FALLBACK, obs.CYCLE_SCOPE,
                cycle=self._drain_cycle, path=obs.SOLVER,
                reason=f"local solver backend fault ({e!r}); admissions "
                       "degrade to the host cycle",
                reason_slug="device_error")
            raise SolverUnavailable(
                f"local solver backend fault: {e!r}") from e
        self._note_arm_wall(kind, "single", _time.monotonic() - t0, W)
        self.last_drain_arm = "single"
        metrics.solver_mesh_devices.set(value=0)
        self._clear_device_error()
        return out

    # -- delta-sync sessions + pipelined dispatch --------------------------

    def _pad_target(self) -> int:
        """Sticky pad target: the pow2 high-water mark, mesh-aligned
        (meshutil.align_pad_target) so the padded workload axis plus
        the null row block-shards evenly over the mesh. Alignment is
        applied whenever a mesh is AVAILABLE — even on drains routed to
        the single-chip arm — so session slot indices map to stable
        (shard, local-row) coordinates across drains and both arms
        solve the byte-identical padded problem. A remote sidecar's
        advertised mesh width (learned from its session responses —
        the client host may have no accelerators at all) joins the
        alignment via lcm; the one-time capacity change when it is
        first learned rides a counted shape_change full sync."""
        from kueue_oss_tpu.solver.meshutil import align_pad_target

        remote_w = (getattr(self.remote, "remote_mesh_devices", 0)
                    if self.remote is not None else 0)
        return align_pad_target(self._pad_hwm, self._mesh(), remote_w)

    def reset_sessions(self, reason: str = "restart") -> None:
        """Drop delta-sync session and resident-device state so the
        next drain of each kind opens with a full SYNC.

        The recovery path calls this after rebuilding a store
        (docs/DURABILITY.md): resident device buffers and sidecar
        session state are gone by design across a restart, and a
        warmed-by-replay store must never be diffed against slot state
        from before the failover."""
        if self._delta_sessions or self._device_states:
            metrics.solver_resync_total.inc(reason)
        self._delta_sessions.clear()
        self._device_states.clear()

    def _note_export_phase(self, wall_s: float) -> None:
        """Fold one export's wall + the columnar view's walk/scatter
        split and dirty-row counts into this drain's phase breakdown
        (ledger satellite: export cost must be attributable)."""
        phases = getattr(self, "_drain_phases", None)
        if phases is None:
            phases = self._drain_phases = {}
        phases["export"] = phases.get("export", 0.0) + wall_s
        col = getattr(self.export_cache, "columnar", None)
        stats = getattr(col, "last_stats", None) or {}
        if stats:
            phases["export_walk"] = (phases.get("export_walk", 0.0)
                                     + stats.get("walk_s", 0.0))
            phases["export_scatter"] = (
                phases.get("export_scatter", 0.0)
                + stats.get("scatter_s", 0.0))
            self._export_stats = {
                "export_mode": stats.get("mode", ""),
                "export_dirty_rows": int(stats.get("dirty_rows", 0)),
                "export_rows": int(stats.get("rows", 0))}
        else:
            self._export_stats = {}

    def _session_encode(self, kind: str, problem: SolverProblem,
                        hint=None):
        """Stable slot/rank re-encoding + the SessionFrame to ship.

        Returns (problem, None) with sessions disabled — the drain then
        behaves exactly like the pre-session engine. A remote client
        configured for legacy frames (sessions_enabled=false) disables
        the whole session layer: there is no point paying the stable
        re-encoding for deltas that would never be sent.
        """
        if not self.use_sessions:
            return problem, None
        if (self.remote is not None
                and not getattr(self.remote, "use_sessions", True)):
            return problem, None
        sess = self._delta_sessions.get(kind)
        if sess is None:
            # the full kernel has no wl_rank tensor (FIFO order rides
            # the timestamp ranks); neutralizing it keeps per-CQ rank
            # ripples off the full session's wire
            neutral = ("wl_rank",) if kind == "full" else ()
            sess = HostDeltaSession(cache=self.export_cache,
                                    neutral_fields=neutral)
            self._delta_sessions[kind] = sess
        # slot->shard interleaving follows whichever mesh the resident
        # tensors will shard over: the remote sidecar's advertised
        # width when a sidecar serves the drains, the local mesh
        # otherwise. A width change is an epoch migration — ONE counted
        # RESYNC re-lays the slots out and rebuilds resident tensors.
        from kueue_oss_tpu.solver.meshutil import mesh_devices

        remote_w = (int(getattr(self.remote, "remote_mesh_devices", 0))
                    if self.remote is not None else 0)
        sess.set_interleave(remote_w if remote_w > 1
                            else mesh_devices(self._mesh()))
        # no sidecar will recompute state_checksum over frames on the
        # local path, so fast-path frames may carry the cheap chained
        # checksum instead of an O(W) crc per drain
        sess.cheap_checksum = self.remote is None
        t0 = time.monotonic()
        slotted, frame = sess.advance(problem, hint=hint)
        phases = getattr(self, "_drain_phases", None)
        if phases is not None:
            phases["encode"] = (phases.get("encode", 0.0)
                                + time.monotonic() - t0)
        if frame is not None and frame.full_reason == "interleave_migration":
            metrics.solver_resync_total.inc("interleave_migration")
        return slotted, frame

    def _local_tensors(self, problem: SolverProblem, frame, *,
                       full: bool, mesh=None):
        """In-process path: resident device buffers keyed by session
        epoch — a delta epoch scatters only the dirty rows to the
        device (donated, so no full padded copy materializes) instead
        of re-uploading the padded problem. With a ``mesh`` the lean
        resident state lives sharded over the ``wl`` axis; mesh and
        single-chip arms keep separate resident copies so arm flips
        cannot corrupt each other's donated buffers."""
        t0 = time.monotonic()
        try:
            return self._local_tensors_inner(problem, frame, full=full,
                                             mesh=mesh)
        finally:
            phases = getattr(self, "_drain_phases", None)
            if phases is not None:
                phases["device_put"] = (phases.get("device_put", 0.0)
                                        + time.monotonic() - t0)

    def _local_tensors_inner(self, problem: SolverProblem, frame, *,
                             full: bool, mesh=None):
        if frame is None:
            if full:
                from kueue_oss_tpu.solver.full_kernels import (
                    to_device_full,
                )

                t = to_device_full(problem)
            else:
                t = to_device(problem)
            if mesh is not None:
                # same placement policy as the resident path; routing
                # already cleared the live-row floor for this drain
                if full:
                    from kueue_oss_tpu.solver.sharded import (
                        maybe_place_full,
                    )

                    t, _placed = maybe_place_full(t, problem, mesh)
                else:
                    from kueue_oss_tpu.solver.sharded import (
                        maybe_place_lean,
                    )

                    t, _placed = maybe_place_lean(t, problem, mesh)
            return t
        kind = "full" if full else "lean"
        if mesh is not None:
            kind = kind + "-mesh"
        dev = self._device_states.get(kind)
        if dev is None:
            dev = self._device_states[kind] = DeviceResidentProblem(
                mesh=mesh)
        return dev.update(problem, frame, full)

    def _dispatch_remote(self, problem: SolverProblem, expect: int,
                         frame, session_key: str, verify: bool,
                         **solve_kw):
        """Pipelined drain dispatch: the remote solve round-trip runs on
        a worker thread while this thread computes the apply prework
        (snapshot for the verify/TAS paths, workload-ref prefetch), so
        the wire RTT overlaps host work instead of adding to it."""
        kw = dict(solve_kw)
        if frame is not None and getattr(self.remote,
                                         "supports_sessions", False):
            kw["frame"] = frame
            kw["session_key"] = session_key
        pool = self._solve_executor()
        fut = pool.submit(self._remote_solve, problem, expect, **kw)
        try:
            self._prework = self._build_prework(
                problem, verify, full=bool(solve_kw.get("full")))
        except Exception:
            self._prework = None  # prework is an optimization only
        return fut.result()

    def _solve_executor(self):
        if self._solve_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._solve_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="solver-dispatch")
        return self._solve_pool

    def _build_prework(self, problem: SolverProblem, verify: bool,
                       full: bool) -> dict:
        """Plan-independent apply preparation, safe to compute before
        the plan arrives. The full path cannot pre-build the oracle
        snapshot (its evictions change usage before the verify), so it
        only prefetches workload refs; the lean path pre-builds the
        snapshot its verify/TAS placement would otherwise build after
        the response."""
        pre: dict = {}
        if not full and (verify or self._drain_tas_ready):
            from kueue_oss_tpu.core.snapshot import build_snapshot

            pre["snapshot"] = build_snapshot(self.store)
        pre["wl_of"] = {k: self.store.workloads.get(k)
                        for k in problem.wl_keys if k}
        return pre

    def _take_prework(self) -> dict:
        pre, self._prework = (self._prework or {}), None
        return pre

    # -- backend resilience ------------------------------------------------

    def _remote_solve(self, problem: SolverProblem, expect: int, **kw):
        """One remote solve with breaker accounting.

        Any transport/backend fault (including a malformed result tuple)
        counts against the circuit breaker and surfaces as
        SolverUnavailable so the scheduler degrades to the host cycle.
        Success is NOT recorded here — only a plan that also passes the
        sanity guard counts as a healthy backend response.
        """
        # duck-typed trace propagation: a SolverClient ships the cycle id
        # over the wire so the sidecar's solve span comes back tagged;
        # arbitrary remote stubs without the attribute still work
        if hasattr(self.remote, "trace_cycle"):
            self.remote.trace_cycle = self._drain_cycle
        try:
            out = tuple(self.remote.solve(problem, **kw))
        except SolverUnavailable as e:
            self.health.record_failure()
            metrics.solver_fallback_total.inc("backend_error")
            self._record_backend_fallback(str(e))
            raise
        except (OSError, TimeoutError) as e:
            # custom remote stubs may surface raw socket errors
            self.health.record_failure()
            metrics.solver_fallback_total.inc("backend_error")
            self._record_backend_fallback(repr(e))
            raise SolverUnavailable(f"solver backend fault: {e!r}") from e
        if len(out) != expect:
            self.health.record_failure()
            metrics.solver_fallback_total.inc("backend_error")
            self._record_backend_fallback(
                f"backend returned {len(out)} arrays, expected {expect}")
            raise SolverUnavailable(
                f"solver backend returned {len(out)} arrays, "
                f"expected {expect}")
        self._import_sidecar_spans()
        return out

    def _record_backend_fallback(self, reason: str) -> None:
        obs.recorder.record(
            obs.SOLVER_FALLBACK, obs.CYCLE_SCOPE, cycle=self._drain_cycle,
            path=obs.SOLVER, reason=reason, reason_slug="backend_error")

    def _import_sidecar_spans(self) -> None:
        """Merge the sidecar's solve spans (returned in the response
        header) into the host tracer. The two processes have unrelated
        perf_counter origins, so spans are END-ALIGNED at the moment the
        response arrived — the duration and the shared cycle id are the
        signal; the sub-millisecond start skew is not."""
        tracer = self._tracer()
        spans = getattr(self.remote, "last_spans", None)
        if tracer is None or not spans:
            return
        now_us = int(tracer.clock() * 1e6)
        tenant = str(getattr(self.remote, "tenant", "") or "")
        for sp in spans:
            # span import is best-effort diagnostics: a version-skewed
            # or garbled spans entry must not abort the drain (the plan
            # itself is separately sanity-guarded)
            try:
                dur_us = int(sp.get("dur_us", 0))
                # a span that ended BEFORE the response (the farm's
                # grant-wait precedes the solve) declares the gap so
                # the merged timeline keeps wait -> solve ordering
                skew_us = int(sp.get("end_skew_us", 0))
                args = {str(k): v
                        for k, v in dict(sp.get("args") or {}).items()
                        if k not in ("name", "ts_us", "dur_us", "tid",
                                     "source")}
                args.setdefault("cycle", self._drain_cycle)
                # each remote source gets its own stable synthetic
                # track (tagged with the tenant) instead of the old
                # shared tid=0 pile-up
                src = str(dict(sp.get("args") or {}).get("source", "")
                          or f"sidecar:{tenant or 'solver'}")
                tracer.add_span(str(sp.get("name", "sidecar_solve")),
                                now_us - skew_us - dur_us, dur_us,
                                source=src, **args)
                if tenant:
                    tracer.track(src, tenant=tenant)
            except Exception:
                continue

    def _check_plan(self, problem: SolverProblem, admitted, opt,
                    admit_round, parked, victim_reason=None, rounds=None,
                    full: bool = False) -> None:
        """Sanity-guard an imported plan BEFORE any store mutation.

        A divergent plan — wrong shapes/dtypes, out-of-bounds flavor
        options, admissions/parkings of null or padding rows — is a
        backend fault: the whole plan is rejected (store untouched, the
        breaker incremented when remote) rather than committed as
        corrupt state. Committed usage is always recomputed host-side
        from the store's own request vectors, so quota arithmetic can
        never be driven by imported tensors; this guard closes the
        remaining index/flag surface.
        """
        fault = self._plan_fault(problem, admitted, opt, admit_round,
                                 parked, victim_reason, rounds, full)
        if fault is None:
            if self.remote is not None:
                self.health.record_success()
            return
        metrics.solver_plan_rejected_total.inc()
        if self.remote is not None:
            self.health.record_failure()
            metrics.solver_fallback_total.inc("plan_rejected")
        obs.recorder.record(
            obs.SOLVER_FALLBACK, obs.CYCLE_SCOPE, cycle=self._drain_cycle,
            path=obs.SOLVER,
            reason=f"divergent solver plan rejected: {fault}",
            reason_slug="plan_rejected")
        raise SolverUnavailable(f"divergent solver plan rejected: {fault}")

    @staticmethod
    def _plan_fault(problem: SolverProblem, admitted, opt, admit_round,
                    parked, victim_reason, rounds,
                    full: bool) -> Optional[str]:
        """Reason the plan is unusable, or None if it checks out."""
        W1 = problem.wl_cqid.shape[0]
        W = W1 - 1
        C = problem.n_cqs
        for name, arr in (("admitted", admitted), ("parked", parked),
                          ("admit_round", admit_round)):
            if arr.ndim != 1 or arr.shape[0] != W1:
                return f"{name} shape {arr.shape} != ({W1},)"
        if victim_reason is not None:
            if victim_reason.ndim != 1 or victim_reason.shape[0] != W1:
                return (f"victim_reason shape {victim_reason.shape} "
                        f"!= ({W1},)")
            # the eviction loop calls int(victim_reason[w]) BEFORE other
            # guards could fire — a non-integral dtype must fail here,
            # not mid-apply after evictions committed
            if not (victim_reason.dtype == np.bool_
                    or np.issubdtype(victim_reason.dtype, np.integer)):
                return (f"victim_reason dtype {victim_reason.dtype} "
                        "is not integral")
        want_opt_ndim = 2 if full else 1
        if opt.ndim != want_opt_ndim or opt.shape[0] != W1:
            return f"opt shape {opt.shape} incompatible with ({W1}, ...)"
        for name, arr in (("opt", opt), ("admit_round", admit_round)):
            if not np.issubdtype(arr.dtype, np.integer):
                return f"{name} dtype {arr.dtype} is not integral"
        for name, arr in (("admitted", admitted), ("parked", parked)):
            if not (arr.dtype == np.bool_
                    or np.issubdtype(arr.dtype, np.integer)):
                return f"{name} dtype {arr.dtype} is not a flag"
        if rounds is not None:
            r = np.asarray(rounds)
            if r.size != 1 or not (
                    r.dtype == np.bool_
                    or np.issubdtype(r.dtype, np.integer)):
                return f"rounds is not an integer scalar ({r.dtype}, " \
                       f"size {r.size})"
        cq = problem.wl_cqid[:W]
        adm = admitted[:W].astype(bool)
        prk = parked[:W].astype(bool)
        if bool((cq[adm] >= C).any()):
            return "plan admits a null/padding row"
        if bool((cq[prk] >= C).any()):
            return "plan parks a null/padding row"
        if not full and bool((adm & prk).any()):
            return "row both admitted and parked"
        rnd = admit_round[:W]
        floor = -1 if full else 0
        if bool((rnd[adm] < floor).any()):
            return f"admitted row with admit_round below {floor}"
        # flavor-option decode bounds, only for rows the apply path
        # actually decodes (full: newly admitted rows, admit_round >= 0)
        n_opt = np.array(
            [len(problem.cq_option_flavors[name])
             for name in problem.cq_names], dtype=np.int64)
        decode = adm & (rnd >= 0) if full else adm
        if not decode.any():
            return None
        cq_d = cq[decode]
        if full:
            ng = problem.cq_ngroups
            if ng is None:
                ng = np.ones(C, dtype=np.int64)
            need_g = int(ng[cq_d].max())
            if opt.shape[1] < need_g:
                return (f"opt group axis {opt.shape[1]} narrower than "
                        f"{need_g} resource groups")
            rows = opt[:W][decode]
            used = np.arange(opt.shape[1])[None, :] < ng[cq_d][:, None]
            bad = used & ((rows < 0) | (rows >= n_opt[cq_d][:, None]))
            if bool(bad.any()):
                return "flavor option index out of range"
        else:
            o = opt[:W][decode]
            if bool(((o < 0) | (o >= n_opt[cq_d])).any()):
                return "flavor option index out of range"
        return None

    # -- plan application --------------------------------------------------

    def _apply_plan(self, problem: SolverProblem, admitted: np.ndarray,
                    opt: np.ndarray, admit_round: np.ndarray,
                    parked: np.ndarray, now: float,
                    result: DrainResult, verify: bool = False) -> None:
        # Collect the committed plan entries in admission order first, so
        # the optional oracle verification can run as one batched native
        # call (SURVEY.md §7 step 4 verify-then-assume pattern).
        pre = self._take_prework()
        wl_of = pre.get("wl_of")
        adm_ws = np.nonzero(admitted[:-1])[0]
        order = adm_ws[np.argsort(admit_round[adm_ws], kind="stable")]
        candidates = []
        declared_of: dict[str, set] = {}
        for w in order:
            key = problem.wl_keys[w]
            wl = (wl_of.get(key) if wl_of is not None
                  else self.store.workloads.get(key))
            if wl is None or wl.is_quota_reserved or not wl.active:
                continue
            cq_name = problem.cq_names[problem.wl_cqid[w]]
            flavor = problem.cq_option_flavors[cq_name][opt[w]]
            info = WorkloadInfo(wl, cluster_queue=cq_name)
            declared = declared_of.get(cq_name)
            if declared is None:
                declared = {
                    r for rg in
                    self.store.cluster_queues[cq_name].resource_groups
                    for r in rg.covered_resources}
                declared_of[cq_name] = declared
            plan_usage: dict[tuple[str, str], int] = {}
            for psr in info.total_requests:
                for r, q in psr.requests.items():
                    if r not in declared:
                        continue  # QuotaCheckStrategy=IgnoreUndeclared
                    fr = (flavor, r)
                    plan_usage[fr] = plan_usage.get(fr, 0) + q
            candidates.append((wl, cq_name, flavor, info, plan_usage))

        candidates, topo_of = self._compute_tas_assignments(
            candidates, snapshot=pre.get("snapshot"))

        if verify and candidates:
            # Verify-then-fallback (scheduler.go:427 fits re-check): plan
            # entries the oracle rejects are not committed — those
            # workloads stay queued for the host scheduler path. The
            # sequential fits/add_usage walk runs in native code when the
            # toolchain is available (kueue_oss_tpu/native/oracle.cpp).
            # The snapshot comes from the pipelined-dispatch prework
            # when it overlapped the solve (no mutations since export).
            from kueue_oss_tpu.core.snapshot import build_snapshot
            from kueue_oss_tpu.native import BatchOracle

            snapshot = pre.get("snapshot") or build_snapshot(self.store)
            oracle = BatchOracle(snapshot.forest.cqs)
            ok = oracle.verify_and_apply(
                [(cq_name, usage)
                 for _, cq_name, _, _, usage in candidates])
        else:
            ok = np.ones(len(candidates), dtype=np.uint8)

        for passed, (wl, cq_name, flavor, info, _) in zip(ok, candidates):
            if not passed:
                metrics.solver_plan_fallbacks_total.inc()
                obs.recorder.record(
                    obs.SOLVER_FALLBACK, wl.key, cycle=self._drain_cycle,
                    cluster_queue=cq_name, path=obs.SOLVER,
                    reason="host oracle re-check rejected the plan entry;"
                           " workload stays queued for the host cycle",
                    reason_slug="oracle_rejected")
                continue
            flavor_of = {r: flavor for psr in info.total_requests
                         for r in psr.requests}
            self._commit_admission(wl, cq_name, flavor_of, info, now,
                                   result, topology=topo_of.get(wl.key))
        # Mirror the solver's inadmissible-parking decisions host-side;
        # StrictFIFO blocked heads (not parked) stay in their heaps.
        for w in np.nonzero(parked[:problem.n_workloads])[0]:
            cq_name = problem.cq_names[problem.wl_cqid[w]]
            self.queues.queues[cq_name].park(problem.wl_keys[w])
            self._record_parked(problem.wl_keys[w], cq_name)

    def _record_parked(self, key: str, cq_name: str) -> None:
        obs.recorder.record(
            obs.SKIPPED, key, cycle=self._drain_cycle,
            cluster_queue=cq_name, path=obs.SOLVER,
            reason="parked inadmissible by the solver plan: no flavor "
                   "option fits at current capacity",
            reason_slug="solver_parked")

    # -- full (preemption-capable) drain -----------------------------------

    def _size_caps(self, problem: SolverProblem) -> tuple[int, int]:
        """Size the full kernel's static caps from the problem.

        h_max bounds victim searches per round: capping it only delays
        later preempt-mode heads a round, so any cap is safe — but the
        host cycle has NO such deferral (every head searches every
        cycle, scheduler.go:286-467), so a cap below the CQ count both
        diverges from host round semantics and throttles NoCandidates
        resolution to h_max classes per round (the round-5 churn
        profile: 49 park-only rounds at h=64 vs 5 at h=1024 on the
        50k x 1k shape). Production drains therefore size lanes to the
        CQ count up to `h_max_cap`; the stepped serve-loop path can run
        a narrow-lane variant for per-round latency. p_max
        bounds candidates per search and MUST cover the largest possible
        candidate set. Candidates are always CONCURRENTLY-ADMITTED
        workloads with nonzero usage in the preemptor's cohort tree
        (preemption.go:311, candidate_generator.go:34-160), so besides
        the cohort population, p_max is bounded by tree capacity. The
        sound capacity measure is the tree's total quota, NOT the root's
        subtree row: usage bubbling subtracts each child's local quota
        on the way up (resource_node.go:210-217), so with lending
        limits admitted usage can sit entirely below the CQs' local
        quotas and never surface at the root. Inductively
        sum(cq usage) <= sum(local quotas in the tree) + usage[root]
        and usage[root] <= subtree[root], and every admitted candidate
        uses >= the smallest positive request on some FR. Rounded up to
        powers of two to reuse compiled kernels.
        """
        C = problem.n_cqs
        if self.h_work_budget is None:
            import jax

            self.h_work_budget = (8192 if jax.default_backend() != "cpu"
                                  else 512)
        K = problem.wl_req.shape[1] if problem.wl_req.ndim == 3 else 1
        g = max(1, int(problem.cq_ngroups.max()) if C else 1)
        # round the budgeted lane count DOWN to a power of two so the
        # budget is actually enforced; the 64-lane floor overrides it
        # for very wide K x g shapes (fewer lanes than that defers too
        # many heads per round to ever converge quickly)
        lane_cap = pow2(max(
            1, self.h_work_budget // max(K * g, 1)) + 1) // 2
        lane_cap = max(64, lane_cap)
        h_max = max(1, pow2(min(C, self.h_max_cap, lane_cap)))
        root_of_cq = problem.cq_root
        wl_root = root_of_cq[np.minimum(problem.wl_cqid[:-1], C - 1)]
        counts = np.bincount(wl_root, minlength=problem.n_nodes + 1)
        pop = int(counts.max()) if counts.size else 1
        # per-FR smallest positive usage a candidate can hold: flavor
        # options plus actual admitted usage (partial admission can sit
        # below every full-count option)
        req = problem.wl_req[:-1].reshape(-1, problem.wl_req.shape[-1])
        if problem.ad_usage is not None:
            req = np.concatenate([req, problem.ad_usage[:-1]], axis=0)
        pos = req > 0
        if pos.any():
            big = np.iinfo(req.dtype).max
            min_req = np.where(pos.any(axis=0),
                               np.where(pos, req, big).min(axis=0), 0)
            # per-node root: last valid entry on the ancestor path
            path = problem.path                       # [N+1, D]
            null = path.shape[0] - 1
            valid = path != null
            last = np.maximum(valid.shape[1] - 1 - np.argmax(
                valid[:, ::-1], axis=1), 0)
            root_of_node = path[np.arange(path.shape[0]), last]
            root_of_node = np.where(valid.any(axis=1), root_of_node, null)
            tree_quota = np.zeros_like(problem.local_quota)
            np.add.at(tree_quota, root_of_node[:-1],
                      problem.local_quota[:-1])
            # workloads admitted BEFORE this drain may predate a quota
            # reduction (usage above today's tree quota is kept), so
            # they are counted directly; the quota bound covers only
            # what the drain itself can newly admit
            if problem.ad_usage is not None:
                adm0 = problem.ad_usage[:-1].any(axis=1)
                adm_counts = np.bincount(
                    wl_root[adm0], minlength=problem.n_nodes + 1)
            else:
                adm_counts = np.zeros(problem.n_nodes + 1, dtype=np.int64)
            cap = 0
            for rn in np.unique(root_of_cq):
                quota = tree_quota[rn] + problem.subtree[rn]
                per_fr = quota // np.maximum(min_req, 1)
                cap = max(cap, int(per_fr[min_req > 0].sum())
                          + int(adm_counts[rn]))
            p_max = min(pop, max(8, cap))
        else:
            p_max = pop
        return h_max, pow2(max(8, p_max))

    def _drain_full(
            self, now: float, verify: bool = False,
            pending: Optional[dict[str, list[WorkloadInfo]]] = None,
    ) -> DrainResult:
        """Drain a preemption-enabled store through solve_backlog_full.

        Reference cycle contract: scheduler.go:286-467 — the kernel
        replays nominate → search → admit/preempt rounds on-device; this
        applies the net plan: evictions first (releasing quota exactly
        like Scheduler._issue_preemptions → evict_workload), then
        admissions in (round, entry-order), then parking decisions.
        """
        result = DrainResult()
        if pending is None:
            pending = self.pending_backlog()
        parked_map: dict[str, list[WorkloadInfo]] = {}
        for name, q in self.queues.queues.items():
            if not q.inadmissible or (
                    self._is_tas_cq(name)
                    and name not in self._drain_tas_ready):
                continue
            # stale entries export as PENDING (pending_backlog); only
            # still-parked (unflushed) entries export as parked0
            infos = [i for k, i in q.inadmissible.items()
                     if k not in q._stale
                     and all(ps.topology_request is None
                             for ps in i.obj.podsets)]
            if infos:
                parked_map[name] = infos
        self._drain_phases = {}
        te = time.monotonic()
        problem = export_problem(self.store, pending,
                                 include_admitted=True, parked=parked_map,
                                 afs=self.queues.afs, now=now,
                                 cache=self.export_cache)
        self._note_export_phase(time.monotonic() - te)
        if problem.n_workloads == 0:
            return result
        hint = getattr(problem, "_columnar_hint", None)
        g_max = int(problem.cq_ngroups.max())
        h_max, p_max = self._size_caps(problem)
        n_live = problem.n_workloads
        self._pad_hwm = max(self._pad_hwm,
                            pow2(max(problem.n_workloads, self.pad_to)))
        problem = pad_workloads(problem, self._pad_target())
        problem, frame = self._session_encode("full", problem, hint=hint)
        dev0 = self._device_totals()

        t0 = time.monotonic()
        if self.remote is not None:
            (admitted, opt, admit_round, parked, rounds, _usage,
             _wl_usage, victim_reason) = self._dispatch_remote(
                problem, 8, frame, "full", verify, full=True,
                g_max=g_max, h_max=h_max, p_max=p_max,
                fs_enabled=self.enable_fair_sharing)
        else:
            (admitted, opt, admit_round, parked, rounds, _usage,
             _wl_usage, victim_reason) = self._local_solve(
                problem, frame, full=True, n_live=n_live, g_max=g_max,
                h_max=h_max, p_max=p_max,
                fs_enabled=self.enable_fair_sharing)
        admitted = np.asarray(admitted)
        opt = np.asarray(opt)
        admit_round = np.asarray(admit_round)
        parked = np.asarray(parked)
        victim_reason = np.asarray(victim_reason)
        if self.remote is not None:
            # imported plans only (see the lean drain's note)
            self._check_plan(problem, admitted, opt, admit_round,
                             parked, victim_reason=victim_reason,
                             rounds=rounds, full=True)
        result.rounds = int(rounds)
        result.solver_time_s = time.monotonic() - t0
        metrics.solver_cycle_duration_seconds.observe(
            "solve", value=result.solver_time_s)

        t1 = time.monotonic()
        self._apply_full_plan(problem, admitted, opt, admit_round, parked,
                              victim_reason, now, result, verify=verify)
        result.apply_time_s = time.monotonic() - t1
        metrics.solver_cycle_duration_seconds.observe(
            "apply", value=result.apply_time_s)
        W = problem.n_workloads
        self._ledger_record(
            result, frame, "full", dev0,
            parked_n=int((np.asarray(parked[:W]).astype(bool)
                          & ~np.asarray(admitted[:W]).astype(bool)).sum()))
        return result

    def _evictor(self):
        """Host scheduler used purely for its eviction state machine."""
        if self.scheduler is None:
            from kueue_oss_tpu.scheduler.scheduler import Scheduler

            self.scheduler = Scheduler(self.store, self.queues)
        return self.scheduler

    def _apply_full_plan(self, problem: SolverProblem, admitted: np.ndarray,
                         opt: np.ndarray, admit_round: np.ndarray,
                         parked: np.ndarray, victim_reason: np.ndarray,
                         now: float, result: DrainResult,
                         verify: bool = False) -> None:
        from kueue_oss_tpu.scheduler.preemption import (
            _VARIANT_REASON,
            IN_CLUSTER_QUEUE,
            IN_COHORT_FAIR_SHARING,
        )
        from kueue_oss_tpu.solver.fair_kernels import V_FAIR_SHARING

        reason_of = dict(_VARIANT_REASON)
        reason_of[V_FAIR_SHARING] = IN_COHORT_FAIR_SHARING

        pre = self._take_prework()
        wl_of = pre.get("wl_of")

        def lookup(key):
            return (wl_of.get(key) if wl_of is not None
                    else self.store.workloads.get(key))

        W = problem.n_workloads
        wl_admitted0 = problem.wl_admitted0

        # 1) evictions: initially-admitted workloads that lost their
        #    admission, or were evicted mid-drain and re-admitted with a
        #    (possibly different) flavor (admit_round >= 0).
        evictor = self._evictor()
        evict_ws = np.nonzero(
            wl_admitted0[:W]
            & ~(admitted[:W] & (admit_round[:W] < 0)))[0]
        for w in evict_ws:
            key = problem.wl_keys[w]
            wl = lookup(key)
            if wl is None or not wl.is_quota_reserved:
                continue
            reason = reason_of.get(int(victim_reason[w]),
                                   IN_CLUSTER_QUEUE)
            evictor.evict_workload(
                key, reason="Preempted",
                message="Preempted by the solver drain plan",
                now=now, preemption_reason=reason,
                decision_path=obs.SOLVER,
                decision_cycle=self._drain_cycle)
            if not admitted[w]:
                result.evicted += 1
                result.evicted_keys.append(key)

        # 2) admissions in (round, entry-order); per-group flavor decode.
        adm_ws = np.nonzero(admitted[:W] & (admit_round[:W] >= 0))[0]
        order = adm_ws[np.argsort(admit_round[adm_ws], kind="stable")]
        candidates = []
        for w in order:
            key = problem.wl_keys[w]
            wl = lookup(key)
            if wl is None or wl.is_quota_reserved or not wl.active:
                continue
            cq_name = problem.cq_names[problem.wl_cqid[w]]
            rg_of = problem.cq_resource_group[cq_name]
            opts = problem.cq_option_flavors[cq_name]
            info = WorkloadInfo(wl, cluster_queue=cq_name)
            flavor_of = {
                r: opts[opt[w, g]] for r, g in rg_of.items()}
            plan_usage: dict[tuple[str, str], int] = {}
            for psr in info.total_requests:
                for r, q in psr.requests.items():
                    if r not in flavor_of:
                        continue  # QuotaCheckStrategy=IgnoreUndeclared
                    fr = (flavor_of[r], r)
                    plan_usage[fr] = plan_usage.get(fr, 0) + q
            candidates.append((wl, cq_name, flavor_of, info, plan_usage))

        # device-TAS placement in admission order; failed placements
        # drop out of the plan (host mop-up) BEFORE the oracle verify so
        # the sequential usage walk matches what actually commits
        candidates, topo_of = self._compute_tas_assignments(candidates)

        if verify and candidates:
            from kueue_oss_tpu.core.snapshot import build_snapshot
            from kueue_oss_tpu.native import BatchOracle

            oracle = BatchOracle(build_snapshot(self.store).forest.cqs)
            ok = oracle.verify_and_apply(
                [(cq_name, usage)
                 for _, cq_name, _, _, usage in candidates])
        else:
            ok = np.ones(len(candidates), dtype=np.uint8)

        for passed, (wl, cq_name, flavor_of, info, _) in zip(ok, candidates):
            if not passed:
                metrics.solver_plan_fallbacks_total.inc()
                obs.recorder.record(
                    obs.SOLVER_FALLBACK, wl.key, cycle=self._drain_cycle,
                    cluster_queue=cq_name, path=obs.SOLVER,
                    reason="host oracle re-check rejected the plan entry;"
                           " workload stays queued for the host cycle",
                    reason_slug="oracle_rejected")
                continue
            self._commit_admission(wl, cq_name, flavor_of, info, now,
                                   result, topology=topo_of.get(wl.key))

        # 3) parking decisions (inadmissible backoff parity).
        for w in np.nonzero(parked[:W] & ~admitted[:W])[0]:
            cq_name = problem.cq_names[problem.wl_cqid[w]]
            self.queues.queues[cq_name].park(problem.wl_keys[w])
            self._record_parked(problem.wl_keys[w], cq_name)

    def _commit_admission(self, wl, cq_name: str,
                          flavor_of: dict[str, str], info: WorkloadInfo,
                          now: float, result: DrainResult,
                          topology: Optional[TopologyAssignment] = None,
                          ) -> None:
        key = wl.key
        persistence = getattr(self.store, "persistence", None)
        if persistence is not None:
            # plan-entry intent before the store mutation, fenced like
            # the host path's (scheduler._admit; docs/DURABILITY.md) —
            # a drain killed mid-apply redoes the uncommitted suffix
            # from the recovered backlog
            persistence.intent("admit", key, rv=wl.resource_version,
                               cycle=self._drain_cycle,
                               cluster_queue=cq_name,
                               detail={"path": "solver"})
        admission = Admission(
            cluster_queue=cq_name,
            podset_assignments=[
                PodSetAssignment(
                    name=psr.name,
                    # undeclared resources carry no flavor under
                    # QuotaCheckStrategy=IgnoreUndeclared
                    flavors={r: flavor_of[r] for r in psr.requests
                             if r in flavor_of},
                    resource_usage=dict(psr.requests),
                    count=psr.count,
                    # device-TAS drains carry the placement computed by
                    # the sequential on-device placer (single podset)
                    topology_assignment=topology,
                )
                for psr in info.total_requests
            ],
        )
        wl.status.admission = admission
        wl.set_condition(WorkloadConditionType.QUOTA_RESERVED, True,
                         reason="QuotaReserved", now=now)
        if wl.is_evicted:
            wl.set_condition(WorkloadConditionType.EVICTED, False,
                             reason="QuotaReserved", now=now)
        if wl.status.requeue_state is not None:
            wl.status.requeue_state.requeue_at = None
        cq_spec = self.store.cluster_queues[cq_name]
        # flavors ACTUALLY assigned (host-path parity: scheduler._admit
        # uses admission.assigned_flavors() too) — flavor_of covers every
        # resource the CQ defines, not just the ones this workload uses
        effective_checks = cq_spec.checks_for_flavors(
            admission.assigned_flavors())
        if effective_checks:
            from kueue_oss_tpu.api.types import AdmissionCheckState
            for ac_name in effective_checks:
                wl.status.admission_checks.setdefault(
                    ac_name, AdmissionCheckState(name=ac_name))
        else:
            wl.set_condition(WorkloadConditionType.ADMITTED, True,
                             reason="Admitted", now=now)
        self.store.update_workload(wl)
        persist_hooks.crash_if("mid_drain")
        self.queues.queues[cq_name].delete(key)
        if (self.queues.afs is not None
                and cq_spec.admission_scope is not None
                and cq_spec.admission_scope.admission_mode
                == "UsageBasedAdmissionFairSharing"):
            # keep the host AfsManager in sync with the plan's entry
            # penalties (scheduler._admit record_admission hook)
            by_resource: dict[str, int] = {}
            for psr in info.total_requests:
                for r, q in psr.requests.items():
                    by_resource[r] = by_resource.get(r, 0) + q
            self.queues.afs.record_admission(
                f"{wl.namespace}/{wl.queue_name}", by_resource, now)
        wait_s = max(now - wl.creation_time, 0.0)
        exemplar = {"cycle": self._drain_cycle, "workload": key}
        metrics.quota_reserved_workload(cq_name, wait_s,
                                        lq=wl.queue_name,
                                        namespace=wl.namespace,
                                        exemplar=exemplar)
        if wl.is_admitted:
            metrics.admitted_workload(cq_name, wait_s,
                                      lq=wl.queue_name,
                                      namespace=wl.namespace,
                                      exemplar=exemplar)
        # queue-wait SLI feed (obs/health.py), host-path parity: the
        # solver drain's admissions count against the same objectives;
        # the priority scope keys by WorkloadPriorityClass name
        pclass = obs.priority_class_of(self.store, wl)
        obs.slo_engine.observe_admission(
            cq_name, wait_s, priority=wl.priority,
            priority_class=pclass, now=now,
            cycle=self._drain_cycle, workload=key)
        obs.recorder.record(
            obs.SOLVER_ADMITTED, key, cycle=self._drain_cycle,
            cluster_queue=cq_name, path=obs.SOLVER,
            reason=f"Admitted by the solver drain plan into "
                   f"ClusterQueue {cq_name}",
            detail={
                "flavors": dict(flavor_of),
                "placed_with_topology": topology is not None,
                "admitted": wl.is_admitted,
                "waitSeconds": round(wait_s, 3),
                "priority": wl.priority,
                "priorityClass": pclass,
                # which solver arm produced this plan (relax / mesh /
                # single / remote) — joins the ledger row's solver_arm
                "solver_arm": ("remote" if self.remote is not None
                               else (self.last_drain_arm or "single")),
            })
        result.admitted += 1
        result.admitted_keys.append(key)
