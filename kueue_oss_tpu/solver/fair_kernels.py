"""Fair-sharing device kernels: DRS, tournaments, fair preemption search.

Mirrors the host fair-sharing stack exactly:
- DRS math (core/quota.py dominant_resource_share; reference
  pkg/cache/scheduler/fair_sharing.go:140-173): per node,
  max over resources of (borrowed-above-subtree-quota * 1000 / lendable
  capacity of the parent) / fair weight, with zero-weight borrowers
  sorting above everything;
- the target-CQ tournament (scheduler/preemption.py _CQOrdering;
  reference fairsharing/ordering.go): descend from the root picking the
  highest-share child, pruning non-borrowing branches;
- the two preemption strategy rules S2-a LessThanOrEqualToFinalShare and
  S2-b LessThanInitialShare (preemption.py _run_first/second_fs_strategy;
  reference preemption.go:371-534) with almost-LCA share comparison
  (fairsharing/least_common_ancestor.go);
- the per-cohort entry tournament used for admission ordering
  (scheduler.py _FairSharingIterator; reference
  fair_sharing_iterator.go:44-130) lives in full_kernels.round_body via
  fair_entry_shares/fair_pick below.

DRS values are compared as (zero-weight-borrows, share) pairs in
float32 — the host compares exact floats; parity holds because shares
at test scales are well separated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kueue_oss_tpu.solver.kernels import (
    _add_usage_along_path,
    refresh_cohort_usage,
)
from kueue_oss_tpu.solver.tensors import (
    BIG,
    POLICY_ANY,
    POLICY_LOWER_OR_NEWER_EQUAL,
    POLICY_LOWER_PRIORITY,
    POLICY_NEVER,
)

#: synthetic candidate variant for fair-sharing victims (the classical
#: V_* codes live in full_kernels; the engine maps this to
#: IN_COHORT_FAIR_SHARING)
V_FAIR_SHARING = 5


def lendable_by_resource(t, pot):
    """calculate_lendable for every node's PARENT: [N+1, R].

    lendable[n, r] = sum over FR columns of resource r of
    potentialAvailable(parent(n)) — usage-independent, computed once.
    """
    lend_nodes = pot @ t.res_onehot.astype(pot.dtype)     # [N+1, R]
    out = lend_nodes[t.parent]                            # [N+1, R]
    return jnp.where(t.has_parent[:, None], out, 0)


def drs_all(t, usage, lendable_r):
    """DRS of every node: (zwb [N+1] bool, share [N+1] f32,
    borrowing [N+1] bool, unweighted [N+1] f32).

    Reference: fair_sharing.go dominantResourceShare — borrowed =
    max(0, usage - subtreeQuota) summed per resource; ratio =
    borrowed * 1000 / lendable(parent); share = ratio / weight;
    zero-weight borrowers take precedence over any weighted share.
    Nodes without a parent have zero DRS.
    """
    borrowed = jnp.maximum(0, usage - t.subtree)          # [N+1, F]
    borrowed_r = borrowed @ t.res_onehot                  # [N+1, R]
    borrowing = jnp.any(borrowed_r > 0, axis=1) & t.has_parent
    ratio = jnp.where(
        (lendable_r > 0) & (borrowed_r > 0),
        borrowed_r.astype(jnp.float32) * 1000.0
        / lendable_r.astype(jnp.float32), 0.0)
    unweighted = jnp.max(ratio, axis=1)
    unweighted = jnp.where(t.has_parent, unweighted, 0.0)
    w = t.node_fair_weight
    share = jnp.where(w > 0, unweighted / jnp.maximum(w, 1e-30), 0.0)
    zwb = (w == 0) & (unweighted > 0)
    return zwb, share, borrowing, unweighted


def drs_gt(a_zwb, a_share, a_unw, b_zwb, b_share, b_unw):
    """compare_drs(a, b) > 0 (higher share = preferred for preemption)."""
    both = a_zwb & b_zwb
    return jnp.where(
        both, a_unw > b_unw,
        jnp.where(a_zwb, True,
                  jnp.where(b_zwb, False, a_share > b_share)))


def drs_ge(a_zwb, a_share, a_unw, b_zwb, b_share, b_unw):
    both = a_zwb & b_zwb
    return jnp.where(
        both, a_unw >= b_unw,
        jnp.where(a_zwb, True,
                  jnp.where(b_zwb, False, a_share >= b_share)))


def drs_le(a_zwb, a_share, a_unw, b_zwb, b_share, b_unw):
    return ~drs_gt(a_zwb, a_share, a_unw, b_zwb, b_share, b_unw)


def drs_lt(a_zwb, a_share, a_unw, b_zwb, b_share, b_unw):
    return ~drs_ge(a_zwb, a_share, a_unw, b_zwb, b_share, b_unw)


def _almost_lca_node(t, cq_node, lca_node):
    """The node on cq_node's path just below lca_node (path position)."""
    path = t.path[cq_node]                                # [D]
    hit = path == lca_node
    d_idx = jnp.arange(path.shape[0], dtype=jnp.int32)
    lca_d = jnp.min(jnp.where(hit, d_idx, path.shape[0]))
    return path[jnp.maximum(lca_d - 1, 0)]


def _lca_of(t, my_path, other_cq_node):
    """First node on my_path that is an ancestor of other_cq_node."""
    null = t.parent.shape[0] - 1
    other_path = t.path[other_cq_node]                    # [D]
    D = my_path.shape[0]
    is_anc = jnp.any(other_path[:, None] == my_path[None, :], axis=0)
    is_anc = is_anc & (my_path != null)
    d_idx = jnp.arange(D, dtype=jnp.int32)
    lca_d = jnp.min(jnp.where(is_anc, d_idx, D))
    return my_path[jnp.minimum(lca_d, D - 1)], lca_d


def fair_search(t, lendable_r, usage0_round, wl_usage, admitted, evicted_f,
                ts, head_w, req, avail_cq, cands, p_max: int):
    """Fair-sharing victim search for ONE preemptor (vmap over lanes).

    Mirrors Preemptor._fair_preemptions: candidate collection
    (_find_fs_candidates), the DRS tournament over target CQs, strategy
    rules S2-a then S2-b, fill-back. ``cands`` is the preemptor root's
    row of build_candidate_table (round-start admitted workloads in the
    shared candidate order, W_null padded). Same return contract as
    classical_search: (success, cand_w [P], victims [P], reason [P] int8,
    any_same_cq, borrow_after).
    """
    from kueue_oss_tpu.solver.full_kernels import (
        V_HIERARCHICAL_RECLAIM,
        V_WITHIN_CQ,
        _height_along_path,
        _remove_usage_along_path,
        _workload_fits,
    )

    W1 = t.wl_cqid.shape[0]
    W_null = W1 - 1
    C = t.cq_node.shape[0]
    N1 = t.parent.shape[0]
    null_node = N1 - 1
    D = t.path.shape[1]
    cqid = t.wl_cqid[head_w]
    cqi = jnp.minimum(cqid, C - 1)
    cq_node = t.cq_node[cqi]
    my_path = t.path[cq_node]
    pot_lendable = lendable_r

    frs_mask = (req > 0) & (req > avail_cq)

    # ---- candidate collection (_find_fs_candidates) ----------------------
    present = cands != W_null
    cand_cqid = t.wl_cqid[cands]                          # [P]
    cand_node = t.cq_node[jnp.minimum(cand_cqid, C - 1)]
    is_adm = present & admitted[cands] & (cands != head_w)
    uses = jnp.any(wl_usage[cands] * frs_mask[None, :] > 0, axis=1)
    same_cq = cand_cqid == cqid
    prio_p = t.wl_prio[head_w]
    ts_p = ts[head_w]
    prio_c = t.wl_prio[cands]
    lower = prio_p > prio_c
    buf_p = jnp.where(ts_p >= t.ts_evict_base, BIG,
                      t.wl_ts_buf[head_w])
    newer_eq = (prio_p == prio_c) & (ts[cands] > buf_p)

    def sat(policy):
        return jnp.where(
            policy == POLICY_NEVER, False,
            jnp.where(policy == POLICY_LOWER_PRIORITY, lower,
                      jnp.where(policy == POLICY_LOWER_OR_NEWER_EQUAL,
                                lower | newer_eq, policy == POLICY_ANY)))

    own_legal = same_cq & sat(t.cq_within_policy[cqi])
    # other CQs: same cohort forest, candidate CQ borrowing on a needed fr
    other_path = t.path[cand_node]
    shares_tree = jnp.any(
        (other_path[:, :, None] == my_path[None, None, :])
        & (my_path[None, None, :] != null_node), axis=(1, 2))
    cq_borrowing = jnp.any(
        frs_mask[None, :]
        & (usage0_round[cand_node] > t.subtree[cand_node]), axis=1)
    has_par = t.has_parent[cq_node]
    other_legal = (~same_cq & has_par & shares_tree & cq_borrowing
                   & sat(t.cq_reclaim_policy[cqi]))
    legal = is_adm & uses & (own_legal | other_legal)

    # ---- candidate ordering (candidates_ordering) -------------------------
    # ``cands`` already carries the shared (priority, -admit_rank, uid)
    # suffix order; the full ordering is a stable bucket sort: legal
    # first, evicted first, other-CQ candidates before own-CQ ones.
    not_evicted = ~evicted_f[cands]
    bucket = jnp.where(
        legal,
        not_evicted.astype(jnp.int32) * 2 + same_cq.astype(jnp.int32), 4)
    p_idx = jnp.arange(p_max, dtype=jnp.int32)
    perm = jnp.argsort(bucket * p_max + p_idx).astype(jnp.int32)
    cand_ok = bucket[perm] < 4
    cand_w = jnp.where(cand_ok, cands[perm], W_null)
    cand_valid = cand_ok
    slot_cqid = jnp.where(cand_valid, t.wl_cqid[cand_w], C)

    # ---- state -------------------------------------------------------------
    # simulate the incoming usage on the preemptor's CQ for the whole
    # strategy phase (preemption.py: cq.simulate_usage_addition(ctx.usage))
    usage_sim = _add_usage_along_path(t, usage0_round, cq_node, req)

    # FairSharingPreemptWithinNominal (trace-time gate): a preemptor
    # whose CQ is not borrowing on any contested FR — with the incoming
    # usage simulated — preempts cross-CQ candidates UNCONDITIONALLY,
    # bypassing the strategy rules (preemption.go:377-412). Those
    # victims carry the InCohortReclamation reason.
    from kueue_oss_tpu import features as _features

    if _features.enabled("FairSharingPreemptWithinNominal"):
        within_nominal = ~jnp.any(
            frs_mask & (usage_sim[cq_node] > t.subtree[cq_node]))
    else:
        within_nominal = jnp.zeros((), dtype=bool)

    on_my_path = jnp.zeros((N1,), dtype=bool).at[my_path].set(
        my_path != null_node)
    root_node = my_path[jnp.maximum(
        jnp.max(jnp.where(my_path != null_node,
                          jnp.arange(D, dtype=jnp.int32), 0)), 0)]

    def fits_fs(u):
        """workloadFitsForFairSharing: fit check without the simulated
        incoming usage."""
        u2 = _remove_usage_along_path(t, u, cq_node, req)
        return _workload_fits(t, u2, cq_node, req, True)

    def head_slot(consumed, only_retry, retry):
        """Per-CQ first unconsumed candidate slot: [C] slot index or p_max."""
        p_idx = jnp.arange(p_max, dtype=jnp.int32)
        ok = cand_valid & ~consumed & (~only_retry | retry)
        eff = jnp.where(ok, p_idx, p_max)
        return jax.ops.segment_min(
            eff, jnp.minimum(slot_cqid, C), num_segments=C + 1)[:C]

    def tournament(u, pruned_cq, pruned_cohort, heads):
        """One descent (_CQOrdering._next_target): returns (target_cq int
        [C or C=none], new pruned sets). Descends at most D levels."""
        zwb, share, borrowing, unw = drs_all(t, u, pot_lendable)
        cq_has_head = heads < p_max

        # prune CQs: (not borrowing and not preemptor's CQ) or no head
        cq_nodes = t.cq_node
        prune_now = ((~borrowing[cq_nodes] & (jnp.arange(C) != cqi))
                     | ~cq_has_head)
        pruned_cq = pruned_cq | prune_now
        # prune cohorts: not borrowing and not on preemptor's path
        is_cohort = ~t.is_cq & (jnp.arange(N1) != null_node)
        pruned_cohort = pruned_cohort | (
            is_cohort & ~borrowing & ~on_my_path)

        current = root_node
        target = C  # none
        done = jnp.zeros((), dtype=bool)
        for _ in range(D):
            # best CQ child of `current`
            cq_parent = t.parent[cq_nodes]
            elig_cq = (cq_parent == current) & ~pruned_cq & ~done
            cq_key_zwb = zwb[cq_nodes]
            cq_key_share = jnp.where(elig_cq, share[cq_nodes], -1.0)
            cq_key_unw = jnp.where(elig_cq, unw[cq_nodes], -1.0)
            # lexicographic argmax (zwb, share/unw, lower head slot)
            best_cq = C
            best_zwb = jnp.zeros((), dtype=bool)
            best_share = jnp.asarray(-1.0, dtype=jnp.float32)
            best_unw = jnp.asarray(-1.0, dtype=jnp.float32)
            # two-pass: first find max key, then tie-break by head slot
            any_elig = jnp.any(elig_cq)
            m_zwb = jnp.any(cq_key_zwb & elig_cq)
            m_share = jnp.max(jnp.where(
                elig_cq & (cq_key_zwb == m_zwb), cq_key_share, -1.0))
            m_unw = jnp.max(jnp.where(
                elig_cq & (cq_key_zwb == m_zwb), cq_key_unw, -1.0))
            is_top = elig_cq & (cq_key_zwb == m_zwb) & jnp.where(
                m_zwb, cq_key_unw == m_unw, cq_key_share == m_share)
            head_of = heads
            best_cq = jnp.argmin(jnp.where(is_top, head_of, p_max + 1))
            best_cq = jnp.where(any_elig, best_cq, C).astype(jnp.int32)
            best_zwb = m_zwb
            best_share = m_share
            best_unw = m_unw

            # best cohort child
            node_idx = jnp.arange(N1)
            elig_co = ((t.parent == current) & is_cohort
                       & ~pruned_cohort & ~done)
            co_share = jnp.where(elig_co, share, -1.0)
            co_unw = jnp.where(elig_co, unw, -1.0)
            any_co = jnp.any(elig_co)
            c_zwb = jnp.any(zwb & elig_co)
            c_share = jnp.max(jnp.where(
                elig_co & (zwb == c_zwb), co_share, -1.0))
            c_unw = jnp.max(jnp.where(elig_co & (zwb == c_zwb), co_unw,
                                      -1.0))
            is_topc = elig_co & (zwb == c_zwb) & jnp.where(
                c_zwb, co_unw == c_unw, co_share == c_share)
            # host iterates children in order and updates on >=: last wins
            best_co = jnp.max(jnp.where(is_topc, node_idx, -1))

            none_found = ~any_elig & ~any_co
            # prune the current cohort when nothing remains below it
            pruned_cohort = pruned_cohort.at[current].set(
                pruned_cohort[current] | (none_found & ~done))
            cq_wins = any_elig & (
                ~any_co | drs_ge(best_zwb, best_share, best_unw,
                                 c_zwb, c_share, c_unw))
            target = jnp.where(~done & cq_wins, best_cq, target)
            done = done | none_found | cq_wins
            current = jnp.where(done, current,
                                jnp.maximum(best_co, 0).astype(jnp.int32))
        return (target.astype(jnp.int32), pruned_cq, pruned_cohort,
                zwb, share, unw)

    # preemptor_new / target_old almost-LCA shares
    def alca_shares(u, tgt_cqid):
        zwb, share, borrowing, unw = drs_all(t, u, pot_lendable)
        tgt_node = t.cq_node[jnp.minimum(tgt_cqid, C - 1)]
        lca, _ = _lca_of(t, my_path, tgt_node)
        pre_n = _almost_lca_node(t, cq_node, lca)
        tgt_n = _almost_lca_node(t, tgt_node, lca)
        return (zwb[pre_n], share[pre_n], unw[pre_n],
                zwb[tgt_n], share[tgt_n], unw[tgt_n], tgt_n)

    # ---- strategy phases ---------------------------------------------------
    def phase_loop(carry):
        (u, consumed, retry, victims, vseq, nv, pruned_cq, pruned_cohort,
         fitted, phase, it) = carry

        heads = head_slot(consumed, phase == 2, retry)
        target, pruned_cq, pruned_cohort, zwb, share, unw = tournament(
            u, pruned_cq, pruned_cohort, heads)
        # parentless preemptor: only its own CQ is a target
        # (_CQOrdering.iter() root-less branch)
        target = jnp.where(
            has_par, target,
            jnp.where(heads[cqi] < p_max, cqi, C)).astype(jnp.int32)
        has_target = target < C
        slot = heads[jnp.minimum(target, C - 1)]
        slot_ok = has_target & (slot < p_max)
        a = cand_w[jnp.minimum(slot, p_max - 1)]
        a_node = t.cq_node[jnp.minimum(t.wl_cqid[a], C - 1)]
        is_own = has_target & (target == cqi)

        (p_zwb, p_share, p_unw, t_zwb, t_share, t_unw,
         tgt_alca) = alca_shares(u, target)

        # target_new = target almost-LCA share after removing the head
        u_try = _remove_usage_along_path(
            t, u, a_node, jnp.where(slot_ok, wl_usage[a], 0))
        zwb2, share2, _b2, unw2 = drs_all(t, u_try, pot_lendable)
        n_zwb, n_share, n_unw = (zwb2[tgt_alca], share2[tgt_alca],
                                 unw2[tgt_alca])

        # strategy rule: phase 1 = S2-a LessThanOrEqualToFinalShare
        # (own-CQ pops skip the rule; a within-nominal preemptor
        # bypasses it for cross-CQ candidates too); phase 2 = S2-b
        # LessThanInitialShare
        s2a = drs_le(p_zwb, p_share, p_unw, n_zwb, n_share, n_unw)
        s2b = drs_lt(p_zwb, p_share, p_unw, t_zwb, t_share, t_unw)
        accept = slot_ok & jnp.where(
            phase == 1, is_own | within_nominal | s2a, s2b)

        u = jnp.where(accept, u_try, u)
        consumed = consumed.at[jnp.minimum(slot, p_max - 1)].set(
            consumed[jnp.minimum(slot, p_max - 1)] | slot_ok)
        # phase-1 rejections go to the retry list (S2-b pass)
        retry = retry.at[jnp.minimum(slot, p_max - 1)].set(
            retry[jnp.minimum(slot, p_max - 1)]
            | (slot_ok & ~accept & (phase == 1) & ~is_own))
        victims = victims.at[jnp.minimum(slot, p_max - 1)].set(
            victims[jnp.minimum(slot, p_max - 1)] | accept)
        vseq = vseq.at[jnp.minimum(slot, p_max - 1)].set(
            jnp.where(accept, nv, vseq[jnp.minimum(slot, p_max - 1)]))
        nv = nv + accept.astype(jnp.int32)
        fitted = accept & fits_fs(u)

        # S2-b: drop the queue after one attempt regardless of outcome
        pruned_cq = pruned_cq.at[jnp.minimum(target, C - 1)].set(
            pruned_cq[jnp.minimum(target, C - 1)]
            | (has_target & (phase == 2)))

        # phase transition: root pruned in phase 1 -> phase 2 with fresh
        # pruning state over the retry list
        root_dead = pruned_cohort[root_node] | ~has_target
        to_phase2 = (phase == 1) & root_dead & ~fitted
        pruned_cq = jnp.where(to_phase2, jnp.zeros_like(pruned_cq),
                              pruned_cq)
        pruned_cohort = jnp.where(
            to_phase2, jnp.zeros_like(pruned_cohort), pruned_cohort)
        # consumed slots stay consumed; retries become poppable again
        consumed = jnp.where(to_phase2, consumed & ~retry, consumed)
        phase = jnp.where(to_phase2, 2, phase)
        return (u, consumed, retry, victims, vseq, nv, pruned_cq,
                pruned_cohort, fitted, phase, it + 1)

    def phase_cond(carry):
        (u, consumed, retry, victims, vseq, nv, pruned_cq, pruned_cohort,
         fitted, phase, it) = carry
        root_dead = pruned_cohort[root_node]
        return (~fitted & (it < 2 * p_max + N1)
                & ~((phase == 2) & root_dead))

    # fresh init constants derive their type from head_w so the carries
    # stay consistent under shard_map's varying-axes check (a no-op on
    # the unsharded path; same pattern as classical_search)
    vzero = head_w.astype(jnp.int32) * 0
    vfalse = vzero != 0
    init = (usage_sim,
            jnp.zeros((p_max,), dtype=bool) | vfalse,   # consumed
            jnp.zeros((p_max,), dtype=bool) | vfalse,   # retry
            jnp.zeros((p_max,), dtype=bool) | vfalse,   # victims
            jnp.full((p_max,), -1, dtype=jnp.int32) + vzero,  # vseq
            vzero,                             # nv
            jnp.zeros((C,), dtype=bool) | vfalse,       # pruned_cq
            jnp.zeros((N1,), dtype=bool) | vfalse,      # pruned_cohort
            vfalse,                            # fitted
            jnp.ones((), dtype=jnp.int32) + vzero,      # phase
            vzero)
    (u_fin, consumed, retry, victims, vseq, nv, _pc, _pco, fitted,
     _phase, _it) = jax.lax.while_loop(phase_cond, phase_loop, init)

    # ---- fill back (incoming usage reverted; allowBorrowing=true) ---------
    u_fb = _remove_usage_along_path(t, u_fin, cq_node, req)

    def fb_cond(carry):
        u, victims, s = carry
        return fitted & (s >= 0)

    def fb_body(carry):
        u, victims, s = carry
        # slot with addition sequence s (skip the last added = nv - 1)
        match = victims & (vseq == s)
        slot = jnp.argmax(match)
        a = cand_w[slot]
        a_node = t.cq_node[jnp.minimum(t.wl_cqid[a], C - 1)]
        tryit = jnp.any(match)
        u_row = jnp.where(tryit, wl_usage[a], 0)
        u = _add_usage_along_path(t, u, a_node, u_row)
        still = _workload_fits(t, u, cq_node, req, True)
        u = _remove_usage_along_path(
            t, u, a_node, jnp.where(tryit & ~still, u_row, 0))
        victims = victims.at[slot].set(
            victims[slot] & ~(tryit & still))
        return (u, victims, s - 1)

    u_fb, victims, _ = jax.lax.while_loop(
        fb_cond, fb_body, (u_fb, victims, nv - 2))

    victims = victims & fitted
    success = fitted
    level_f, _ = _height_along_path(
        t, jnp.where(success, u_fb, usage0_round), cq_node, req)
    borrow_after = jnp.max(jnp.where(frs_mask, level_f, 0))
    victim_same = victims & (t.wl_cqid[cand_w] == cqid)
    any_same_cq = jnp.any(victim_same)
    # within-nominal bypass victims are entitlement reclamations
    # (kueue.InCohortReclamationReason), not fair-sharing preemptions
    cross_reason = jnp.where(within_nominal, V_HIERARCHICAL_RECLAIM,
                             V_FAIR_SHARING)
    reason = jnp.where(
        victims,
        jnp.where(victim_same, V_WITHIN_CQ, cross_reason),
        0).astype(jnp.int8)
    return success, cand_w, victims, reason, any_same_cq, borrow_after


# ---------------------------------------------------------------------------
# admission-order tournament (fair_sharing_iterator.go)
# ---------------------------------------------------------------------------


def fair_entry_pick(t, lendable_r, usage, cand_w, req_c, ts, active):
    """Pick the next entry to process under fair sharing.

    Mirrors _FairSharingIterator.pop(): take the first remaining entry's
    root cohort, compute per-entry DRS values along its path with the
    entry's usage hypothetically added (on the CURRENT mutated usage),
    and run the per-cohort tournament bottom-up — at every cohort the
    child with the lowest share wins, ties broken by higher priority,
    then earlier timestamp. Returns the winning entry index (C if none).
    """
    C = cand_w.shape[0]
    N1 = t.parent.shape[0]
    null_node = N1 - 1
    D = t.path.shape[1]
    W_null = t.wl_cqid.shape[0] - 1

    cq_nodes = t.cq_node                                  # [C]
    paths = t.path[cq_nodes]                              # [C, D]

    # per-entry DRS along its path with the entry usage added
    # (_compute_drs: simulate_usage_addition then shares up the path)
    v = req_c                                             # [C, F]
    rows_new = []
    for d in range(D):
        node = paths[:, d]
        ok = (node != null_node)[:, None]
        la = jnp.maximum(0, t.local_quota[node] - usage[node])
        rows_new.append(usage[node] + jnp.where(ok, v, 0))
        v = jnp.maximum(0, v - la)
    rows_new = jnp.stack(rows_new, axis=1)                # [C, D, F]
    borrowed = jnp.maximum(0, rows_new - t.subtree[paths])
    borrowed_r = jnp.einsum("cdf,fr->cdr", borrowed, t.res_onehot)
    lend = lendable_r[paths]                              # [C, D, R]
    ratio = jnp.where((lend > 0) & (borrowed_r > 0),
                      borrowed_r.astype(jnp.float32) * 1000.0
                      / lend.astype(jnp.float32), 0.0)
    unw = jnp.max(ratio, axis=2)                          # [C, D]
    unw = jnp.where(t.has_parent[paths], unw, 0.0)
    w = t.node_fair_weight[paths]
    share = jnp.where(w > 0, unw / jnp.maximum(w, 1e-30), 0.0)
    zwb = (w == 0) & (unw > 0)

    # FairSharingPrioritizeNonBorrowing (trace-time gate): the leading
    # tournament key prefers subtrees NOT borrowing on the entry's
    # REQUESTED resources at this level
    # (fair_sharing_iterator.go:180-193)
    from kueue_oss_tpu import features as _features

    fs_nonborrow = _features.enabled("FairSharingPrioritizeNonBorrowing")
    prio_step = _features.enabled("PrioritySortingWithinCohort")
    if fs_nonborrow:
        # FLAVOR-resource granularity, matching the host's
        # DRS.is_borrowing_on over borrowed_frs (quota.py) — borrowing
        # on another flavor of the same resource must not penalize
        borrow_on_req = jnp.any(
            (borrowed > 0) & (req_c[:, None, :] > 0), axis=2)  # [C, D]
        borrow_on_req = borrow_on_req & t.has_parent[paths]

    # bottom-up winner propagation over the cohort forest
    prio = t.wl_prio[cand_w]
    ets = ts[cand_w]
    e_idx = jnp.arange(C, dtype=jnp.int32)
    win = jnp.full((N1,), C, dtype=jnp.int32)
    win = win.at[cq_nodes].set(jnp.where(active, e_idx, C), mode="drop")
    depth_cq = t.depth[cq_nodes]                          # [C]

    max_d = D - 1
    n_idx = jnp.arange(N1, dtype=jnp.int32)
    for d in range(max_d, 0, -1):
        e = win                                           # [N1]
        contend = (t.depth == d) & (e < C) & (n_idx != null_node)
        ec = jnp.minimum(e, C - 1)
        # position of this node on the entry's path
        j = jnp.clip(depth_cq[ec] - d, 0, D - 1)
        parent = jnp.where(contend, t.parent, null_node)
        seg = jnp.minimum(parent, null_node)
        # lexicographic segment-min: [not-borrowing-on-requested first
        # when gated,] zwb asc (non-borrower first), value asc, -prio
        # asc, ts asc, entry idx asc
        if fs_nonborrow:
            k_bor = jnp.where(contend, borrow_on_req[ec, j], True)
            m_b = jax.ops.segment_min(
                k_bor.astype(jnp.int32), seg, num_segments=N1)
            contend = contend & (k_bor.astype(jnp.int32) == m_b[seg])
        k_zwb = jnp.where(contend, zwb[ec, j], True)
        k_val = jnp.where(contend,
                          jnp.where(zwb[ec, j], unw[ec, j], share[ec, j]),
                          jnp.inf)
        # the priority tie-break is gated like the host's step 3
        # (PrioritySortingWithinCohort; a constant key = skipped step)
        k_prio = (jnp.where(contend, -prio[ec], BIG)
                  if prio_step else jnp.zeros_like(prio[ec]))
        k_ts = jnp.where(contend, ets[ec], BIG)
        m_z = jax.ops.segment_min(
            k_zwb.astype(jnp.int32), seg, num_segments=N1)
        c1 = contend & (k_zwb.astype(jnp.int32) == m_z[seg])
        m_v = jax.ops.segment_min(
            jnp.where(c1, k_val, jnp.inf), seg, num_segments=N1)
        c2 = c1 & (k_val == m_v[seg])
        m_p = jax.ops.segment_min(
            jnp.where(c2, k_prio, BIG), seg, num_segments=N1)
        c3 = c2 & (k_prio == m_p[seg])
        m_t = jax.ops.segment_min(
            jnp.where(c3, k_ts, BIG), seg, num_segments=N1)
        c4 = c3 & (k_ts == m_t[seg])
        m_e = jax.ops.segment_min(
            jnp.where(c4, ec, C), seg, num_segments=N1)
        win = jnp.where((t.depth == d - 1) & (m_e < C)
                        & ~t.is_cq, m_e, win)

    # the host pops from the FIRST remaining entry's root tree
    first_e = jnp.min(jnp.where(active, e_idx, C))
    first_root = t.cq_root[jnp.minimum(first_e, C - 1)]
    # parentless CQ: the entry itself wins directly
    winner = jnp.where(
        t.has_parent[t.cq_node[jnp.minimum(first_e, C - 1)]],
        win[first_root], first_e)
    return jnp.where(first_e < C, winner, C).astype(jnp.int32)
