"""Jitted admission kernels over dense quota tensors.

The drain kernel reproduces the reference scheduler's cycle semantics
(pkg/scheduler/scheduler.go:286-467) exactly, but runs the whole backlog in
one XLA program:

  round (= one reference cycle, lax.while_loop):
    1. head selection   — per-CQ lowest-rank pending workload (segment min)
    2. nomination       — batched flavor-option classification against the
                          hierarchical availability (level-wise top-down)
    3. entry ordering   — lexsort by (borrow level, -priority, timestamp)
    4. admission scan   — lax.scan in entry order: re-check fit under the
                          current usage, bubble usage up the cohort path;
                          Preempt-mode entries reserve entitled capacity
                          and park (reservations die with the round)
    5. rebuild          — cohort usage recomputed bottom-up from CQ rows,
                          mirroring the reference's fresh per-cycle snapshot

All control flow is lax.* (no data-dependent Python), shapes are static,
quantities are int32 (the exporter guarantees no overflow), so XLA maps the
batched phases onto the VPU and the scan stays on-chip.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kueue_oss_tpu.solver.tensors import BIG, SolverProblem

# candidate modes
M_NOFIT = 0
M_PREEMPT = 1
M_FIT = 2


class ProblemTensors(NamedTuple):
    """Device-side mirror of SolverProblem (jit pytree)."""

    parent: jnp.ndarray
    depth: jnp.ndarray
    height: jnp.ndarray
    has_parent: jnp.ndarray
    is_cq: jnp.ndarray
    path: jnp.ndarray
    subtree: jnp.ndarray
    local_quota: jnp.ndarray
    nominal: jnp.ndarray
    has_borrow: jnp.ndarray
    borrow_limit: jnp.ndarray
    usage0: jnp.ndarray
    cq_node: jnp.ndarray
    cq_strict: jnp.ndarray
    cq_try_next: jnp.ndarray
    cq_nflavors: jnp.ndarray
    wl_cqid: jnp.ndarray
    wl_rank: jnp.ndarray
    wl_prio: jnp.ndarray
    wl_ts: jnp.ndarray
    wl_uid: jnp.ndarray
    wl_req: jnp.ndarray
    wl_valid: jnp.ndarray


def host_tensors(p: SolverProblem) -> ProblemTensors:
    """The lean kernel's input tensors as HOST (numpy) arrays.

    Split out of :func:`to_device` so callers that reuse resident device
    buffers (DeviceResidentProblem's donated full-sync overwrite) can
    build the new content without first materializing a second full set
    of device buffers."""
    import numpy as np

    is_cq = np.zeros(p.parent.shape[0], dtype=bool)
    is_cq[p.cq_node] = True
    return ProblemTensors(
        parent=p.parent,
        depth=p.depth,
        height=p.height,
        has_parent=p.has_parent,
        is_cq=is_cq,
        path=p.path,
        subtree=p.subtree,
        local_quota=p.local_quota,
        nominal=p.nominal,
        has_borrow=p.has_borrow,
        borrow_limit=p.borrow_limit,
        usage0=p.usage0,
        cq_node=p.cq_node,
        cq_strict=p.cq_strict,
        cq_try_next=p.cq_try_next,
        cq_nflavors=p.cq_nflavors,
        wl_cqid=p.wl_cqid,
        wl_rank=p.wl_rank,
        wl_prio=p.wl_prio,
        wl_ts=p.wl_ts,
        wl_uid=p.wl_uid,
        wl_req=p.wl_req,
        wl_valid=p.wl_valid,
    )


def to_device(p: SolverProblem) -> ProblemTensors:
    return jax.tree_util.tree_map(jnp.asarray, host_tensors(p))


# ---------------------------------------------------------------------------
# Hierarchical quota algebra, tensorized (resource_node.go)
# ---------------------------------------------------------------------------


def refresh_cohort_usage(t: ProblemTensors, usage: jnp.ndarray) -> jnp.ndarray:
    """Recompute cohort rows bottom-up from ClusterQueue rows.

    Mirrors the accumulate step of resource_node.go:210-217: a parent's
    usage is the sum over children of max(0, child_usage - child_local).
    """
    u = jnp.where(t.is_cq[:, None], usage, 0)
    d_max = t.path.shape[1]
    depth_col = t.depth[:, None]
    for d in range(d_max - 1, 0, -1):
        contrib = jnp.where(depth_col == d,
                            jnp.maximum(0, u - t.local_quota), 0)
        u = u.at[t.parent].add(contrib, mode="drop")
    return u


def accumulate_full_charge(parent: jnp.ndarray, depth: jnp.ndarray,
                           values: jnp.ndarray, d_max: int) -> jnp.ndarray:
    """Sum node-row values into every ancestor WITHOUT local-quota
    absorption — refresh_cohort_usage's relaxed cousin.

    The exact algebra absorbs each child's local quota on the way up
    (only the overflow bubbles); the convex relaxation
    (solver/relax.py) instead prices the AGGREGATE load under each
    node against that node's total headroom, which is exactly this
    full-charge accumulation. ``d_max`` is the static ancestor-path
    width (path.shape[1]).
    """
    u = values
    depth_col = depth[:, None]
    for d in range(d_max - 1, 0, -1):
        u = u.at[parent].add(jnp.where(depth_col == d, u, 0),
                             mode="drop")
    return u


def available_all(t: ProblemTensors, usage: jnp.ndarray) -> jnp.ndarray:
    """available() for every node, level-wise from the roots down.

    Mirrors resource_node.go:104-118: root avail = subtree - usage; child
    avail = localAvailable + min(parentAvail, storedInParent - usedInParent
    + borrowingLimit).
    """
    avail = t.subtree - usage  # correct for depth-0 (roots)
    local_avail = jnp.maximum(0, t.local_quota - usage)
    stored = t.subtree - t.local_quota
    used_in_parent = jnp.maximum(0, usage - t.local_quota)
    clamp = jnp.where(t.has_borrow,
                      stored - used_in_parent + t.borrow_limit, BIG)
    depth_col = t.depth[:, None]
    for d in range(1, t.path.shape[1]):
        parent_avail = avail[t.parent]
        cand = local_avail + jnp.minimum(parent_avail, clamp)
        avail = jnp.where(depth_col == d, cand, avail)
    return avail


def potential_available_all(t: ProblemTensors) -> jnp.ndarray:
    """potentialAvailable() for every node (resource_node.go:122-133)."""
    pot = t.subtree  # roots
    cap = jnp.where(t.has_borrow, t.subtree + t.borrow_limit, BIG)
    depth_col = t.depth[:, None]
    for d in range(1, t.path.shape[1]):
        parent_pot = pot[t.parent]
        cand = jnp.minimum(t.local_quota + parent_pot, cap)
        pot = jnp.where(depth_col == d, cand, pot)
    return pot


def borrow_levels(t: ProblemTensors, usage: jnp.ndarray,
                  cand_w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """FindHeightOfLowestSubtreeThatFits, batched over candidates/options.

    Returns (level [C,K,F] int32, may_reclaim [C,K,F] bool) for each
    candidate workload's request; level is 0 where req == 0.
    Reference parity: classical/hierarchical_preemption.go:221-243.
    """
    null = t.parent.shape[0] - 1
    req = t.wl_req[cand_w]                       # [C,K,F]
    paths = t.path[t.cq_node]                    # [C,D]
    d_max = paths.shape[1]

    level = jnp.zeros_like(req)
    may_reclaim = jnp.zeros(req.shape, dtype=bool)
    found = req == 0
    rem = req
    for d in range(d_max):
        node = paths[:, d]                       # [C]
        node_valid = (node != null)[:, None, None]
        usage_n = usage[node][:, None, :]
        subtree_n = t.subtree[node][:, None, :]
        la_n = jnp.maximum(
            0, t.local_quota[node] - usage[node])[:, None, :]
        not_borrowing = usage_n + rem <= subtree_n
        newly = (~found) & not_borrowing & node_valid
        level = jnp.where(newly, t.height[node][:, None, None], level)
        may_reclaim = jnp.where(
            newly, t.has_parent[node][:, None, None], may_reclaim)
        found = found | newly
        rem = jnp.where(found | ~node_valid, rem, rem - la_n)
    # Not found anywhere: whole-hierarchy height, no proper subtree.
    root_idx = paths[:, d_max - 1]
    for d in range(d_max - 2, -1, -1):
        root_idx = jnp.where(root_idx == null, paths[:, d], root_idx)
    root_h = t.height[root_idx][:, None, None]
    level = jnp.where(found, level, root_h)
    return level, may_reclaim


# ---------------------------------------------------------------------------
# Per-round candidate nomination
# ---------------------------------------------------------------------------


def nominate(t: ProblemTensors, usage: jnp.ndarray, avail: jnp.ndarray,
             pot: jnp.ndarray, cand_w: jnp.ndarray, cursor: jnp.ndarray):
    """Classify each CQ's head: (mode, chosen option, borrow level,
    next cursor).

    Mirrors flavorassigner fitsResourceQuota + fungibility option
    selection, including the LastTriedFlavorIdx cursor: the search starts
    at ``cursor[head]`` and the returned next-cursor encodes where a
    re-nomination after a failed re-check must resume
    (flavorassigner.go:843,939-947). Preempt here corresponds to the
    reference's Preempt mode with NoCandidates (the solver path is used
    when no preemption policy is enabled, so SimulatePreemption would
    find no targets).
    """
    req = t.wl_req[cand_w]                        # [C,K,F]
    k_arange = jnp.arange(req.shape[1], dtype=jnp.int32)[None, :]
    cursor_c = cursor[cand_w][:, None]            # [C,1]
    valid = t.wl_valid[cand_w] & (k_arange >= cursor_c)  # [C,K]
    avail_cq = avail[t.cq_node][:, None, :]       # [C,1,F]
    pot_cq = pot[t.cq_node][:, None, :]
    nominal_cq = t.nominal[t.cq_node][:, None, :]

    level, may_reclaim = borrow_levels(t, usage, cand_w)

    nonzero = req > 0
    fit_fr = (~nonzero) | (req <= avail_cq)               # [C,K,F]
    within_cap = (~nonzero) | (req <= pot_cq)
    preemptish_fr = (~nonzero) | (
        within_cap & ((req <= nominal_cq) | may_reclaim))

    opt_fit = valid & jnp.all(fit_fr, axis=-1)            # [C,K]
    opt_preempt = valid & jnp.all(fit_fr | preemptish_fr, axis=-1)
    opt_level = jnp.max(jnp.where(nonzero, level, 0), axis=-1)  # [C,K]

    K = req.shape[1]
    k_idx = jnp.arange(K, dtype=jnp.int32)[None, :]

    def first_true(mask):  # [C,K] -> [C] first index or K
        return jnp.min(jnp.where(mask, k_idx, K), axis=1)

    # default policy (whenCanBorrow=Borrow): first fitting option.
    k_default = first_true(opt_fit)
    # whenCanBorrow=TryNextFlavor: first non-borrowing fit, else the fit
    # with the lowest borrow level (ties -> earliest flavor).
    k_nonborrow = first_true(opt_fit & (opt_level == 0))
    lvl_key = jnp.where(opt_fit, opt_level * K + k_idx, BIG)
    k_bestlvl = jnp.argmin(lvl_key, axis=1).astype(jnp.int32)
    k_try_next = jnp.where(
        k_nonborrow < K, k_nonborrow,
        jnp.where(jnp.any(opt_fit, axis=1), k_bestlvl, K))
    k_fit = jnp.where(t.cq_try_next, k_try_next, k_default)

    any_fit = k_fit < K
    k_preempt = first_true(opt_preempt & ~opt_fit)
    any_preempt = k_preempt < K

    k_chosen = jnp.where(any_fit, k_fit,
                         jnp.where(any_preempt, k_preempt, 0))
    k_chosen = k_chosen.astype(jnp.int32)
    mode = jnp.where(any_fit, M_FIT,
                     jnp.where(any_preempt, M_PREEMPT, M_NOFIT))
    borrow = jnp.take_along_axis(opt_level, k_chosen[:, None], axis=1)[:, 0]

    # Flavor cursor for re-nomination: the search breaks early only at a
    # fit the fungibility policy accepts (default: any fit; TryNextFlavor:
    # a non-borrowing fit); then the next attempt resumes at the following
    # flavor. Walking off the end resets the cursor to 0.
    early_break = jnp.where(t.cq_try_next, k_nonborrow < K, any_fit)
    nfl = t.cq_nflavors
    next_cursor = jnp.where(
        early_break & (k_chosen < nfl - 1), k_chosen + 1, 0)
    return mode, k_chosen, borrow, next_cursor.astype(jnp.int32)


# ---------------------------------------------------------------------------
# In-round admission scan (entry order, usage bubbling)
# ---------------------------------------------------------------------------


def _avail_along_path(t: ProblemTensors, usage: jnp.ndarray,
                      cq_node: jnp.ndarray) -> jnp.ndarray:
    """available() for one CQ under the current usage: walk root -> leaf."""
    path = t.path[cq_node]                        # [D]
    null = t.parent.shape[0] - 1
    avail = jnp.zeros((t.subtree.shape[1],), dtype=jnp.int32)
    started = jnp.zeros((), dtype=bool)
    for d in range(path.shape[0] - 1, -1, -1):
        node = path[d]
        is_valid = node != null
        usage_n = usage[node]
        subtree_n = t.subtree[node]
        local_q = t.local_quota[node]
        local_avail = jnp.maximum(0, local_q - usage_n)
        stored = subtree_n - local_q
        used_in_parent = jnp.maximum(0, usage_n - local_q)
        clamp = jnp.where(t.has_borrow[node],
                          stored - used_in_parent + t.borrow_limit[node], BIG)
        root_avail = subtree_n - usage_n
        child_avail = local_avail + jnp.minimum(avail, clamp)
        cand = jnp.where(started, child_avail, root_avail)
        avail = jnp.where(is_valid, cand, avail)
        started = started | is_valid
    return avail


def _add_usage_along_path(t: ProblemTensors, usage: jnp.ndarray,
                          cq_node: jnp.ndarray,
                          val: jnp.ndarray) -> jnp.ndarray:
    """addUsage with bubbling (resource_node.go:137-145) along one path."""
    path = t.path[cq_node]
    null = t.parent.shape[0] - 1
    for d in range(path.shape[0]):
        node = path[d]
        is_valid = node != null
        usage_n = usage[node]
        local_avail = jnp.maximum(0, t.local_quota[node] - usage_n)
        usage = usage.at[node].add(jnp.where(is_valid, val, 0))
        val = jnp.maximum(0, val - local_avail)
    return usage


def _round_scan(t: ProblemTensors, usage, cq_usage, admitted, parked,
                cand_w, mode, k_chosen, borrow):
    # strict queues never park (their head keeps blocking the queue)
    """Process this round's nominated heads in entry order.

    ``usage`` is the working tensor (admissions + reservations, bubbled);
    ``cq_usage`` carries only durable CQ-row usage (admissions). Cohort
    rows are rebuilt from it at round end, which also drops reservations —
    exactly like the reference's fresh per-cycle snapshot.
    """
    C = cand_w.shape[0]
    W_null = t.wl_rank.shape[0] - 1

    prio = t.wl_prio[cand_w]
    ts = t.wl_ts[cand_w]
    uid = t.wl_uid[cand_w]
    active = (cand_w != W_null) & (mode != M_NOFIT)
    sort_borrow = jnp.where(active, borrow, BIG)
    order = jnp.lexsort((uid, ts, -prio, sort_borrow))

    def step(carry, slot):
        usage, cq_usage, admitted, parked, any_admitted = carry
        w, cqid, m, k, brw = slot
        cq_node = t.cq_node[cqid]
        req = t.wl_req[w, k]                        # [F]
        is_active = (w != W_null) & (m != M_NOFIT)

        # Preempt mode: reserve entitled capacity and park
        # (scheduler.go reserveCapacityForUnreclaimablePreempt).
        usage_cq = usage[cq_node]
        nominal_cq = t.nominal[cq_node]
        bl = t.borrow_limit[cq_node]
        reserve_borrowing = jnp.where(
            t.has_borrow[cq_node],
            jnp.minimum(req, nominal_cq + bl - usage_cq), req)
        reserve_nominal = jnp.minimum(req, nominal_cq - usage_cq)
        reserve = jnp.maximum(
            0, jnp.where(brw > 0, reserve_borrowing, reserve_nominal))

        is_preempt = is_active & (m == M_PREEMPT)
        usage = _add_usage_along_path(
            t, usage, cq_node, jnp.where(is_preempt, reserve, 0))
        # Preempt-no-targets heads requeue with reason Generic: parked for
        # BestEffortFIFO, pushed back to the heap (still blocking) for
        # StrictFIFO (cluster_queue.go requeueIfNotPresent).
        parked = parked.at[w].set(
            parked[w] | (is_preempt & ~t.cq_strict[cqid]))

        # Fit mode: re-check under current usage, then admit.
        avail_now = _avail_along_path(t, usage, cq_node)
        still_fits = jnp.all((req == 0) | (req <= avail_now))
        do_admit = is_active & (m == M_FIT) & still_fits
        admit_vec = jnp.where(do_admit, req, 0)
        usage = _add_usage_along_path(t, usage, cq_node, admit_vec)
        cq_usage = cq_usage.at[cq_node].add(admit_vec)
        admitted = admitted.at[w].set(admitted[w] | do_admit)
        any_admitted = any_admitted | do_admit
        return (usage, cq_usage, admitted, parked, any_admitted), None

    slots = (cand_w[order], jnp.arange(C, dtype=jnp.int32)[order],
             mode[order], k_chosen[order], borrow[order])
    init = (usage, cq_usage, admitted, parked, jnp.zeros((), dtype=bool))
    (usage, cq_usage, admitted, parked, any_admitted), _ = jax.lax.scan(
        step, init, slots)
    return cq_usage, admitted, parked, any_admitted


# ---------------------------------------------------------------------------
# The drain loop
# ---------------------------------------------------------------------------


def _select_heads(t: ProblemTensors, admitted, parked):
    """Per-CQ lowest-rank pending workload (two-pass int32 segment min)."""
    C = t.cq_node.shape[0]
    W1 = t.wl_rank.shape[0]
    W_null = W1 - 1
    pending = ~admitted & ~parked
    rank_eff = jnp.where(pending, t.wl_rank, BIG)
    min_rank = jax.ops.segment_min(
        rank_eff[:-1], t.wl_cqid[:-1], num_segments=C + 1)[:C]
    w_idx = jnp.arange(W1 - 1, dtype=jnp.int32)
    is_head = rank_eff[:-1] == min_rank[t.wl_cqid[:-1]]
    head_w = jax.ops.segment_min(
        jnp.where(is_head, w_idx, W_null), t.wl_cqid[:-1],
        num_segments=C + 1)[:C]
    has_head = min_rank < BIG
    return jnp.where(has_head, head_w, W_null).astype(jnp.int32)


def _solve_backlog_impl(t: ProblemTensors):
    """Drain the backlog: run reference-equivalent cycles until quiescent.

    Returns (admitted [W+1] bool, chosen_option [W+1] int32,
    admit_round [W+1] int32, parked [W+1] bool, rounds int32,
    final usage [N+1, F]).
    """
    W1 = t.wl_rank.shape[0]
    C = t.cq_node.shape[0]
    W_null = W1 - 1
    pot = potential_available_all(t)

    def cond(state):
        _, _, _, _, _, _, progress, rounds = state
        return progress & (rounds < W1 + C + 2)

    def body(state):
        usage, admitted, parked, cursor, opt, admit_round, _, rounds = state
        parked_before = parked
        cursor_before = cursor
        cand_w = _select_heads(t, admitted, parked)
        avail = available_all(t, usage)
        mode, k_chosen, borrow, next_cursor = nominate(
            t, usage, avail, pot, cand_w, cursor)

        # Park NoFit heads of BestEffortFIFO queues; StrictFIFO heads stay
        # and block their queue (inadmissible-parking parity).
        is_head = cand_w != W_null
        strict_head = t.cq_strict & is_head
        park_now = is_head & (mode == M_NOFIT) & ~strict_head
        parked = parked.at[cand_w].set(parked[cand_w] | park_now)

        was_admitted = admitted
        cq_usage, admitted, parked, any_admitted = _round_scan(
            t, usage, usage, admitted, parked, cand_w, mode, k_chosen,
            borrow)
        usage = refresh_cohort_usage(t, cq_usage)

        newly = admitted[cand_w] & ~was_admitted[cand_w]
        opt = opt.at[cand_w].set(jnp.where(newly, k_chosen, opt[cand_w]))
        admit_round = admit_round.at[cand_w].set(
            jnp.where(newly, rounds, admit_round[cand_w]))
        # Record the flavor cursor for heads that stay pending, so their
        # next nomination resumes at the right flavor.
        is_head = cand_w != W_null
        keep = is_head & ~admitted[cand_w]
        cursor = cursor.at[cand_w].set(
            jnp.where(keep, next_cursor, cursor[cand_w]))

        # Progress = any admission, any head parked (NoFit or Preempt
        # mode — the queue advances next round), or any cursor movement
        # (the head will try different flavors next round).
        progress = (any_admitted
                    | jnp.any(parked & ~parked_before)
                    | jnp.any(cursor != cursor_before))
        return (usage, admitted, parked, cursor, opt, admit_round, progress,
                rounds + 1)

    init = (
        t.usage0,
        jnp.zeros(W1, dtype=bool),
        jnp.zeros(W1, dtype=bool),
        jnp.zeros(W1, dtype=jnp.int32),
        jnp.zeros(W1, dtype=jnp.int32),
        jnp.full(W1, -1, dtype=jnp.int32),
        jnp.ones((), dtype=bool),
        jnp.zeros((), dtype=jnp.int32),
    )
    usage, admitted, parked, _cursor, opt, admit_round, _, rounds = (
        jax.lax.while_loop(cond, body, init))
    admitted = admitted.at[W_null].set(False)
    parked = parked.at[W_null].set(False)
    return admitted, opt, admit_round, parked, rounds, usage


solve_backlog = jax.jit(_solve_backlog_impl)


# ---------------------------------------------------------------------------
# Scenario-batched entry (kueue_oss_tpu/sim what-if engine)
# ---------------------------------------------------------------------------

#: ProblemTensors fields a scenario overlay may vary per scenario. The
#: lean drain is pure int/bool arithmetic, so a vmapped batch is
#: bit-identical to solving each scenario alone (the batched while_loop
#: freezes finished lanes with a select, never perturbing their state).
BATCHABLE_FIELDS = frozenset({
    "nominal", "subtree", "local_quota", "has_borrow", "borrow_limit",
    "usage0", "wl_cqid", "wl_rank", "wl_prio", "wl_ts", "wl_valid",
    "wl_req",
})

#: Every ProblemTensors field. The drain body is shape-static pure
#: gather/scatter arithmetic with no host-side dependence on array
#: CONTENT, so any field may carry the scenario axis — the federation
#: dispatcher batches whole canvas-normalized problems from DIFFERENT
#: clusters this way (sim/dispatch.py). BATCHABLE_FIELDS remains the
#: documented subset single-problem overlay sweeps vary.
ALL_PROBLEM_FIELDS = frozenset(ProblemTensors._fields)


@functools.lru_cache(maxsize=None)
def _batched_solver(fields: frozenset):
    """Jitted vmap of the lean drain over a leading scenario axis.

    Only the overlay's ``fields`` carry the [S, ...] axis; everything
    else (notably the large wl_req tensor when quota-only sweeps leave
    it untouched) broadcasts unbatched, so an S-way batch does not cost
    S copies of the whole problem."""
    axes = ProblemTensors(
        **{f: (0 if f in fields else None)
           for f in ProblemTensors._fields})
    return jax.jit(jax.vmap(_solve_backlog_impl, in_axes=(axes,)))


def solve_backlog_batched(t: ProblemTensors, overrides: dict):
    """Solve S counterfactual variants of one padded problem in ONE
    device dispatch.

    ``overrides`` maps BATCHABLE_FIELDS names to stacked [S, ...] arrays
    (scenario variants of the corresponding base array); unnamed fields
    are shared across the batch. Returns the solve_backlog tuple with a
    leading scenario axis on every output.
    """
    if not overrides:
        raise ValueError("batched solve needs at least one scenario-"
                         "varying field (use solve_backlog otherwise)")
    bad = set(overrides) - ALL_PROBLEM_FIELDS
    if bad:
        raise ValueError(
            f"fields {sorted(bad)} are not ProblemTensors fields; "
            f"batchable: {sorted(ALL_PROBLEM_FIELDS)}")
    fn = _batched_solver(frozenset(overrides))
    return fn(t._replace(**{k: jnp.asarray(v)
                            for k, v in overrides.items()}))
