"""Process-separated solver service (the gRPC-sidecar analog).

SURVEY.md §2.4: the reference's control plane is one Go process; the
TPU-native design adds a sidecar carrying the CQ×FlavorResource usage
tensor + pending-workload request tensor to a separate JAX solver
process, so the control plane never blocks on device compilation and
the solver can sit on the TPU host while the scheduler runs elsewhere.

Wire contract (BASELINE.json: tensor export ≙ Cache.Snapshot, plan
import ≙ assume path; docs/SOLVER_PROTOCOL.md has the full spec):

  legacy (stateless) request:
    header {kind?: "solve", caps, fs_enabled, full} + npz(problem arrays)
    response = header JSON {ok, names, spans} + npz(full plan arrays)

  session frames (delta-sync, the production path):
    SYNC:  header {kind: "sync", sid, epoch, checksum, meta, caps...}
           + npz(problem arrays) — (re)opens session ``sid`` with the
           full padded problem pinned on the sidecar across drains
    DELTA: header {kind: "delta", sid, epoch, base_epoch, checksum,
           meta_delta, caps...} + npz(dirty rows + small replacements)
    responses are COMPACT: header {ok, compact, epoch, spans} + npz of
    decided rows only (admitted/parked/evicted indices), not eight full
    W-sized arrays
    RESYNC: any session/epoch/checksum mismatch answers in-band
    {ok: false, resync: <reason>} and the client falls back to a full
    SYNC (counted in metrics.solver_resync_total — never silently wrong;
    the engine's plan guard still validates every imported plan)

Transport is a length-prefixed unix-domain socket (protocol framing is
what a gRPC stub would generate; no proto toolchain is assumed in the
image). The client side plugs into SolverEngine via `remote=`: the
engine still exports, verifies, and commits — only the solve itself
crosses the process boundary.

Resilience (this layer's failure contract):

- a truncated frame, EOF mid-frame, undecodable header/npz, or a frame
  above ``max_frame_bytes`` raises ``SolverProtocolError`` — never a
  confusing struct/zipfile error, and never an allocation sized by an
  attacker-controlled length prefix;
- ``SolverClient.solve`` runs under a per-call deadline with bounded
  retries (exponential backoff + seeded jitter, fresh connection per
  attempt = automatic reconnect) and collapses exhaustion into
  ``SolverUnavailable`` for the engine/breaker to act on;
- the server catches solve-side exceptions and reports them in-band
  (``{"ok": false}``) so one bad request cannot wedge a handler thread.
"""

from __future__ import annotations

import io
import json
import os
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Optional

import numpy as np

from kueue_oss_tpu import metrics, resilience
from kueue_oss_tpu.persist import hooks as persist_hooks
from kueue_oss_tpu.solver.delta import (
    ARRAY_FIELDS,
    META_FIELDS,
    DeviceResidentProblem,
    SessionFrame,
    apply_delta,
    deserialize_delta,
    serialize_delta,
    state_checksum,
)
from kueue_oss_tpu.solver.resilience import SolverUnavailable
from kueue_oss_tpu.solver.tensors import SolverProblem

#: SolverProblem fields shipped as arrays; the rest go in the header
#: (canonical list lives in solver/delta.py, shared with the delta layer)
_ARRAY_FIELDS = ARRAY_FIELDS
_META_FIELDS = META_FIELDS


class SolverProtocolError(ConnectionError):
    """Garbled wire state: short read/EOF mid-frame, oversized frame, or
    an undecodable header/payload. Distinct from plain ConnectionError so
    callers can tell a *misbehaving* peer from an absent one."""


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def default_timeout_s() -> float:
    """Per-call deadline; KUEUE_SOLVER_TIMEOUT_S overrides the 600 s
    default (the pre-robustness hardcode) without a code change."""
    return _env_float("KUEUE_SOLVER_TIMEOUT_S", 600.0)


def default_max_frame_bytes() -> int:
    """Frame-size guard; KUEUE_SOLVER_MAX_FRAME_MB overrides 256 MiB.
    Checked BEFORE allocating, on both sides of the wire."""
    return int(_env_float("KUEUE_SOLVER_MAX_FRAME_MB", 256.0) * (1 << 20))


def default_max_sessions() -> int:
    """Resident-session cap; KUEUE_SOLVER_MAX_SESSIONS overrides 4.
    A federated farm (N tenants x ~2 kernel kinds each) must raise this
    or the LRU thrashes — evictions are counted, never silent."""
    return max(1, int(_env_float("KUEUE_SOLVER_MAX_SESSIONS", 4.0)))


def _send(sock: socket.socket, header: dict, blob: bytes) -> None:
    h = json.dumps(header).encode()
    sock.sendall(struct.pack(">II", len(h), len(blob)))
    sock.sendall(h)
    sock.sendall(blob)


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None,
                clock=time.monotonic) -> bytes:
    """Read exactly n bytes; with ``deadline`` (absolute, in ``clock``
    units) the whole read is bounded, not just each recv: a peer
    dripping one byte per op-timeout would otherwise reset the clock on
    every chunk and stall far past the caller's budget."""
    buf = b""
    while len(buf) < n:
        if deadline is not None:
            remaining = deadline - clock()
            if remaining <= 0:
                raise TimeoutError(
                    f"deadline exhausted mid-frame: got {len(buf)} of "
                    f"{n} bytes")
            sock.settimeout(remaining)
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise SolverProtocolError(
                f"peer closed mid-frame: got {len(buf)} of {n} bytes")
        buf += chunk
    return buf


def _recv(sock: socket.socket,
          max_frame_bytes: Optional[int] = None,
          deadline: Optional[float] = None,
          clock=time.monotonic) -> tuple[dict, bytes]:
    if max_frame_bytes is None:
        max_frame_bytes = default_max_frame_bytes()
    hlen, blen = struct.unpack(
        ">II", _recv_exact(sock, 8, deadline, clock))
    if hlen + blen > max_frame_bytes:
        # reject before allocating: the length prefix is peer-controlled
        raise SolverProtocolError(
            f"frame of {hlen + blen} bytes exceeds the "
            f"{max_frame_bytes}-byte limit")
    try:
        header = json.loads(_recv_exact(sock, hlen, deadline, clock))
    except (ValueError, UnicodeDecodeError) as e:
        raise SolverProtocolError(f"undecodable frame header: {e}") from e
    if not isinstance(header, dict):
        raise SolverProtocolError("frame header is not a JSON object")
    return header, _recv_exact(sock, blen, deadline, clock)


def serialize_problem(p: SolverProblem) -> tuple[dict, bytes]:
    arrays = {}
    for name in _ARRAY_FIELDS:
        v = getattr(p, name)
        if v is not None:
            arrays[name] = np.asarray(v)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    meta = {name: getattr(p, name) for name in _META_FIELDS}
    return meta, buf.getvalue()


def deserialize_problem(meta: dict, blob: bytes) -> SolverProblem:
    data = np.load(io.BytesIO(blob))
    kwargs = {name: (data[name] if name in data else None)
              for name in _ARRAY_FIELDS}
    kwargs.update(meta)
    return SolverProblem(**kwargs)


def _solve_kernel(tensors, header: dict, mesh=None):
    """Run the jitted kernel matching the request params; returns
    (out tuple, legacy array names). With a ``mesh`` BOTH kernels
    block-shard the workload axis over it — the full kernel
    additionally shard_maps its victim-search lanes inside the solve
    (row and lane sharding compose) — and plans stay bit-identical to
    the single-chip kernels either way."""
    if header["full"]:
        from kueue_oss_tpu.solver.full_kernels import solve_backlog_full

        out = solve_backlog_full(
            tensors, header["g_max"], header["h_max"], header["p_max"],
            fs_enabled=header["fs_enabled"], mesh=mesh)
        names = ["admitted", "opt", "admit_round", "parked",
                 "rounds", "usage", "wl_usage", "victim_reason"]
    else:
        if mesh is not None:
            from kueue_oss_tpu.solver.meshutil import lean_mesh_solver

            out = lean_mesh_solver(mesh)(tensors)
        else:
            from kueue_oss_tpu.solver.kernels import solve_backlog

            out = solve_backlog(tensors)
        names = ["admitted", "opt", "admit_round", "parked",
                 "rounds", "usage"]
    return out, names


def _spans(header: dict, t0: float) -> list[dict]:
    """The response's trace-context spans: the sidecar solve itself
    plus (farmed requests) the DRR grant-wait the handler thread just
    paid. Every span names its ``source`` so the importing host tracer
    lands it on a stable per-process/per-tenant synthetic track."""
    tenant = str(header.get("tenant", ""))
    src_tail = tenant or "solver"
    span_args = {"full": bool(header["full"]),
                 "kind": header.get("kind", "solve"),
                 "source": f"sidecar:{src_tail}"}
    if tenant:
        span_args["tenant"] = tenant
    if header.get("trace_cycle") is not None:
        span_args["cycle"] = header["trace_cycle"]
    solve_dur_us = int((time.perf_counter() - t0) * 1e6)
    spans = [{"name": "sidecar_solve", "dur_us": solve_dur_us,
              "args": span_args}]
    try:
        from kueue_oss_tpu.federation.farm import last_grant_wait_s

        wait_s = last_grant_wait_s()
    except Exception:
        wait_s = 0.0
    if wait_s > 0.0:
        wait_args = {"kind": "grant_wait",
                     "source": f"farm:{src_tail}"}
        if tenant:
            wait_args["tenant"] = tenant
        if header.get("trace_cycle") is not None:
            wait_args["cycle"] = header["trace_cycle"]
        # the wait ENDED when the solve began: end_skew_us lets the
        # importing tracer place it just before the solve span instead
        # of overlapping it (both are end-aligned at response arrival)
        spans.append({"name": "farm_grant_wait",
                      "dur_us": int(wait_s * 1e6),
                      "end_skew_us": solve_dur_us,
                      "args": wait_args})
    return spans


def compact_plan(out, full: bool) -> dict[str, np.ndarray]:
    """Encode a plan as decided rows only: admitted indices (+ their
    flavor options and rounds), parked indices, and nonzero
    victim-reason rows — a few KB instead of eight W-sized arrays."""
    admitted = np.asarray(out[0]).astype(bool)
    opt = np.asarray(out[1])
    admit_round = np.asarray(out[2])
    parked = np.asarray(out[3]).astype(bool)
    adm_idx = np.nonzero(admitted)[0].astype(np.int32)
    arrays = {
        "adm_idx": adm_idx,
        "adm_opt": opt[adm_idx].astype(np.int32),
        "adm_round": admit_round[adm_idx].astype(np.int32),
        "park_idx": np.nonzero(parked)[0].astype(np.int32),
        "rounds": np.asarray(out[4]),
    }
    if full:
        vr = np.asarray(out[7])
        vr_idx = np.nonzero(vr)[0].astype(np.int32)
        arrays["vr_idx"] = vr_idx
        arrays["vr_val"] = vr[vr_idx].astype(np.int32)
    return arrays


def expand_compact_plan(data, W1: int, full: bool, g_max: int):
    """Client-side inverse of compact_plan: rebuild the dense arrays the
    engine's plan guard and apply paths consume. Reconstruction is pure
    scatter — overlaps or out-of-range indices in a corrupt response
    survive into the dense arrays for the sanity guard to reject."""
    adm_idx = np.asarray(data["adm_idx"])
    adm_opt = np.asarray(data["adm_opt"])
    admitted = np.zeros(W1, dtype=bool)
    parked = np.zeros(W1, dtype=bool)
    admitted[adm_idx] = True
    parked[np.asarray(data["park_idx"])] = True
    if full:
        g = adm_opt.shape[1] if adm_opt.ndim == 2 else max(1, g_max)
        opt = np.zeros((W1, g), dtype=np.int32)
        admit_round = np.full(W1, -1, dtype=np.int32)
    else:
        opt = np.zeros(W1, dtype=np.int32)
        admit_round = np.zeros(W1, dtype=np.int32)
    opt[adm_idx] = adm_opt
    admit_round[adm_idx] = np.asarray(data["adm_round"])
    rounds = np.asarray(data["rounds"])
    usage = np.zeros(1, dtype=np.int32)  # engine ignores usage tensors
    if not full:
        return admitted, opt, admit_round, parked, rounds, usage
    victim = np.zeros(W1, dtype=np.int32)
    victim[np.asarray(data["vr_idx"])] = np.asarray(data["vr_val"])
    return (admitted, opt, admit_round, parked, rounds, usage,
            np.zeros(1, dtype=np.int32), victim)


class _SidecarSession:
    """Resident state for one (sid) delta-sync session: the problem's
    numpy mirror + the device tensors pinned across drains (mesh-placed
    over the sidecar's ``wl`` mesh when one is detected and the padded
    axis shards evenly)."""

    def __init__(self, mesh=None) -> None:
        self.lock = threading.Lock()
        self.kwargs: Optional[dict] = None
        self.meta: Optional[dict] = None
        self.epoch = -1
        self.device = DeviceResidentProblem(mesh=mesh)


def _resync(reason: str) -> tuple[dict, bytes]:
    return {"ok": False, "resync": reason}, b""


def _solve_mesh(sess):
    """The mesh this solve should run on, or None. BOTH kernels follow
    the session's resident placement: DeviceResidentProblem row-shards
    the workload axis for lean AND full tensors (the full kernel then
    composes its victim-search lane shard_map on top) when the padded
    axis divides the mesh and the live-row floor clears. A session
    whose tensors stayed replicated solves single-chip — routing a
    replicated resident problem through the mesh solver would silently
    re-place it every drain."""
    return sess.device.mesh if sess.device.mesh_placed else None


def _solve_resilient(server, sess, tensors, header: dict,
                     problem: SolverProblem, frame):
    """Mesh solve with the sidecar-side mesh -> single-chip fallback.

    Mirrors the in-process engine's chain: a mesh fault (device loss,
    SPMD compile abort) trips the SERVER mesh, re-seeds the session's
    resident state unsharded, and serves the same request single-chip —
    one slow request instead of a permanently failing sidecar. Counted
    in this process's solver_fallback_total{mesh_error}; never silent.
    Successful mesh solves report this process's mesh width gauge and
    shard-imbalance histogram, exactly like the in-process engine arm.
    """
    from kueue_oss_tpu.solver import meshutil

    mesh = _solve_mesh(sess)
    if mesh is not None:
        try:
            out = _solve_kernel(tensors, header, mesh)[0]
            metrics.solver_mesh_devices.set(
                value=meshutil.mesh_devices(mesh))
            # both drains row-shard the workload axis now, so both
            # observe the block-shard skew the interleaved session
            # layout is meant to flatten
            metrics.solver_shard_imbalance.observe(
                value=meshutil.shard_imbalance(
                    problem.wl_cqid, problem.n_cqs, mesh))
            return out
        except Exception:
            metrics.solver_fallback_total.inc("mesh_error")
            metrics.solver_mesh_devices.set(value=0)
            if server is not None:
                server.mesh = None
            sess.device.mesh = None
            sess.device.tensors = None  # force an unsharded re-seed
            tensors = sess.device.update(problem, frame,
                                         bool(header["full"]))
    out = _solve_kernel(tensors, header, None)[0]
    metrics.solver_mesh_devices.set(value=0)
    return out


# -- pod-scale (multi-host) sidecar mode -------------------------------------
#
# docs/SOLVER_PROTOCOL.md "Pod-scale sessions": after a jax.distributed
# bootstrap (KUEUE_SOLVER_COORDINATOR / SolverBackendConfig
# coordinator_* fields) the detected mesh spans EVERY process's
# devices, and SPMD solves over it are collective — each process must
# enter the same jitted computation in the same order. The wire
# protocol therefore cannot run independently per host: process 0 (the
# coordinator) owns the unix socket and re-broadcasts each stateless
# request to the followers, which sit in follower_solve_loop() and
# join every solve. Delta-sync sessions are per-process resident state
# and are NOT supported in this mode — a session frame answers an
# in-band error (run pod-scale clients with sessions_enabled=false).


def _bcast_bytes(payload: Optional[bytes]) -> bytes:
    """One coordinator->follower broadcast of a byte blob. Process 0
    passes the payload; followers pass None and receive it. Two
    collectives — the int64 length, then the body — because
    broadcast_one_to_all needs shape agreement on every process.

    The body travels as int32 WORDS (zero-padded to a word boundary,
    the length collective carries the exact byte count): the XLA:CPU
    gloo all-reduce widens sub-32-bit integers on the wire, so a uint8
    body lands int32-strided in the receiver's uint8 buffer — each
    payload byte followed by three zeros, truncated at n.
    """
    from jax.experimental import multihost_utils as mhu

    if payload is None:
        n = int(mhu.broadcast_one_to_all(np.zeros((), np.int64)))
        body = mhu.broadcast_one_to_all(np.zeros((n + 3) // 4, np.int32))
        return np.asarray(body).tobytes()[:n]
    mhu.broadcast_one_to_all(np.int64(len(payload)))
    padded = payload + b"\x00" * (-len(payload) % 4)
    mhu.broadcast_one_to_all(np.frombuffer(padded, np.int32))
    return payload


def _multihost_solve(header: dict, blob: bytes, mesh):
    """The collective body every process of the pod mesh runs for one
    stateless request: deserialize the (identically broadcast)
    problem, pad + row-shard it over the global mesh, solve, and
    materialize the plan host-side everywhere (host_replicated inside
    the sharded entry points). Returns (out tuple, array names)."""
    problem = deserialize_problem(header["meta"], blob)
    if header["full"]:
        from kueue_oss_tpu.solver.sharded import solve_backlog_full_sharded

        out = solve_backlog_full_sharded(
            problem, mesh, header["g_max"], header["h_max"],
            header["p_max"], fs_enabled=header["fs_enabled"])
        names = ["admitted", "opt", "admit_round", "parked",
                 "rounds", "usage", "wl_usage", "victim_reason"]
    else:
        from kueue_oss_tpu.solver.sharded import solve_backlog_sharded

        out = solve_backlog_sharded(problem, mesh)
        names = ["admitted", "opt", "admit_round", "parked",
                 "rounds", "usage"]
    return out, names


def follower_solve_loop(mesh_mode: Optional[str] = None) -> int:
    """Body for every non-coordinator process of a pod-scale sidecar:
    block on the coordinator's broadcast, join each collective solve,
    repeat until the shutdown op arrives. Returns the number of solves
    served (tests assert on it). Call AFTER
    meshutil.bootstrap_distributed — serve_multihost() wires both.

    A solve that raises does so DETERMINISTICALLY on every process of
    the pod (same program, same broadcast inputs), so the coordinator
    reports it in-band to its client while each follower swallows its
    own copy and stays in the loop — the broadcast order never skews.
    """
    from kueue_oss_tpu.solver.meshutil import detect_mesh

    mesh = detect_mesh(mesh_mode)
    if mesh is None:
        raise RuntimeError(
            "follower_solve_loop needs a mesh; a pod-scale sidecar "
            "without one cannot join collective solves")
    served = 0
    while True:
        header = json.loads(_bcast_bytes(None).decode("utf-8"))
        if header.get("op") == "shutdown":
            return served
        blob = _bcast_bytes(None)
        try:
            _multihost_solve(header, blob, mesh)
        except Exception:
            pass  # the coordinator's copy reports in-band
        served += 1


def serve_multihost(socket_path: str,
                    coordinator_address: Optional[str] = None,
                    num_processes: Optional[int] = None,
                    process_id: Optional[int] = None,
                    mesh_mode: Optional[str] = None,
                    **server_kwargs):
    """Pod-scale sidecar entry point.

    Bootstraps jax.distributed from the explicit coordinator args
    (SolverBackendConfig.coordinator_*) or KUEUE_SOLVER_COORDINATOR,
    then splits by rank: process 0 returns a ready ``SolverServer``
    whose stateless solves are re-broadcast to the pod (run
    serve_forever / serve_in_background on it; server_close() releases
    the followers); every other process enters follower_solve_loop and
    returns its served-solve count once the coordinator shuts down.
    """
    from kueue_oss_tpu.solver import meshutil

    n = meshutil.bootstrap_distributed(coordinator_address,
                                       num_processes, process_id)
    metrics.solver_multihost_processes.set(value=n)
    if meshutil.process_index() != 0:
        return follower_solve_loop(mesh_mode)
    server = SolverServer(socket_path, mesh_mode=mesh_mode,
                          **server_kwargs)
    server.multihost = n > 1
    return server


def _session_request(header: dict, blob: bytes,
                     server) -> tuple[dict, bytes]:
    """Handle a SYNC or DELTA frame against the server's session store."""
    t0 = time.perf_counter()
    kind = header["kind"]
    sid = str(header.get("sid", ""))
    tenant = str(header.get("tenant", ""))
    if kind == "sync":
        data = np.load(io.BytesIO(blob))
        kwargs = {name: (np.array(data[name]) if name in data else None)
                  for name in _ARRAY_FIELDS}
        meta = {k: int(v) for k, v in dict(header["meta"]).items()}
        want = header.get("checksum")
        if want is not None and state_checksum(kwargs, meta) != int(want):
            # a sync that decoded but doesn't match its own checksum is
            # transport corruption, not a session-state divergence
            return {"ok": False, "error": "sync frame checksum mismatch"
                    }, b""
        sess = (server.session(sid, tenant) if server is not None
                else _SidecarSession())
        with sess.lock:
            sess.kwargs, sess.meta = kwargs, meta
            sess.epoch = int(header.get("epoch", 0))
            problem = SolverProblem(**kwargs, **meta)
            frame = SessionFrame(epoch=sess.epoch,
                                 checksum=int(want or 0), delta=None)
            tensors = sess.device.update(problem, frame,
                                         bool(header["full"]))
            out = _solve_resilient(server, sess, tensors, header,
                                   problem, frame)
            arrays = compact_plan(out, bool(header["full"]))
            epoch = sess.epoch
    else:  # delta
        sess = (server.get_session(sid, tenant)
                if server is not None else None)
        if sess is None:
            return _resync("session_missing")
        with sess.lock:
            if sess.kwargs is None:
                return _resync("session_missing")
            if int(header["base_epoch"]) != sess.epoch:
                return _resync("epoch_mismatch")
            delta = deserialize_delta(header, blob)
            apply_delta(sess.kwargs, sess.meta, delta)
            # torn-tail kill point (docs/ROBUSTNESS.md): the delta's
            # dirty rows are applied but the epoch has not advanced and
            # the checksum is unverified — a SIGKILL here leaves (or, in
            # raise mode, simulates) torn resident session state that
            # the next drain must detect and heal through RESYNC
            persist_hooks.crash_if("sidecar_session_store")
            sess.epoch = delta.epoch
            if state_checksum(sess.kwargs, sess.meta) != delta.checksum:
                # resident state diverged from the host's: drop the
                # session so the client re-seeds it with a full SYNC
                server.drop_session(sid, tenant)
                return _resync("checksum_mismatch")
            problem = SolverProblem(**sess.kwargs, **sess.meta)
            frame = SessionFrame(epoch=delta.epoch,
                                 checksum=delta.checksum, delta=delta)
            tensors = sess.device.update(problem, frame,
                                         bool(header["full"]))
            out = _solve_resilient(server, sess, tensors, header,
                                   problem, frame)
            arrays = compact_plan(out, bool(header["full"]))
            epoch = sess.epoch
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    from kueue_oss_tpu.solver.meshutil import mesh_devices

    # advertise the sidecar's mesh width so a mesh-less client can
    # re-pad its next drains to a shardable axis (engine._pad_target);
    # without this, a CPU-only control plane would ship pow2+1 rows
    # forever and the accelerator sidecar could never shard them
    return {"ok": True, "compact": True, "epoch": epoch,
            "mesh_devices": mesh_devices(getattr(server, "mesh", None)
                                         if server is not None else None),
            "spans": _spans(header, t0)}, buf.getvalue()


def solve_request(header: dict, blob: bytes,
                  server=None) -> tuple[dict, bytes]:
    """Run one solve for a decoded request; returns (header, npz blob).

    Shared by the production handler and the chaos harness (which wraps
    it to corrupt/delay/drop the response deterministically). ``server``
    carries the session store for SYNC/DELTA frames; without it, SYNC
    degrades to a stateless solve and DELTA answers resync.

    With a solver farm attached (``server.farm``, see
    federation/farm.py), the whole solve body runs under the farm's
    weighted deficit-round-robin admission: the tenant id from the
    frame header picks the queue, and an over-quota tenant gets an
    in-band backpressure error instead of solver time — the client
    collapses that into ``SolverUnavailable`` and the engine degrades
    to host cycles, so a starved tenant never wedges.

    The optional ``trace_cycle`` header field is the host scheduler's
    cycle id: the response carries a ``spans`` list timing the sidecar
    solve, tagged with that cycle, so the engine can merge it into the
    host Tracer's Chrome-trace export as one timeline.
    """
    farm = getattr(server, "farm", None)
    if farm is not None:
        resp, out = farm.run(
            str(header.get("tenant", "")),
            lambda: _solve_request_body(header, blob, server))
        if resp.get("ok"):
            # echo the DRR grant-wait so the client's engine can ledger
            # it per drain (solver_farm_grant_wait_seconds carries the
            # same value farm-side)
            from kueue_oss_tpu.federation.farm import last_grant_wait_s

            resp.setdefault("grant_wait_ms",
                            round(last_grant_wait_s() * 1e3, 3))
        return resp, out
    return _solve_request_body(header, blob, server)


def _solve_request_body(header: dict, blob: bytes,
                        server=None) -> tuple[dict, bytes]:
    kind = header.get("kind", "solve")
    if kind in ("sync", "delta"):
        if server is not None and getattr(server, "multihost", False):
            # sessions are per-process resident state; the pod-scale
            # coordinator serves stateless solves only (run the client
            # with sessions_enabled=false against this sidecar)
            return {"ok": False, "error": "delta-sync sessions are "
                    "unsupported in multihost mode"}, b""
        if kind == "delta" and server is None:
            return _resync("session_unsupported")
        return _session_request(header, blob, server)
    t0 = time.perf_counter()
    if (server is not None and getattr(server, "multihost", False)
            and getattr(server, "mesh", None) is not None):
        # collective pod solve: replay the request to the followers,
        # then join the same SPMD computation they run
        with server._multihost_lock:
            _bcast_bytes(json.dumps(header).encode("utf-8"))
            _bcast_bytes(blob)
            out, names = _multihost_solve(header, blob, server.mesh)
        buf = io.BytesIO()
        np.savez(buf, **{n: np.asarray(v) for n, v in zip(names, out)})
        return {"ok": True, "names": names,
                "spans": _spans(header, t0)}, buf.getvalue()
    problem = deserialize_problem(header["meta"], blob)
    if header["full"]:
        from kueue_oss_tpu.solver.full_kernels import to_device_full

        tensors = to_device_full(problem)
    else:
        from kueue_oss_tpu.solver.kernels import to_device

        tensors = to_device(problem)
    out, names = _solve_kernel(tensors, header)
    buf = io.BytesIO()
    np.savez(buf, **{n: np.asarray(v) for n, v in zip(names, out)})
    return {"ok": True, "names": names,
            "spans": _spans(header, t0)}, buf.getvalue()


def respond(sock: socket.socket, header: dict, blob: bytes,
            server=None) -> None:
    """Solve a decoded request and reply on ``sock``; solve-side
    exceptions are reported in-band, a vanished client is ignored.
    Shared by the production handler and the chaos harness's healthy
    tail, so the two cannot drift apart."""
    try:
        resp_header, resp_blob = solve_request(header, blob, server)
    except Exception as e:  # report in-band; don't wedge the thread
        resp_header, resp_blob = {"ok": False, "error": repr(e)}, b""
    try:
        _send(sock, resp_header, resp_blob)
    except OSError:
        return  # client gave up (deadline) mid-response


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        try:
            # the read is deadline-bounded: a client that stalls
            # mid-frame must not pin this handler thread forever (the
            # server joins handler threads on close)
            header, blob = _recv(
                self.request, self.server.max_frame_bytes,
                deadline=time.monotonic() + self.server.read_timeout_s)
        except (ConnectionError, TimeoutError):
            return  # covers SolverProtocolError: drop the bad request
        respond(self.request, header, blob, self.server)


class SolverServer(socketserver.ThreadingUnixStreamServer):
    """The sidecar process body: `SolverServer(path).serve_forever()`."""

    allow_reuse_address = True
    # handler threads must not block process exit: a wedged client
    # connection would otherwise hang server_close() (block_on_close
    # joins non-daemon handler threads)
    daemon_threads = True

    def __init__(self, socket_path: str,
                 max_frame_bytes: Optional[int] = None,
                 read_timeout_s: Optional[float] = None,
                 max_sessions: Optional[int] = None,
                 mesh_mode: Optional[str] = None,
                 mesh_min_workloads: int = 1024) -> None:
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        super().__init__(socket_path, _Handler)
        self.socket_path = socket_path
        self.max_frame_bytes = (max_frame_bytes if max_frame_bytes
                                is not None else default_max_frame_bytes())
        self.read_timeout_s = (read_timeout_s if read_timeout_s
                               is not None else default_timeout_s())
        #: delta-sync session store ((tenant, sid) -> _SidecarSession),
        #: LRU-capped so abandoned sessions can't accumulate resident
        #: problems. The tenant component namespaces the table: two
        #: control planes reusing a sid can never read each other's
        #: resident state (docs/FEDERATION.md).
        self.sessions: dict[tuple[str, str], _SidecarSession] = {}
        self._sessions_lock = threading.Lock()
        self.max_sessions = (max(1, int(max_sessions))
                             if max_sessions is not None
                             else default_max_sessions())
        #: optional federation/farm.py FarmScheduler; when set, every
        #: decoded request is admitted through its per-tenant DRR queue
        self.farm = None
        #: sidecar mesh detection (solver/meshutil.py): sessions place
        #: their resident lean tensors over the mesh and solve via the
        #: sharded SPMD drain; full solves lane-shard. KUEUE_SOLVER_MESH
        #: / mesh_mode governs it exactly like the in-process engine.
        try:
            from kueue_oss_tpu.solver.meshutil import detect_mesh

            self.mesh = detect_mesh(mesh_mode)
        except Exception:
            self.mesh = None
        #: problems narrower than this solve single-chip even with a
        #: mesh (the mesh is the large-backlog path)
        self.mesh_min_workloads = int(mesh_min_workloads)
        #: pod-scale coordinator mode (serve_multihost sets it): every
        #: stateless solve is re-broadcast to the follower processes
        #: and solved collectively over the global mesh; session
        #: frames answer an in-band error. The lock serializes the
        #: broadcast+solve pair — handler threads must not interleave
        #: collectives or the followers would decode skewed frames.
        self.multihost = False
        self._multihost_lock = threading.Lock()

    def session(self, sid: str, tenant: str = "") -> _SidecarSession:
        key = (tenant, sid)
        with self._sessions_lock:
            sess = self.sessions.pop(key, None)
            if sess is None:
                sess = _SidecarSession(mesh=self.mesh)
                sess.device.mesh_min_rows = self.mesh_min_workloads
            self.sessions[key] = sess  # re-insert = LRU touch
            while len(self.sessions) > self.max_sessions:
                self.sessions.pop(next(iter(self.sessions)))
                metrics.solver_session_evictions_total.inc("lru")
            return sess

    def get_session(self, sid: str,
                    tenant: str = "") -> Optional[_SidecarSession]:
        key = (tenant, sid)
        with self._sessions_lock:
            sess = self.sessions.pop(key, None)
            if sess is not None:
                self.sessions[key] = sess
            return sess

    def drop_session(self, sid: str, tenant: str = "") -> None:
        with self._sessions_lock:
            self.sessions.pop((tenant, sid), None)

    def drop_tenant(self, tenant: str) -> int:
        """Evict every resident session of one tenant (farm-side chaos /
        tenant decommission); the tenant's next frame answers
        ``resync: session_missing`` and its client re-seeds with a full
        SYNC — counted, never silent. Returns the eviction count."""
        with self._sessions_lock:
            victims = [k for k in self.sessions if k[0] == tenant]
            for k in victims:
                self.sessions.pop(k, None)
                metrics.solver_session_evictions_total.inc(
                    "tenant_evicted")
            return len(victims)

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t

    def server_close(self) -> None:
        if self.multihost:
            self.multihost = False
            try:
                with self._multihost_lock:
                    _bcast_bytes(json.dumps({"op": "shutdown"}).encode())
            except Exception:
                pass  # followers already gone; don't wedge shutdown
        super().server_close()


class _ClientSession:
    """Client-side view of one sidecar session (per engine kernel kind)."""

    __slots__ = ("sid", "acked_epoch")

    def __init__(self) -> None:
        self.sid = os.urandom(8).hex()
        self.acked_epoch = -1


class _ResyncRequested(Exception):
    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


class SolverClient:
    """Engine-side stub: SolverEngine(remote=SolverClient(path)).

    Every ``solve`` runs under a per-call deadline (``timeout_s``) with
    up to ``max_retries`` re-attempts on transport faults. Each attempt
    opens a fresh connection (automatic reconnect after a sidecar
    restart) and backs off exponentially with seeded jitter between
    attempts. Exhaustion — deadline or retries — raises
    ``SolverUnavailable`` for the engine's circuit breaker.

    With a ``frame`` (a delta-session SessionFrame from the engine's
    HostDeltaSession), the request goes out as a DELTA when the sidecar
    is known to hold the frame's base epoch, else a full SYNC; an
    in-band resync answer falls back to a SYNC within the same call
    (once — a second resync demand is a backend fault). Duplicate
    delivery is safe: the sidecar's epoch guard rejects an already-
    applied delta with a resync, which the SYNC fallback absorbs.

    ``clock``/``sleep`` are injectable so the chaos tests drive the
    deadline/backoff logic without real waiting.
    """

    #: engines check this before routing session frames here
    supports_sessions = True

    def __init__(self, socket_path: str,
                 timeout_s: Optional[float] = None,
                 max_retries: int = 2,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 max_frame_bytes: Optional[int] = None,
                 jitter_seed: int = 0,
                 clock=time.monotonic,
                 sleep=time.sleep,
                 sessions: Optional[bool] = None,
                 tenant: str = "") -> None:
        self.socket_path = socket_path
        #: federation tenant id; rides EVERY frame header so the farm's
        #: DRR scheduler can bill the request and the sidecar keys the
        #: session under (tenant, sid) — empty = single-tenant sidecar
        self.tenant = str(tenant)
        self.timeout_s = (timeout_s if timeout_s is not None
                          else default_timeout_s())
        self.max_retries = max(0, int(max_retries))
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.max_frame_bytes = (max_frame_bytes if max_frame_bytes
                                is not None else default_max_frame_bytes())
        self._rng = random.Random(jitter_seed)
        self._clock = clock
        self._sleep = sleep
        #: host cycle id shipped in the next request's header (set by
        #: SolverEngine before each solve) so sidecar spans come back
        #: tagged with the cycle they served
        self.trace_cycle: Optional[int] = None
        #: sidecar spans from the LAST successful solve's response header
        self.last_spans: list[dict] = []
        #: the farm's DRR grant-wait echoed in the LAST successful
        #: response (ms; 0 = dedicated sidecar or farm idle)
        self.last_grant_wait_ms = 0.0
        #: the sidecar's advertised mesh width (session responses);
        #: the engine aligns its pad target to it so the sidecar can
        #: shard the resident problem (0 = unknown / no sidecar mesh)
        self.remote_mesh_devices = 0
        if sessions is None:
            sessions = os.environ.get("KUEUE_SOLVER_SESSIONS") != "0"
        self.use_sessions = bool(sessions)
        self._sessions: dict[str, _ClientSession] = {}
        #: wire accounting for bench/diagnostics: bytes per frame kind
        #: and the last successful frame's (kind, bytes)
        self.bytes_by_kind: dict[str, int] = {}
        self.frames_by_kind: dict[str, int] = {}
        self.last_frame: Optional[tuple[str, int]] = None

    @classmethod
    def from_config(cls, cfg) -> "SolverClient":
        """Build from a config.SolverBackendConfig."""
        if cfg.socket_path is None:
            raise ValueError("solver.socketPath is required for a remote "
                             "solver backend")
        return cls(cfg.socket_path,
                   timeout_s=cfg.timeout_seconds,
                   max_retries=cfg.max_retries,
                   backoff_base_s=cfg.retry_backoff_base_seconds,
                   backoff_max_s=cfg.retry_backoff_max_seconds,
                   max_frame_bytes=cfg.max_frame_bytes,
                   sessions=getattr(cfg, "sessions_enabled", None),
                   tenant=getattr(cfg, "tenant", "")
                   or os.environ.get("KUEUE_SOLVER_TENANT", ""))

    # -- payload builders --------------------------------------------------

    def _base_params(self, full: bool, g_max: int, h_max: int,
                     p_max: int, fs_enabled: bool) -> dict:
        params = {"full": full, "g_max": g_max, "h_max": h_max,
                  "p_max": p_max, "fs_enabled": fs_enabled}
        if self.tenant:
            params["tenant"] = self.tenant
        if self.trace_cycle is not None:
            params["trace_cycle"] = int(self.trace_cycle)
        return params

    def _build_payload(self, mode: str, problem: SolverProblem,
                       params: dict, frame, st) -> tuple[dict, bytes]:
        if mode == "legacy":
            meta, blob = serialize_problem(problem)
            header = {**params, "meta": meta}
        elif mode == "delta":
            dh, blob = serialize_delta(frame.delta)
            header = {**params, **dh, "kind": "delta", "sid": st.sid}
        else:  # sync / resync
            meta, blob = serialize_problem(problem)
            header = {**params, "meta": meta, "kind": "sync",
                      "sid": st.sid, "epoch": frame.epoch,
                      "checksum": frame.checksum}
        # enforce the frame guard on our OWN request too: a server-side
        # rejection of an oversized frame shows up as a reset/EOF and
        # would be misread as a transient connection fault and retried
        # (deterministically) every drain
        n_frame = len(json.dumps(header).encode()) + len(blob)
        if n_frame > self.max_frame_bytes:
            raise SolverUnavailable(
                f"request frame of {n_frame} bytes exceeds the "
                f"{self.max_frame_bytes}-byte limit (problem too large "
                "for the remote backend)")
        return header, blob

    def _account(self, kind: str, header: dict, blob: bytes) -> None:
        n = len(json.dumps(header).encode()) + len(blob)
        self.bytes_by_kind[kind] = self.bytes_by_kind.get(kind, 0) + n
        self.frames_by_kind[kind] = self.frames_by_kind.get(kind, 0) + 1
        self.last_frame = (kind, n)
        metrics.solver_session_frames_total.inc(kind)
        metrics.solver_session_bytes_total.inc(kind, by=float(n))
        # devtel transfer ledger: request frames are direction "tx"
        from kueue_oss_tpu.obs import devtel

        devtel.collector.note_wire("remote", self.tenant, n)

    # -- the call ----------------------------------------------------------

    def solve(self, problem: SolverProblem, *, full: bool,
              g_max: int = 1, h_max: int = 32, p_max: int = 128,
              fs_enabled: bool = False, frame=None,
              session_key: str = "default"):
        params = self._base_params(full, g_max, h_max, p_max, fs_enabled)
        self.last_spans = []
        self.last_grant_wait_ms = 0.0
        st = None
        mode = "legacy"
        if frame is not None and self.use_sessions:
            st = self._sessions.setdefault(session_key, _ClientSession())
            mode = ("delta" if (frame.delta is not None
                                and st.acked_epoch
                                == frame.delta.base_epoch)
                    else "sync")
        header, blob = self._build_payload(mode, problem, params,
                                           frame, st)
        deadline = self._clock() + self.timeout_s
        attempt = 0
        resynced = False
        last_err: Optional[BaseException] = None
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                metrics.solver_deadline_exceeded_total.inc()
                raise SolverUnavailable(
                    f"solver call deadline ({self.timeout_s}s) exhausted "
                    f"after {attempt} attempt(s): {last_err!r}"
                ) from last_err
            try:
                out = self._solve_once(header, blob, remaining,
                                       problem, params)
                if st is not None:
                    st.acked_epoch = frame.epoch
                self._account("resync" if resynced else mode,
                              header, blob)
                ctl = resilience.controller
                if ctl.active(resilience.FEDERATION, "farm_unavailable"):
                    ctl.report(resilience.FEDERATION, "farm_unavailable",
                               False, reason="solver farm answered; "
                                             "dedicated lane restored")
                return out
            except _ResyncRequested as e:
                # the sidecar lost (or never had) our session state:
                # fall back to a full SYNC within this same call. Does
                # not count against the transport retry budget — the
                # sidecar is demonstrably alive.
                metrics.solver_resync_total.inc(e.reason)
                if mode != "delta" or resynced:
                    raise SolverUnavailable(
                        f"sidecar demanded resync twice: {e.reason}")
                resynced = True
                mode = "sync"
                header, blob = self._build_payload(
                    "sync", problem, params, frame, st)
                continue
            except (TimeoutError, socket.timeout) as e:
                last_err = e
                metrics.solver_remote_failures_total.inc("timeout")
            except SolverProtocolError as e:
                last_err = e
                metrics.solver_remote_failures_total.inc("protocol")
            except OSError as e:  # conn refused/reset, missing socket, …
                last_err = e
                metrics.solver_remote_failures_total.inc("connection")
            attempt += 1
            if attempt > self.max_retries:
                raise SolverUnavailable(
                    f"solver call failed after {attempt} attempt(s): "
                    f"{last_err!r}") from last_err
            metrics.solver_remote_retries_total.inc()
            delay = min(self.backoff_base_s * (2 ** (attempt - 1)),
                        self.backoff_max_s)
            delay += self._rng.uniform(0, delay)  # full jitter
            delay = min(delay, max(0.0, deadline - self._clock()))
            if delay > 0:
                self._sleep(delay)

    def _solve_once(self, header: dict, blob: bytes, budget_s: float,
                    problem: SolverProblem, params: dict):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(budget_s)  # bounds connect and the send as ops
        op_deadline = self._clock() + budget_s
        try:
            sock.connect(self.socket_path)
            _send(sock, header, blob)
            # the WHOLE response read shares one deadline — a slow-drip
            # peer must not reset the timer per chunk
            resp, body = _recv(sock, self.max_frame_bytes,
                               deadline=op_deadline, clock=self._clock)
        finally:
            sock.close()
        if not resp.get("ok", False):
            if isinstance(resp.get("resync"), str):
                raise _ResyncRequested(resp["resync"])
            # the sidecar is up but the solve itself failed; a retry
            # would deterministically fail again, so don't burn the
            # deadline on it
            metrics.solver_remote_failures_total.inc("server")
            err = str(resp.get("error", "unknown"))
            if "backpressure" in err:
                # the farm is throttling this whole control plane: the
                # federation ladder degrades past the farm rung and the
                # engine's breaker walks us down to host cycles
                resilience.controller.report(
                    resilience.FEDERATION, "farm_unavailable", True,
                    reason=f"farm refused the solve: {err}")
            raise SolverUnavailable(
                f"solver sidecar reported failure: {err}")
        spans = resp.get("spans")
        self.last_spans = spans if isinstance(spans, list) else []
        try:
            self.last_grant_wait_ms = float(
                resp.get("grant_wait_ms", 0.0) or 0.0)
        except (TypeError, ValueError):
            self.last_grant_wait_ms = 0.0
        try:
            self.remote_mesh_devices = int(resp.get("mesh_devices", 0))
        except (TypeError, ValueError):
            self.remote_mesh_devices = 0
        try:
            data = np.load(io.BytesIO(body))
            if resp.get("compact"):
                return expand_compact_plan(
                    data, problem.wl_cqid.shape[0],
                    bool(params["full"]), int(params["g_max"]))
            names = resp.get("names")
            if not isinstance(names, list) or not names:
                raise SolverProtocolError(
                    "response header carries no names")
            return tuple(data[n] for n in names)
        except SolverProtocolError:
            raise
        except Exception as e:  # zipfile/np decode errors on corruption
            raise SolverProtocolError(
                f"undecodable plan payload: {e!r}") from e
