"""Process-separated solver service (the gRPC-sidecar analog).

SURVEY.md §2.4: the reference's control plane is one Go process; the
TPU-native design adds a sidecar carrying the CQ×FlavorResource usage
tensor + pending-workload request tensor to a separate JAX solver
process, so the control plane never blocks on device compilation and
the solver can sit on the TPU host while the scheduler runs elsewhere.

Wire contract (BASELINE.json: tensor export ≙ Cache.Snapshot, plan
import ≙ assume path):

  request  = header JSON {caps, fs_enabled, full} + npz(SolverProblem arrays)
  response = header JSON {rounds}             + npz(plan arrays)

Transport is a length-prefixed unix-domain socket (protocol framing is
what a gRPC stub would generate; no proto toolchain is assumed in the
image). The client side plugs into SolverEngine via `remote=`: the
engine still exports, verifies, and commits — only the solve itself
crosses the process boundary.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import socket
import socketserver
import struct
import threading
from typing import Optional

import numpy as np

from kueue_oss_tpu.solver.tensors import SolverProblem

#: SolverProblem fields shipped as arrays; the rest go in the header
_ARRAY_FIELDS = [
    f.name for f in dataclasses.fields(SolverProblem)
    if f.name not in ("fr_list", "node_names", "cq_names", "wl_keys",
                      "cq_option_flavors", "cq_resource_group", "scale",
                      "n_resources", "ts_evict_base", "admit_rank_base")
]
_META_FIELDS = ["n_resources", "ts_evict_base", "admit_rank_base", "scale"]


def _send(sock: socket.socket, header: dict, blob: bytes) -> None:
    h = json.dumps(header).encode()
    sock.sendall(struct.pack(">II", len(h), len(blob)))
    sock.sendall(h)
    sock.sendall(blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv(sock: socket.socket) -> tuple[dict, bytes]:
    hlen, blen = struct.unpack(">II", _recv_exact(sock, 8))
    header = json.loads(_recv_exact(sock, hlen))
    return header, _recv_exact(sock, blen)


def serialize_problem(p: SolverProblem) -> tuple[dict, bytes]:
    arrays = {}
    for name in _ARRAY_FIELDS:
        v = getattr(p, name)
        if v is not None:
            arrays[name] = np.asarray(v)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    meta = {name: getattr(p, name) for name in _META_FIELDS}
    return meta, buf.getvalue()


def deserialize_problem(meta: dict, blob: bytes) -> SolverProblem:
    data = np.load(io.BytesIO(blob))
    kwargs = {name: (data[name] if name in data else None)
              for name in _ARRAY_FIELDS}
    kwargs.update(meta)
    return SolverProblem(**kwargs)


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        try:
            header, blob = _recv(self.request)
        except ConnectionError:
            return
        problem = deserialize_problem(header["meta"], blob)
        if header["full"]:
            from kueue_oss_tpu.solver.full_kernels import (
                solve_backlog_full,
                to_device_full,
            )

            out = solve_backlog_full(
                to_device_full(problem), header["g_max"],
                header["h_max"], header["p_max"],
                fs_enabled=header["fs_enabled"])
            names = ["admitted", "opt", "admit_round", "parked",
                     "rounds", "usage", "wl_usage", "victim_reason"]
        else:
            from kueue_oss_tpu.solver.kernels import (
                solve_backlog,
                to_device,
            )

            out = solve_backlog(to_device(problem))
            names = ["admitted", "opt", "admit_round", "parked",
                     "rounds", "usage"]
        buf = io.BytesIO()
        np.savez(buf, **{n: np.asarray(v) for n, v in zip(names, out)})
        _send(self.request, {"ok": True, "names": names}, buf.getvalue())


class SolverServer(socketserver.ThreadingUnixStreamServer):
    """The sidecar process body: `SolverServer(path).serve_forever()`."""

    allow_reuse_address = True

    def __init__(self, socket_path: str) -> None:
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        super().__init__(socket_path, _Handler)
        self.socket_path = socket_path

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


class SolverClient:
    """Engine-side stub: SolverEngine(remote=SolverClient(path))."""

    def __init__(self, socket_path: str, timeout_s: float = 600.0) -> None:
        self.socket_path = socket_path
        self.timeout_s = timeout_s

    def solve(self, problem: SolverProblem, *, full: bool,
              g_max: int = 1, h_max: int = 32, p_max: int = 128,
              fs_enabled: bool = False):
        meta, blob = serialize_problem(problem)
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout_s)
        try:
            sock.connect(self.socket_path)
            _send(sock, {"meta": meta, "full": full, "g_max": g_max,
                         "h_max": h_max, "p_max": p_max,
                         "fs_enabled": fs_enabled}, blob)
            header, body = _recv(sock)
        finally:
            sock.close()
        data = np.load(io.BytesIO(body))
        return tuple(data[n] for n in header["names"])
