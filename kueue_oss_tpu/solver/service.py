"""Process-separated solver service (the gRPC-sidecar analog).

SURVEY.md §2.4: the reference's control plane is one Go process; the
TPU-native design adds a sidecar carrying the CQ×FlavorResource usage
tensor + pending-workload request tensor to a separate JAX solver
process, so the control plane never blocks on device compilation and
the solver can sit on the TPU host while the scheduler runs elsewhere.

Wire contract (BASELINE.json: tensor export ≙ Cache.Snapshot, plan
import ≙ assume path):

  request  = header JSON {caps, fs_enabled, full} + npz(SolverProblem arrays)
  response = header JSON {rounds}             + npz(plan arrays)

Transport is a length-prefixed unix-domain socket (protocol framing is
what a gRPC stub would generate; no proto toolchain is assumed in the
image). The client side plugs into SolverEngine via `remote=`: the
engine still exports, verifies, and commits — only the solve itself
crosses the process boundary.

Resilience (this layer's failure contract):

- a truncated frame, EOF mid-frame, undecodable header/npz, or a frame
  above ``max_frame_bytes`` raises ``SolverProtocolError`` — never a
  confusing struct/zipfile error, and never an allocation sized by an
  attacker-controlled length prefix;
- ``SolverClient.solve`` runs under a per-call deadline with bounded
  retries (exponential backoff + seeded jitter, fresh connection per
  attempt = automatic reconnect) and collapses exhaustion into
  ``SolverUnavailable`` for the engine/breaker to act on;
- the server catches solve-side exceptions and reports them in-band
  (``{"ok": false}``) so one bad request cannot wedge a handler thread.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Optional

import numpy as np

from kueue_oss_tpu import metrics
from kueue_oss_tpu.solver.resilience import SolverUnavailable
from kueue_oss_tpu.solver.tensors import SolverProblem

#: SolverProblem fields shipped as arrays; the rest go in the header
_ARRAY_FIELDS = [
    f.name for f in dataclasses.fields(SolverProblem)
    if f.name not in ("fr_list", "node_names", "cq_names", "wl_keys",
                      "cq_option_flavors", "cq_resource_group", "scale",
                      "n_resources", "ts_evict_base", "admit_rank_base")
]
_META_FIELDS = ["n_resources", "ts_evict_base", "admit_rank_base", "scale"]


class SolverProtocolError(ConnectionError):
    """Garbled wire state: short read/EOF mid-frame, oversized frame, or
    an undecodable header/payload. Distinct from plain ConnectionError so
    callers can tell a *misbehaving* peer from an absent one."""


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def default_timeout_s() -> float:
    """Per-call deadline; KUEUE_SOLVER_TIMEOUT_S overrides the 600 s
    default (the pre-robustness hardcode) without a code change."""
    return _env_float("KUEUE_SOLVER_TIMEOUT_S", 600.0)


def default_max_frame_bytes() -> int:
    """Frame-size guard; KUEUE_SOLVER_MAX_FRAME_MB overrides 256 MiB.
    Checked BEFORE allocating, on both sides of the wire."""
    return int(_env_float("KUEUE_SOLVER_MAX_FRAME_MB", 256.0) * (1 << 20))


def _send(sock: socket.socket, header: dict, blob: bytes) -> None:
    h = json.dumps(header).encode()
    sock.sendall(struct.pack(">II", len(h), len(blob)))
    sock.sendall(h)
    sock.sendall(blob)


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None,
                clock=time.monotonic) -> bytes:
    """Read exactly n bytes; with ``deadline`` (absolute, in ``clock``
    units) the whole read is bounded, not just each recv: a peer
    dripping one byte per op-timeout would otherwise reset the clock on
    every chunk and stall far past the caller's budget."""
    buf = b""
    while len(buf) < n:
        if deadline is not None:
            remaining = deadline - clock()
            if remaining <= 0:
                raise TimeoutError(
                    f"deadline exhausted mid-frame: got {len(buf)} of "
                    f"{n} bytes")
            sock.settimeout(remaining)
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise SolverProtocolError(
                f"peer closed mid-frame: got {len(buf)} of {n} bytes")
        buf += chunk
    return buf


def _recv(sock: socket.socket,
          max_frame_bytes: Optional[int] = None,
          deadline: Optional[float] = None,
          clock=time.monotonic) -> tuple[dict, bytes]:
    if max_frame_bytes is None:
        max_frame_bytes = default_max_frame_bytes()
    hlen, blen = struct.unpack(
        ">II", _recv_exact(sock, 8, deadline, clock))
    if hlen + blen > max_frame_bytes:
        # reject before allocating: the length prefix is peer-controlled
        raise SolverProtocolError(
            f"frame of {hlen + blen} bytes exceeds the "
            f"{max_frame_bytes}-byte limit")
    try:
        header = json.loads(_recv_exact(sock, hlen, deadline, clock))
    except (ValueError, UnicodeDecodeError) as e:
        raise SolverProtocolError(f"undecodable frame header: {e}") from e
    if not isinstance(header, dict):
        raise SolverProtocolError("frame header is not a JSON object")
    return header, _recv_exact(sock, blen, deadline, clock)


def serialize_problem(p: SolverProblem) -> tuple[dict, bytes]:
    arrays = {}
    for name in _ARRAY_FIELDS:
        v = getattr(p, name)
        if v is not None:
            arrays[name] = np.asarray(v)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    meta = {name: getattr(p, name) for name in _META_FIELDS}
    return meta, buf.getvalue()


def deserialize_problem(meta: dict, blob: bytes) -> SolverProblem:
    data = np.load(io.BytesIO(blob))
    kwargs = {name: (data[name] if name in data else None)
              for name in _ARRAY_FIELDS}
    kwargs.update(meta)
    return SolverProblem(**kwargs)


def solve_request(header: dict, blob: bytes) -> tuple[dict, bytes]:
    """Run one solve for a decoded request; returns (header, npz blob).

    Shared by the production handler and the chaos harness (which wraps
    it to corrupt/delay/drop the response deterministically).

    The optional ``trace_cycle`` header field is the host scheduler's
    cycle id: the response carries a ``spans`` list timing the sidecar
    solve, tagged with that cycle, so the engine can merge it into the
    host Tracer's Chrome-trace export as one timeline.
    """
    t0 = time.perf_counter()
    problem = deserialize_problem(header["meta"], blob)
    if header["full"]:
        from kueue_oss_tpu.solver.full_kernels import (
            solve_backlog_full,
            to_device_full,
        )

        out = solve_backlog_full(
            to_device_full(problem), header["g_max"],
            header["h_max"], header["p_max"],
            fs_enabled=header["fs_enabled"])
        names = ["admitted", "opt", "admit_round", "parked",
                 "rounds", "usage", "wl_usage", "victim_reason"]
    else:
        from kueue_oss_tpu.solver.kernels import (
            solve_backlog,
            to_device,
        )

        out = solve_backlog(to_device(problem))
        names = ["admitted", "opt", "admit_round", "parked",
                 "rounds", "usage"]
    buf = io.BytesIO()
    np.savez(buf, **{n: np.asarray(v) for n, v in zip(names, out)})
    span_args = {"full": bool(header["full"])}
    if header.get("trace_cycle") is not None:
        span_args["cycle"] = header["trace_cycle"]
    spans = [{"name": "sidecar_solve",
              "dur_us": int((time.perf_counter() - t0) * 1e6),
              "args": span_args}]
    return {"ok": True, "names": names, "spans": spans}, buf.getvalue()


def respond(sock: socket.socket, header: dict, blob: bytes) -> None:
    """Solve a decoded request and reply on ``sock``; solve-side
    exceptions are reported in-band, a vanished client is ignored.
    Shared by the production handler and the chaos harness's healthy
    tail, so the two cannot drift apart."""
    try:
        resp_header, resp_blob = solve_request(header, blob)
    except Exception as e:  # report in-band; don't wedge the thread
        resp_header, resp_blob = {"ok": False, "error": repr(e)}, b""
    try:
        _send(sock, resp_header, resp_blob)
    except OSError:
        return  # client gave up (deadline) mid-response


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        try:
            # the read is deadline-bounded: a client that stalls
            # mid-frame must not pin this handler thread forever (the
            # server joins handler threads on close)
            header, blob = _recv(
                self.request, self.server.max_frame_bytes,
                deadline=time.monotonic() + self.server.read_timeout_s)
        except (ConnectionError, TimeoutError):
            return  # covers SolverProtocolError: drop the bad request
        respond(self.request, header, blob)


class SolverServer(socketserver.ThreadingUnixStreamServer):
    """The sidecar process body: `SolverServer(path).serve_forever()`."""

    allow_reuse_address = True
    # handler threads must not block process exit: a wedged client
    # connection would otherwise hang server_close() (block_on_close
    # joins non-daemon handler threads)
    daemon_threads = True

    def __init__(self, socket_path: str,
                 max_frame_bytes: Optional[int] = None,
                 read_timeout_s: Optional[float] = None) -> None:
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        super().__init__(socket_path, _Handler)
        self.socket_path = socket_path
        self.max_frame_bytes = (max_frame_bytes if max_frame_bytes
                                is not None else default_max_frame_bytes())
        self.read_timeout_s = (read_timeout_s if read_timeout_s
                               is not None else default_timeout_s())

    def serve_in_background(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        return t


class SolverClient:
    """Engine-side stub: SolverEngine(remote=SolverClient(path)).

    Every ``solve`` runs under a per-call deadline (``timeout_s``) with
    up to ``max_retries`` re-attempts on transport faults. Each attempt
    opens a fresh connection (automatic reconnect after a sidecar
    restart) and backs off exponentially with seeded jitter between
    attempts. Exhaustion — deadline or retries — raises
    ``SolverUnavailable`` for the engine's circuit breaker.

    ``clock``/``sleep`` are injectable so the chaos tests drive the
    deadline/backoff logic without real waiting.
    """

    def __init__(self, socket_path: str,
                 timeout_s: Optional[float] = None,
                 max_retries: int = 2,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 max_frame_bytes: Optional[int] = None,
                 jitter_seed: int = 0,
                 clock=time.monotonic,
                 sleep=time.sleep) -> None:
        self.socket_path = socket_path
        self.timeout_s = (timeout_s if timeout_s is not None
                          else default_timeout_s())
        self.max_retries = max(0, int(max_retries))
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.max_frame_bytes = (max_frame_bytes if max_frame_bytes
                                is not None else default_max_frame_bytes())
        self._rng = random.Random(jitter_seed)
        self._clock = clock
        self._sleep = sleep
        #: host cycle id shipped in the next request's header (set by
        #: SolverEngine before each solve) so sidecar spans come back
        #: tagged with the cycle they served
        self.trace_cycle: Optional[int] = None
        #: sidecar spans from the LAST successful solve's response header
        self.last_spans: list[dict] = []

    @classmethod
    def from_config(cls, cfg) -> "SolverClient":
        """Build from a config.SolverBackendConfig."""
        if cfg.socket_path is None:
            raise ValueError("solver.socketPath is required for a remote "
                             "solver backend")
        return cls(cfg.socket_path,
                   timeout_s=cfg.timeout_seconds,
                   max_retries=cfg.max_retries,
                   backoff_base_s=cfg.retry_backoff_base_seconds,
                   backoff_max_s=cfg.retry_backoff_max_seconds,
                   max_frame_bytes=cfg.max_frame_bytes)

    def solve(self, problem: SolverProblem, *, full: bool,
              g_max: int = 1, h_max: int = 32, p_max: int = 128,
              fs_enabled: bool = False):
        meta, blob = serialize_problem(problem)
        header = {"meta": meta, "full": full, "g_max": g_max,
                  "h_max": h_max, "p_max": p_max,
                  "fs_enabled": fs_enabled}
        if self.trace_cycle is not None:
            header["trace_cycle"] = int(self.trace_cycle)
        self.last_spans = []
        # enforce the frame guard on our OWN request too: a server-side
        # rejection of an oversized frame shows up as a reset/EOF and
        # would be misread as a transient connection fault and retried
        # (deterministically) every drain
        n_frame = len(json.dumps(header).encode()) + len(blob)
        if n_frame > self.max_frame_bytes:
            raise SolverUnavailable(
                f"request frame of {n_frame} bytes exceeds the "
                f"{self.max_frame_bytes}-byte limit (problem too large "
                "for the remote backend)")
        deadline = self._clock() + self.timeout_s
        attempt = 0
        last_err: Optional[BaseException] = None
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                metrics.solver_deadline_exceeded_total.inc()
                raise SolverUnavailable(
                    f"solver call deadline ({self.timeout_s}s) exhausted "
                    f"after {attempt} attempt(s): {last_err!r}"
                ) from last_err
            try:
                return self._solve_once(header, blob, remaining)
            except (TimeoutError, socket.timeout) as e:
                last_err = e
                metrics.solver_remote_failures_total.inc("timeout")
            except SolverProtocolError as e:
                last_err = e
                metrics.solver_remote_failures_total.inc("protocol")
            except OSError as e:  # conn refused/reset, missing socket, …
                last_err = e
                metrics.solver_remote_failures_total.inc("connection")
            attempt += 1
            if attempt > self.max_retries:
                raise SolverUnavailable(
                    f"solver call failed after {attempt} attempt(s): "
                    f"{last_err!r}") from last_err
            metrics.solver_remote_retries_total.inc()
            delay = min(self.backoff_base_s * (2 ** (attempt - 1)),
                        self.backoff_max_s)
            delay += self._rng.uniform(0, delay)  # full jitter
            delay = min(delay, max(0.0, deadline - self._clock()))
            if delay > 0:
                self._sleep(delay)

    def _solve_once(self, header: dict, blob: bytes, budget_s: float):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(budget_s)  # bounds connect and the send as ops
        op_deadline = self._clock() + budget_s
        try:
            sock.connect(self.socket_path)
            _send(sock, header, blob)
            # the WHOLE response read shares one deadline — a slow-drip
            # peer must not reset the timer per chunk
            resp, body = _recv(sock, self.max_frame_bytes,
                               deadline=op_deadline, clock=self._clock)
        finally:
            sock.close()
        if not resp.get("ok", False):
            # the sidecar is up but the solve itself failed; a retry
            # would deterministically fail again, so don't burn the
            # deadline on it
            metrics.solver_remote_failures_total.inc("server")
            raise SolverUnavailable(
                f"solver sidecar reported failure: "
                f"{resp.get('error', 'unknown')}")
        names = resp.get("names")
        if not isinstance(names, list) or not names:
            raise SolverProtocolError("response header carries no names")
        spans = resp.get("spans")
        self.last_spans = spans if isinstance(spans, list) else []
        try:
            data = np.load(io.BytesIO(body))
            return tuple(data[n] for n in names)
        except Exception as e:  # zipfile/np decode errors on corruption
            raise SolverProtocolError(
                f"undecodable plan payload: {e!r}") from e
