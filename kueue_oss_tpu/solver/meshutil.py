"""Mesh plumbing shared by the engine, the sidecar, and the bench.

The multi-chip kernels (solver/sharded.py, full_kernels mesh lanes)
need three things every production call site repeats: a portable
``shard_map`` (the API moved between jax releases; the image's jax
still ships it under ``jax.experimental``), mesh *detection* (config /
env / device-count auto), and a cache of jitted mesh drains so every
drain of the same (mesh, shape) reuses one compiled SPMD program.
Centralizing them here keeps `engine.py` and `service.py` free of
version probing and makes the sidecar's placement decisions identical
to the in-process engine's.

Mesh mode grammar (``SolverBackendConfig.mesh`` / ``KUEUE_SOLVER_MESH``):

- ``auto`` (default): build a 1-D ``wl`` mesh over all local devices
  when ``jax.device_count() > 1``; single-chip otherwise.
- ``off`` / ``none`` / ``0`` / ``1`` — and any unrecognized string —
  never build a mesh (unknown values fail CLOSED: a typo must not
  enable the multi-chip path).
- an integer ``n``: mesh over the first ``n`` local devices; fewer
  available devices means NO mesh, never a silently narrower one.
"""

from __future__ import annotations

from typing import Optional

MESH_AXIS = "wl"

#: KUEUE_SOLVER_COORDINATOR grammar: "host:port,num_processes,process_id"
COORDINATOR_ENV = "KUEUE_SOLVER_COORDINATOR"

#: one-shot jax.distributed bootstrap state (process-wide, like
#: jax.distributed itself); tests reset it between subprocess twins by
#: running each twin in its own interpreter
_distributed = {"initialized": False, "processes": 1, "process_id": 0}


def parse_coordinator(spec: Optional[str]
                      ) -> Optional[tuple[str, int, int]]:
    """Parse a ``host:port,num_processes,process_id`` coordinator spec
    (the KUEUE_SOLVER_COORDINATOR grammar). Returns None for
    absent/empty, and FAILS CLOSED (None + no multi-host init) on any
    malformed value — a typo must degrade to single-host, never
    half-initialize a distributed runtime."""
    if not spec:
        return None
    parts = [p.strip() for p in str(spec).split(",")]
    if len(parts) != 3 or not parts[0]:
        return None
    try:
        n, pid = int(parts[1]), int(parts[2])
    except ValueError:
        return None
    if n < 2 or not (0 <= pid < n):
        return None
    return parts[0], n, pid


def bootstrap_distributed(coordinator_address: Optional[str] = None,
                          num_processes: Optional[int] = None,
                          process_id: Optional[int] = None) -> int:
    """Idempotent multi-host bootstrap: ``jax.distributed.initialize``
    driven by explicit args (SolverBackendConfig.coordinator_*) or the
    ``KUEUE_SOLVER_COORDINATOR`` env ("host:port,num_processes,pid").

    Returns the process count (1 = single-host, nothing initialized).
    After a successful bootstrap ``jax.devices()`` is GLOBAL, so
    :func:`detect_mesh` builds the pod-wide mesh with no further
    changes. On the CPU backend the gloo collectives implementation is
    selected first — the default CPU collectives cannot execute
    cross-process computations at all — and each process should run ONE
    local device: gloo's TCP pairs carry untagged ordered frames, so
    concurrent per-device execution threads issuing collectives inside
    one SPMD program interleave on the pair and abort with a preamble
    size mismatch (real pods run one process per host regardless).
    """
    if _distributed["initialized"]:
        return _distributed["processes"]
    if coordinator_address is None:
        import os

        parsed = parse_coordinator(os.environ.get(COORDINATOR_ENV))
        if parsed is None:
            return 1
        coordinator_address, num_processes, process_id = parsed
    if not num_processes or num_processes < 2:
        return 1
    import jax

    if "cpu" in str(getattr(jax.config, "jax_platforms", None) or "cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:
            pass  # non-CPU build or option renamed: backend default
        try:
            # belt and suspenders for the gloo frame-interleaving
            # hazard above: synchronous dispatch keeps two PROGRAMS
            # from being in flight at once (the one-device-per-process
            # deployment shape handles the within-program case)
            jax.config.update("jax_cpu_enable_async_dispatch", False)
        except Exception:
            pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes), process_id=int(process_id))
    _distributed.update(initialized=True,
                        processes=int(num_processes),
                        process_id=int(process_id))
    return int(num_processes)


def process_count() -> int:
    """jax process count AFTER any bootstrap (1 = single-host)."""
    if not _distributed["initialized"]:
        return 1
    import jax

    return int(jax.process_count())


def process_index() -> int:
    if not _distributed["initialized"]:
        return 0
    import jax

    return int(jax.process_index())


def host_replicated(arrays) -> tuple:
    """Materialize global (possibly cross-process sharded) solver
    outputs as full host numpy arrays on EVERY process. Collective —
    all processes of the mesh must call it in the same order. Identity
    (plain np.asarray) on single-process runs."""
    import numpy as np

    if process_count() < 2:
        return tuple(np.asarray(a) for a in arrays)
    from jax.experimental import multihost_utils as mhu

    out = []
    for a in arrays:
        if (getattr(a, "ndim", 1) == 0
                or getattr(a, "is_fully_replicated", False)):
            # replicated values are addressable everywhere already
            out.append(np.asarray(a))
        else:
            out.append(np.asarray(mhu.process_allgather(a, tiled=True)))
    return tuple(out)


def shard_map(f, mesh, in_specs, out_specs):
    """Version-portable shard_map.

    On a jax new enough to expose ``jax.shard_map`` the default
    varying-axes checking runs (the kernels mark their per-shard
    carries with :func:`pvary`); on the older ``jax.experimental``
    spelling the replication checker is disabled instead — it predates
    varying-type annotations, and the drain carries deliberately mix
    replicated tree state with shard-varying workload rows."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as esm

    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def pvary(x, axis: str):
    """Mark a replicated value varying over ``axis`` where the running
    jax tracks varying-axes types (``jax.lax.pcast``, paired with the
    ``jax.shard_map`` spelling above); identity on older jax, where the
    value is already just a per-device array inside shard_map."""
    import jax

    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, (axis,), to="varying")
    return x


def parse_mesh_mode(mode: Optional[str]) -> Optional[int]:
    """Normalize a mesh mode string to a device-count request.

    Returns None for "off", -1 for "auto" (all devices), or a positive
    explicit device count. Unknown strings FAIL CLOSED (off): a typo-ed
    env var intended to disable the multi-chip path must never enable
    it — config-file values are additionally validated at load
    (configuration.validate).
    """
    if mode is None:
        import os

        mode = os.environ.get("KUEUE_SOLVER_MESH") or "auto"
    mode = str(mode).strip().lower()
    if mode in ("auto", "on", "true", ""):
        return -1
    try:
        n = int(mode)
    except ValueError:
        return None  # "off"/"none"/"disabled"/typos: all off
    return n if n > 1 else None


def detect_mesh(mode: Optional[str] = None, max_devices: int = 0):
    """Build the 1-D ``wl`` mesh the mode asks for, or None.

    An explicit device count requires at least that many local devices
    — fewer yields no mesh (fail closed) rather than a silently
    narrower layout. ``max_devices`` (when > 0) caps the mesh width —
    the chaos harness's mesh-shrink injection re-detects with a lower
    cap, the way a real device loss shrinks the usable slice.
    """
    want = parse_mesh_mode(mode)
    if want is None:
        return None
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    if want < 0:
        n = len(devices)
    elif want > len(devices):
        # pinned width unavailable: no mesh, not a silently narrower
        # one (the docstring contract — an explicit count REQUIRES at
        # least that many devices; solver_mesh_devices reports 0)
        return None
    else:
        n = want
    if max_devices > 0:
        n = min(n, max_devices)
    if n < 2:
        return None
    return Mesh(np.array(devices[:n]), (MESH_AXIS,))


def mesh_devices(mesh) -> int:
    return int(mesh.shape[MESH_AXIS]) if mesh is not None else 0


def mesh_divisible(mesh, w1: int) -> bool:
    """Whether a [W+1]-row workload axis block-shards evenly."""
    return mesh is not None and w1 % mesh_devices(mesh) == 0


def align_pad_target(target_w: int, mesh, extra_width: int = 0) -> int:
    """Grow a pad target so the padded axis (target_w + null row)
    splits evenly over the mesh — and over ``extra_width`` when given
    (the REMOTE sidecar's advertised mesh, which need not match the
    client's local device count; lcm covers both). Sticky with a
    monotone pad high-water mark: the same widths always yield the same
    alignment, so session slot coordinates (shard, local row) stay
    stable across drains."""
    import math

    widths = [w for w in (mesh_devices(mesh), int(extra_width)) if w > 1]
    if not widths:
        return target_w
    m = math.lcm(*widths)
    return target_w + (-(target_w + 1)) % m


def live_rows(wl_cqid, n_cqs: int) -> int:
    """Real (non-padding, non-null, non-recycled) workload rows in a
    padded export — the count the mesh floors gate on. ONE definition,
    shared by engine routing, resident placement, and the sidecar."""
    import numpy as np

    return int((np.asarray(wl_cqid[:-1]) < n_cqs).sum())


def shard_imbalance(wl_cqid, n_cqs: int, mesh) -> float:
    """Real-row imbalance across shards: (max - min) / mean occupied
    rows per shard (0.0 = perfectly even). Padding and recycled session
    slots count as empty."""
    import numpy as np

    n = mesh_devices(mesh)
    if n < 2:
        return 0.0
    occ = np.asarray(wl_cqid) < n_cqs
    if occ.shape[0] % n != 0:
        # defense in depth: callers observe row-sharded drains (lean
        # and full), whose padded axis always divides; a non-divisible
        # axis has no block shards to skew
        return 0.0
    per = occ.reshape(n, -1).sum(axis=1).astype(np.float64)
    mean = float(per.mean())
    if mean <= 0:
        return 0.0
    return float((per.max() - per.min()) / mean)


#: jitted lean mesh drains keyed by (mesh, axis); shapes key further
#: inside jit's own cache
_lean_cache: dict = {}


def lean_mesh_solver(mesh, axis: str = MESH_AXIS):
    """Cached jitted production lean drain for ``mesh`` — the full
    solve_backlog contract (admitted, opt, admit_round, parked, rounds,
    usage), bit-identical to the single-chip kernel."""
    import jax

    key = (mesh, axis)
    fn = _lean_cache.get(key)
    if fn is None:
        from kueue_oss_tpu.solver.sharded import make_sharded_drain

        fn = jax.jit(make_sharded_drain(mesh, axis))
        _lean_cache[key] = fn
    return fn


#: jitted mesh-sharded relax-LP programs keyed by (mesh, iters, axis)
_relax_cache: dict = {}


def relax_mesh_lp(mesh, iters: int, axis: str = MESH_AXIS):
    """Cached mesh-sharded projected-gradient LP for the relaxed
    admission arm (solver/relax.py; body in
    sharded.make_sharded_relax_lp)."""
    key = (mesh, int(iters), axis)
    fn = _relax_cache.get(key)
    if fn is None:
        from kueue_oss_tpu.solver.sharded import make_sharded_relax_lp

        fn = make_sharded_relax_lp(mesh, int(iters), axis)
        _relax_cache[key] = fn
    return fn
