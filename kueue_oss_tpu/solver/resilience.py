"""Resilience primitives for the solver backend.

The TPU solver is an *opt-in* backend: the host BestEffortFIFO cycle is
the reference behavior, and the control plane must survive the solver
sidecar crashing, hanging, or returning garbage without ever stalling an
admission round (ROADMAP north star; Aryl/Gavel treat scheduler-backend
failure as a first-class event for the same reason — a stalled admission
loop starves the whole cluster).

Two pieces live here:

- ``SolverUnavailable`` — the single fault type the scheduler routing
  sees. Transport errors, exhausted deadlines, server-reported failures,
  and sanity-guard plan rejections all collapse into it; the scheduler's
  reaction is always the same (degrade to the host cycle).
- ``SolverHealth`` — a closed → open → half-open circuit breaker. The
  engine consults ``allow()`` before touching the remote backend,
  records each outcome, and a tripped breaker short-circuits drains to
  the host path until a cooldown expires; then a single probe call
  either closes the breaker or re-opens it for another cooldown.

The clock is injected so breaker tests (and the chaos harness) run with
a fake clock — no sleeps.
"""

from __future__ import annotations

import time

from kueue_oss_tpu import metrics

#: breaker states (exported for tests/metrics; gauge encodes the index)
CLOSED = "closed"
HALF_OPEN = "half-open"
OPEN = "open"

_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class SolverUnavailable(Exception):
    """The solver backend cannot produce a usable plan right now.

    Raised by SolverClient after retries/deadline are exhausted, by the
    engine when the breaker is open or the imported plan fails the
    sanity guard. The scheduler treats it exactly like an unsupported
    problem shape: the admission round completes on the host path.
    """


class SolverHealth:
    """Circuit breaker over the remote solver backend.

    closed     -- calls flow; ``failure_threshold`` consecutive failures
                  trip the breaker open.
    open       -- calls are refused without touching the socket until
                  ``cooldown_s`` has elapsed.
    half-open  -- after the cooldown one probe call is allowed; success
                  closes the breaker, failure re-opens it (and restarts
                  the cooldown).

    Single-threaded by design: the scheduler loop is the only caller, so
    allow()/record_*() pairs never interleave.
    """

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 30.0,
                 clock=time.monotonic) -> None:
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = cooldown_s
        self.clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        #: total closed/half-open -> open transitions (mirrors the
        #: kueue_tpu_solver_breaker_trips_total counter)
        self.trips = 0
        self._opened_at = 0.0
        # the state gauge is written only on TRANSITIONS: SolverEngine
        # default-constructs a SolverHealth per instance, and a fresh
        # (closed) breaker must not overwrite the gauge while another
        # engine's live breaker is open

    def _set_state(self, state: str) -> None:
        self.state = state
        metrics.solver_breaker_state.set(value=_STATE_CODE[state])

    def allow(self) -> bool:
        """Whether a remote call may be attempted right now."""
        if self.state == OPEN:
            if self.clock() - self._opened_at >= self.cooldown_s:
                self._set_state(HALF_OPEN)  # next call is the probe
                return True
            return False
        return True

    def record_success(self) -> None:
        self.consecutive_failures = 0
        if self.state != CLOSED:
            self._set_state(CLOSED)

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if (self.state == HALF_OPEN
                or self.consecutive_failures >= self.failure_threshold):
            self._trip()

    def _trip(self) -> None:
        if self.state != OPEN:
            self.trips += 1
            metrics.solver_breaker_trips_total.inc()
        self._opened_at = self.clock()
        self._set_state(OPEN)
