"""Resilience primitives for the solver backend.

The TPU solver is an *opt-in* backend: the host BestEffortFIFO cycle is
the reference behavior, and the control plane must survive the solver
sidecar crashing, hanging, or returning garbage without ever stalling an
admission round (ROADMAP north star; Aryl/Gavel treat scheduler-backend
failure as a first-class event for the same reason — a stalled admission
loop starves the whole cluster).

Two pieces live here:

- ``SolverUnavailable`` — the single fault type the scheduler routing
  sees. Transport errors, exhausted deadlines, server-reported failures,
  and sanity-guard plan rejections all collapse into it; the scheduler's
  reaction is always the same (degrade to the host cycle).
- ``SolverHealth`` — a closed → open → half-open circuit breaker. The
  engine consults ``allow()`` before touching the remote backend,
  records each outcome, and a tripped breaker short-circuits drains to
  the host path until a cooldown expires; then a single probe call
  either closes the breaker or re-opens it for another cooldown.

Half-open admits exactly ONE in-flight probe: concurrent callers stay
degraded (host path) instead of thundering-herding the recovering
sidecar — the probe slot discipline is the resilience package's shared
:class:`~kueue_oss_tpu.resilience.CooldownPolicy`, and every breaker
transition reports the ``breaker_open`` condition into the process-wide
degradation controller (docs/ROBUSTNESS.md "Degradation ladder").

The clock is injected so breaker tests (and the chaos harness) run with
a fake clock — no sleeps.
"""

from __future__ import annotations

import threading
import time

from kueue_oss_tpu import metrics, resilience

#: breaker states (exported for tests/metrics; gauge encodes the index)
CLOSED = "closed"
HALF_OPEN = "half-open"
OPEN = "open"

_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class SolverUnavailable(Exception):
    """The solver backend cannot produce a usable plan right now.

    Raised by SolverClient after retries/deadline are exhausted, by the
    engine when the breaker is open or the imported plan fails the
    sanity guard. The scheduler treats it exactly like an unsupported
    problem shape: the admission round completes on the host path.
    """


class SolverHealth:
    """Circuit breaker over the remote solver backend.

    closed     -- calls flow; ``failure_threshold`` consecutive failures
                  trip the breaker open.
    open       -- calls are refused without touching the socket until
                  ``cooldown_s`` has elapsed.
    half-open  -- after the cooldown exactly one probe call is allowed;
                  success closes the breaker, failure re-opens it (and
                  restarts the cooldown). Concurrent callers during the
                  probe are refused — they keep degrading to the host
                  path instead of piling onto the recovering sidecar.

    allow()/record_*() hold a lock, so concurrent drains (the serve
    loop plus an operator-triggered drain) see a consistent machine.
    The cooldown's *elapsed* check keeps this instance's injected clock
    (tests drive it); the single-probe slot is the shared CooldownPolicy
    in the resilience package, giving every half-open re-probe in the
    system one discipline.
    """

    #: the shared-cooldown-policy key for the probe slot
    _KEY = (resilience.SOLVER, "breaker_open")

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 30.0,
                 clock=time.monotonic) -> None:
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = cooldown_s
        self.clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        #: total closed/half-open -> open transitions (mirrors the
        #: kueue_tpu_solver_breaker_trips_total counter)
        self.trips = 0
        self._opened_at = 0.0
        self._lock = threading.Lock()
        # the state gauge is written only on TRANSITIONS: SolverEngine
        # default-constructs a SolverHealth per instance, and a fresh
        # (closed) breaker must not overwrite the gauge while another
        # engine's live breaker is open

    @property
    def probing(self) -> bool:
        """Whether a half-open probe is in flight right now."""
        return resilience.controller.cooldowns.probing(self._KEY)

    def _set_state(self, state: str) -> None:
        self.state = state
        metrics.solver_breaker_state.set(value=_STATE_CODE[state])

    def allow(self) -> bool:
        """Whether a remote call may be attempted right now.

        At most one caller gets True per half-open window; it MUST
        follow up with record_success()/record_failure() to release the
        probe slot.
        """
        cooldowns = resilience.controller.cooldowns
        with self._lock:
            if self.state == OPEN:
                if self.clock() - self._opened_at < self.cooldown_s:
                    return False
                if not cooldowns.acquire_probe(self._KEY):
                    return False  # someone else is already probing
                self._set_state(HALF_OPEN)
                return True
            if self.state == HALF_OPEN:
                # a second drain arriving mid-probe stays degraded
                return cooldowns.acquire_probe(self._KEY)
            return True

    def record_success(self) -> None:
        ctl = resilience.controller
        with self._lock:
            ctl.cooldowns.release_probe(self._KEY)
            self.consecutive_failures = 0
            if self.state != CLOSED:
                self._set_state(CLOSED)
                ctl.report(resilience.SOLVER, "breaker_open", False,
                           reason="probe succeeded; breaker closed")

    def record_failure(self) -> None:
        ctl = resilience.controller
        with self._lock:
            ctl.cooldowns.release_probe(self._KEY)
            self.consecutive_failures += 1
            if (self.state == HALF_OPEN
                    or self.consecutive_failures >= self.failure_threshold):
                self._trip()

    def _trip(self) -> None:
        if self.state != OPEN:
            self.trips += 1
            metrics.solver_breaker_trips_total.inc()
        self._opened_at = self.clock()
        self._set_state(OPEN)
        resilience.controller.report(
            resilience.SOLVER, "breaker_open", True,
            reason=(f"breaker open after "
                    f"{self.consecutive_failures} consecutive failures"))
