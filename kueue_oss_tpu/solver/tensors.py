"""Snapshot → dense tensor export for the TPU solver.

Flattens the cohort forest into parents-first node arrays over a global
(flavor, resource) vocabulary, and the pending backlog into per-workload
flavor-option request tensors. All quantities are int32 after gcd-based
unit scaling (the exporter rejects problems whose totals could overflow).

Reference parity: this is the tensor form of pkg/cache/scheduler's
Snapshot — resource_node.go quantities (nominal/subtree/local quota,
borrowing limits, usage) plus the queue heads' request vectors.
"""

from __future__ import annotations

import dataclasses
import math
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from kueue_oss_tpu.api.types import (
    FlavorFungibilityPolicy,
    FlavorFungibilityPreference,
    FlavorResource,
    PreemptionPolicyValue,
    QueueingStrategy,
    ResourceFlavor,
)
from kueue_oss_tpu.core.snapshot import Snapshot, build_snapshot
from kueue_oss_tpu.core.store import Store
from kueue_oss_tpu.core.workload_info import (
    WorkloadInfo,
    effective_priority,
    ignore_undeclared_resources,
    queue_order_timestamp,
    quota_reservation_time,
)
from kueue_oss_tpu.core.workload_info import (
    requests_config_generation as _wli_requests_config_generation,
)
from kueue_oss_tpu.scheduler.flavor_assigner import (
    _selector_matches,
    _untolerated_taint,
)

#: "infinity" for missing borrowing limits; headroom against int32 overflow.
BIG = np.int32(1 << 30)
#: quantities must stay below this after scaling so sums can't overflow.
MAX_QUANTITY = 1 << 28


class UnsupportedProblem(Exception):
    """Raised when a scenario needs the oracle path (solver fallback)."""


def pow2(n: int) -> int:
    """Next power of two >= n — THE bucketing primitive for every
    padded axis (workload rows, scenario batches, scatter widths), so
    a future padding-policy change has one home."""
    p = 1
    while p < n:
        p *= 2
    return p


#: preemption-policy encoding shared with the kernels
POLICY_NEVER = 0
POLICY_LOWER_PRIORITY = 1
POLICY_LOWER_OR_NEWER_EQUAL = 2
POLICY_ANY = 3

_POLICY_CODE = {
    "Never": POLICY_NEVER,
    "LowerPriority": POLICY_LOWER_PRIORITY,
    "LowerOrNewerEqualPriority": POLICY_LOWER_OR_NEWER_EQUAL,
    "Any": POLICY_ANY,
}

#: sentinel for "no borrowWithinCohort maxPriorityThreshold"
NO_THRESHOLD = np.int32(-(1 << 31) + 1)


@dataclass
class SolverProblem:
    """Dense problem instance. Node axis is [N+1] (last row = null node);
    workload axis is [W+1] (last row = null workload).

    The workload axis unifies pending and (with include_admitted) admitted
    workloads: admitted rows carry their admission usage in ``ad_usage``
    and are eviction candidates for the preemption kernel; on eviction
    they re-enter the pending set and re-assign through their option rows.
    The flavor-option axis K spans (resource group, flavor) pairs:
    ``cq_opt_group[c, k]`` names option k's group, and a workload's
    assignment picks one option per group (groups cover disjoint
    (flavor, resource) columns, so they are independent subproblems —
    flavorassigner.go:599-765 assigns each group its own flavor walk).
    """

    # --- node (CQ + cohort) arrays, parents-first topo order -------------
    parent: np.ndarray        # [N+1] int32, null node index N for roots
    depth: np.ndarray         # [N+1] int32
    height: np.ndarray        # [N+1] int32 (cohort height; CQs are 0)
    has_parent: np.ndarray    # [N+1] bool
    path: np.ndarray          # [N+1, D] int32 ancestor chain (self first), padded with N
    nominal: np.ndarray       # [N+1, F] int32
    subtree: np.ndarray       # [N+1, F] int32
    local_quota: np.ndarray   # [N+1, F] int32
    has_borrow: np.ndarray    # [N+1, F] bool
    borrow_limit: np.ndarray  # [N+1, F] int32 (BIG when unset)
    usage0: np.ndarray        # [N+1, F] int32 (initial usage incl. cohorts)

    # --- ClusterQueue arrays (C = number of CQs) --------------------------
    cq_node: np.ndarray       # [C] int32 node index of each CQ
    cq_strict: np.ndarray     # [C] bool (StrictFIFO)
    cq_try_next: np.ndarray   # [C] bool (whenCanBorrow == TryNextFlavor)
    cq_root_height: np.ndarray  # [C] int32 height of the CQ's root cohort
    cq_nflavors: np.ndarray   # [C] int32 number of flavor options (all groups)

    # --- workload arrays --------------------------------------------------
    wl_cqid: np.ndarray       # [W+1] int32 CQ id (C for null)
    wl_rank: np.ndarray       # [W+1] int32 FIFO rank within its CQ
    wl_prio: np.ndarray       # [W+1] int32
    wl_ts: np.ndarray         # [W+1] int32 (dense timestamp rank)
    wl_uid: np.ndarray        # [W+1] int32
    wl_req: np.ndarray        # [W+1, K, F] int32 request under flavor-option k
    wl_valid: np.ndarray      # [W+1, K] bool option exists & taints/selector ok

    # --- preemption extension (zero-sized/empty on fit-only exports) ------
    wl_parked0: Optional[np.ndarray] = None    # [W+1] bool initially parked
    wl_admitted0: Optional[np.ndarray] = None  # [W+1] bool initially admitted
    wl_evicted0: Optional[np.ndarray] = None   # [W+1] bool Evicted condition
    wl_admit_rank: Optional[np.ndarray] = None  # [W+1] int32 reservation rank
    ad_usage: Optional[np.ndarray] = None      # [W+1, F] int32 admission usage
    cq_within_policy: Optional[np.ndarray] = None   # [C] int32 POLICY_*
    cq_reclaim_policy: Optional[np.ndarray] = None  # [C] int32 POLICY_*
    cq_bwc_forbidden: Optional[np.ndarray] = None   # [C] bool
    cq_bwc_threshold: Optional[np.ndarray] = None   # [C] int32 (NO_THRESHOLD)
    cq_preempt_try_next: Optional[np.ndarray] = None  # [C] bool
    cq_pref_pob: Optional[np.ndarray] = None        # [C] bool (PreemptionOverBorrowing)
    cq_fair_weight: Optional[np.ndarray] = None     # [C] float32
    cq_root: Optional[np.ndarray] = None            # [C] int32 root node idx
    cq_opt_group: Optional[np.ndarray] = None       # [C, K] int32 (-1 none)
    cq_ngroups: Optional[np.ndarray] = None         # [C] int32
    fr_resource: Optional[np.ndarray] = None        # [F] int32 resource id
    node_fair_weight: Optional[np.ndarray] = None   # [N+1] float32
    #: scheduling-equivalence class per workload (BestEffortFIFO NoFit
    #: dedup, cluster_queue.go:371/handleInadmissibleHash); n_classes =
    #: sentinel for StrictFIFO / dedup-disabled workloads
    wl_class: Optional[np.ndarray] = None           # [W+1] int32
    class_root: Optional[np.ndarray] = None         # [n_classes+1] int32
    n_classes: int = 0
    #: admission fair sharing (KEP-4136): per-workload dense LocalQueue
    #: id + scalarized penalty increment, per-LQ decayed starting
    #: penalty, per-CQ UsageBasedAdmissionFairSharing flag
    wl_lq: Optional[np.ndarray] = None              # [W+1] int32
    wl_afs_penalty: Optional[np.ndarray] = None     # [W+1] float32
    #: newer-equal preemption threshold rank: a candidate satisfies the
    #: LowerOrNewerEqualPriority timestamp test iff its ts rank exceeds
    #: this (own rank normally; under SchedulerTimestampPreemptionBuffer
    #: the rank of the last distinct timestamp within the 5-min buffer)
    wl_ts_buf: Optional[np.ndarray] = None          # [W+1] int32
    lq_penalty0: Optional[np.ndarray] = None        # [L+1] float32
    cq_afs: Optional[np.ndarray] = None             # [C] bool
    #: host-only raw inputs behind the dense encodings above; the
    #: delta-session layer (solver/delta.py) re-ranks them with stable
    #: order-preserving ids so churn doesn't dirty every row. Never
    #: serialized to the sidecar.
    wl_raw_ts: Optional[np.ndarray] = None          # [W+1] float64
    wl_raw_admit_ts: Optional[np.ndarray] = None    # [W+1] float64
    wl_class_tok: Optional[np.ndarray] = None       # [W+1] int64 (-1 none)
    class_tok_root: Optional[np.ndarray] = None     # [n_toks] int32
    n_resources: int = 1
    #: timestamp rank assigned to round-r evictions: ts_evict_base + r
    ts_evict_base: int = 0
    #: reservation rank for round-r re-admissions: admit_rank_base + r
    admit_rank_base: int = 0

    # --- host-side decode tables -----------------------------------------
    fr_list: list[FlavorResource] = field(default_factory=list)
    node_names: list[str] = field(default_factory=list)
    cq_names: list[str] = field(default_factory=list)
    wl_keys: list[str] = field(default_factory=list)
    #: per CQ: ordered flavor names (option k -> flavor, spanning groups)
    cq_option_flavors: dict[str, list[str]] = field(default_factory=dict)
    #: per CQ: resource name -> group index (admission decode)
    cq_resource_group: dict[str, dict[str, int]] = field(default_factory=dict)
    scale: int = 1

    @property
    def n_nodes(self) -> int:
        return self.parent.shape[0] - 1

    @property
    def n_cqs(self) -> int:
        return self.cq_node.shape[0]

    @property
    def n_workloads(self) -> int:
        return self.wl_cqid.shape[0] - 1


def pad_workloads(problem: SolverProblem, target_w: int) -> SolverProblem:
    """Pad the workload axis to ``target_w`` rows (plus the null row).

    Padding rows carry the null CQ id (C) so head selection's segment
    reduction drops them, no valid options, and no initial state — they
    are inert. Fills must never alias a real row: ``wl_uid`` pads with
    BIG, not 0 (a legitimate uid-0 workload must stay distinguishable
    from padding in any uid-keyed comparison or diagnostic decode).
    Power-of-two bucketing keeps the jitted kernels' shape cache small
    when drains run repeatedly over a changing backlog (the Simulator
    drains after every event batch).

    Layout contract: inert rows are inserted BEFORE the null row, so
    the null row is ALWAYS the last row of the padded axis. The
    row-sharded kernels (solver/sharded.py) depend on this — they pad
    an uneven axis to a mesh multiple and unpad the plan by
    re-concatenating ``[:W1-1]`` with the final row; kernels address
    the null row as ``[-1]``. Inserting padding anywhere else would
    shift dump scatters off the rows the single-chip kernel writes and
    break bit-identical parity.
    """
    W = problem.n_workloads
    if target_w <= W:
        return problem
    pad = target_w - W
    C = problem.n_cqs

    def pad1(arr, fill, dtype=None):
        if arr is None:
            return None
        body, null_row = arr[:-1], arr[-1:]
        pad_shape = (pad,) + arr.shape[1:]
        filler = np.full(pad_shape, fill, dtype=arr.dtype)
        return np.concatenate([body, filler, null_row])

    return dataclasses.replace(
        problem,
        wl_cqid=pad1(problem.wl_cqid, C),
        wl_rank=pad1(problem.wl_rank, BIG),
        wl_prio=pad1(problem.wl_prio, 0),
        wl_ts=pad1(problem.wl_ts, 0),
        wl_uid=pad1(problem.wl_uid, BIG),
        wl_req=pad1(problem.wl_req, 0),
        wl_valid=pad1(problem.wl_valid, False),
        wl_parked0=pad1(problem.wl_parked0, False),
        wl_admitted0=pad1(problem.wl_admitted0, False),
        wl_evicted0=pad1(problem.wl_evicted0, False),
        wl_admit_rank=pad1(problem.wl_admit_rank, 0),
        ad_usage=pad1(problem.ad_usage, 0),
        wl_class=pad1(problem.wl_class, problem.n_classes),
        wl_lq=pad1(problem.wl_lq, 0),
        wl_afs_penalty=pad1(problem.wl_afs_penalty, 0.0),
        wl_ts_buf=pad1(problem.wl_ts_buf, 0),
        wl_raw_ts=pad1(problem.wl_raw_ts, 0.0),
        wl_raw_admit_ts=pad1(problem.wl_raw_admit_ts, 0.0),
        wl_class_tok=pad1(problem.wl_class_tok, -1),
        wl_keys=list(problem.wl_keys) + [""] * pad,
    )


def _flavor_compatible(info: WorkloadInfo, flavor: ResourceFlavor,
                       allowed_keys: frozenset[str]) -> bool:
    for ps in info.obj.podsets:
        if _untolerated_taint(ps, flavor) is not None:
            return False
        if not _selector_matches(ps, flavor, allowed_keys):
            return False
    return True


def flavor_option_ceilings(
    store: Store,
) -> dict[str, dict[FlavorResource, int]]:
    """Static zero-usage capacity ceilings per CQ flavor option.

    For every ClusterQueue and every (flavor, resource) quota it
    declares, the most capacity the batch oracle could EVER grant the
    CQ on that option: its nominal quota plus — when borrowing is
    permitted — the rest of its cohort root subtree's nominal pool
    (capped by the borrowing limit). Pure spec data, so the result is
    valid for one ``ExportCache.spec_gen`` and is the capacity side of
    the streaming flavor-pick witness: a mid-window capacity event can
    raise an option's availability at most to this ceiling, so a
    flavor pick is event-stable iff every earlier compatible option's
    ceiling sits below the request (scheduler/streaming.py).
    """
    def cohort_root(name: str) -> str:
        seen: set[str] = set()
        cur = name
        while cur not in seen:
            seen.add(cur)
            spec_c = store.cohorts.get(cur)
            if spec_c is None or not spec_c.parent:
                break
            cur = spec_c.parent
        return cur

    # nominal pool per cohort root: every member CQ's quotas plus any
    # cohort-level quotas along the subtree
    pool: dict[str, dict[FlavorResource, int]] = {}

    def add_quotas(root: str, resource_groups) -> None:
        tot = pool.setdefault(root, {})
        for rg in resource_groups:
            for fq in rg.flavors:
                for rq in fq.resources:
                    fr = (fq.name, rq.name)
                    tot[fr] = tot.get(fr, 0) + rq.nominal

    for spec in store.cluster_queues.values():
        if spec.cohort:
            add_quotas(cohort_root(spec.cohort), spec.resource_groups)
    for cname, cspec in store.cohorts.items():
        add_quotas(cohort_root(cname), cspec.resource_groups)

    out: dict[str, dict[FlavorResource, int]] = {}
    for name, spec in store.cluster_queues.items():
        ceilings: dict[FlavorResource, int] = {}
        root_pool = pool.get(cohort_root(spec.cohort),
                             {}) if spec.cohort else {}
        for rg in spec.resource_groups:
            for fq in rg.flavors:
                for rq in fq.resources:
                    fr = (fq.name, rq.name)
                    ceil = rq.nominal
                    bl = rq.borrowing_limit
                    if spec.cohort and (bl is None or bl > 0):
                        lendable = max(
                            0, root_pool.get(fr, 0) - rq.nominal)
                        ceil += (lendable if bl is None
                                 else min(bl, lendable))
                    ceilings[fr] = ceil
        out[name] = ceilings
    return out


class _WlRow:
    """Per-workload cached export quantities (drain-invariant)."""

    __slots__ = ("stamp", "cid", "prio", "uid", "raw_ts", "evicted",
                 "shape_id", "class_tok", "lq_key", "totals",
                 "usage_fs", "usage_qs", "admit_ts")

    def __init__(self, stamp, cid, prio, uid, raw_ts, evicted, shape_id,
                 class_tok, lq_key, totals, usage_fs, usage_qs, admit_ts):
        self.stamp = stamp
        self.cid = cid
        self.prio = prio
        self.uid = uid
        self.raw_ts = raw_ts
        self.evicted = evicted
        self.shape_id = shape_id
        self.class_tok = class_tok
        self.lq_key = lq_key
        self.totals = totals
        self.usage_fs = usage_fs
        self.usage_qs = usage_qs
        self.admit_ts = admit_ts


class ExportCache:
    """Cross-drain memo for :func:`export_problem`.

    Rebuilding the whole problem with per-workload Python loops cost
    ~0.35 s per drain at 15k workloads — more than the solve itself once
    the kernel got fast. The cache keeps per-workload rows and interns
    request tensors by scheduling shape (CQ, pinned flavor, resource
    totals, per-podset selector/tolerations — the exact inputs of the
    option-validity walk), so repeated drains assemble ``wl_req`` /
    ``wl_valid`` with one vectorized gather instead of loops.

    Invalidation is event-driven: a Workload event drops that key's row;
    any other kind (ClusterQueue, Cohort, ResourceFlavor, ...) bumps
    ``spec_gen``, which retires every derived table through the stamp
    check on the next export. Gate flips, request-shaping config changes
    and vocabulary growth are caught by the per-export stamp itself
    (``features.all_gates()``, ``requests_config_generation()``, the FR
    vocabulary and CQ name ordering are all part of it).
    """

    def __init__(self, store: Store, subscribe: bool = True) -> None:
        self.store = store
        self.spec_gen = 0
        self.rows: dict[str, _WlRow] = {}
        #: interned scheduling shapes; shape 0 is the all-invalid row
        self._shape_ids: dict[tuple, int] = {}
        self._shape_valid: list[np.ndarray] = []
        self._shape_req: list[np.ndarray] = []
        self._stack_valid: Optional[np.ndarray] = None
        self._stack_req: Optional[np.ndarray] = None
        #: interned (cid, scheduling_hash) -> class token; token -> root
        self._class_toks: dict[tuple, int] = {}
        self._tok_root: list[int] = []
        self._stamp: Optional[tuple] = None
        self._fr_index: dict[FlavorResource, int] = {}
        #: per-spec-gen CQ tables: covered resources + selector key sets
        self._cq_gen = -1
        self._cq_covered: list[set] = []
        self._cq_allowed_keys: list[list[frozenset]] = []
        #: delta-session dirty tracking (solver/delta.py): workload keys
        #: and CQ names touched since the last consume_dirty(). These
        #: feed the ProblemDelta emit stats and the no-change fast path;
        #: the delta itself stays content-based (compared, not inferred)
        #: so queue-order churn that produces no store event is still
        #: caught.
        self.dirty_keys: set[str] = set()
        self.dirty_cqs: set[str] = set()
        self.events_seen = 0
        #: incremental columnar assembly view (solver/columnar.py). Only
        #: subscribed caches get one: an unsubscribed cache never sees
        #: invalidation events, so its columns could go silently stale.
        self.columnar = None
        if subscribe:
            store.watch(self._on_event)
            import os

            if os.environ.get("KUEUE_COLUMNAR_EXPORT", "1") != "0":
                from kueue_oss_tpu.solver.columnar import ColumnarStore

                self.columnar = ColumnarStore(self)

    def _on_event(self, event) -> None:
        verb, kind, obj = event
        self.events_seen += 1
        if kind == "Workload":
            self.rows.pop(obj.key, None)
            self.dirty_keys.add(obj.key)
            if self.columnar is not None:
                self.columnar.note_dirty(obj.key)
            lq = self.store.local_queues.get(
                f"{obj.namespace}/{obj.queue_name}")
            if lq is not None:
                self.dirty_cqs.add(lq.cluster_queue)
        else:
            self.spec_gen += 1
            name = getattr(obj, "name", None)
            if kind == "ClusterQueue" and name:
                self.dirty_cqs.add(name)

    def consume_dirty(self) -> tuple[set[str], set[str]]:
        """Return-and-clear the dirty sets (one delta emission's worth)."""
        keys, cqs = self.dirty_keys, self.dirty_cqs
        self.dirty_keys, self.dirty_cqs = set(), set()
        return keys, cqs

    def dirty_snapshot(self) -> tuple[int, frozenset, frozenset]:
        """Non-consuming view (spec_gen, dirty keys, dirty CQs).

        The streaming fast path (scheduler/streaming.py) reads this
        for its fences and status surface: spec_gen is THE spec-change
        fence (any quota edit, flavor change, cohort edit, or node
        flap bumps it), and the dirty sets size the delta the next
        full solve will ship — without stealing the delta session's
        consume_dirty()."""
        return (self.spec_gen, frozenset(self.dirty_keys),
                frozenset(self.dirty_cqs))

    # -- derived-table lifecycle ------------------------------------------

    def refresh(self, fr_list: list, cq_names: list[str], K: int,
                F: int) -> tuple:
        """Return the stamp rows must carry, clearing derived state when
        anything it covers changed since the previous export."""
        from kueue_oss_tpu import features

        stamp = (self.spec_gen, tuple(sorted(features.all_gates().items())),
                 _wli_requests_config_generation(), tuple(fr_list),
                 tuple(cq_names), K)
        if stamp != self._stamp:
            self._stamp = stamp
            self.rows.clear()
            self._shape_ids.clear()
            self._shape_valid = [np.zeros(K, dtype=bool)]
            self._shape_req = [np.zeros((K, max(1, F)), dtype=np.int64)]
            self._stack_valid = None
            self._stack_req = None
            self._class_toks.clear()
            self._tok_root = []
            self._fr_index = {fr: i for i, fr in enumerate(fr_list)}
        return self._stamp

    def cq_tables(self, cq_names: list[str]) -> None:
        """Per-CQ covered-resource sets and selector key universes,
        cached per spec generation."""
        if self._cq_gen == self.spec_gen and len(self._cq_covered) == len(
                cq_names):
            return
        self._cq_gen = self.spec_gen
        self._cq_covered = []
        self._cq_allowed_keys = []
        for name in cq_names:
            spec = self.store.cluster_queues[name]
            covered = {r for rg in spec.resource_groups
                       for r in rg.covered_resources}
            per_group = []
            for rg in spec.resource_groups:
                per_group.append(frozenset(
                    key for fq in rg.flavors
                    for key in self.store.resource_flavors.get(
                        fq.name, ResourceFlavor(name=fq.name)).node_labels))
            self._cq_covered.append(covered)
            self._cq_allowed_keys.append(per_group)

    def shape_matrices(self) -> tuple[np.ndarray, np.ndarray]:
        if (self._stack_valid is None
                or self._stack_valid.shape[0] != len(self._shape_valid)):
            self._stack_valid = np.stack(self._shape_valid)
            self._stack_req = np.stack(self._shape_req)
        return self._stack_valid, self._stack_req

    # -- row building ------------------------------------------------------

    def row(self, info: WorkloadInfo, cid: int, stamp: tuple,
            strict: bool, root: int, K: int, F: int) -> _WlRow:
        r = self.rows.get(info.key)
        if r is not None and r.stamp is stamp:
            return r
        r = self._build_row(info, cid, stamp, strict, root, K, F)
        self.rows[info.key] = r
        return r

    def _build_row(self, info: WorkloadInfo, cid: int, stamp: tuple,
                   strict: bool, root: int, K: int, F: int) -> _WlRow:
        from kueue_oss_tpu import features

        wl = info.obj
        for ps in wl.podsets:
            if (ps.topology_request is not None
                    and ps.topology_request.podset_group_name):
                raise UnsupportedProblem(
                    f"workload {info.key} uses podset topology groups")
        totals: dict[str, int] = {}
        for psr in info.total_requests:
            for rname, q in psr.requests.items():
                totals[rname] = totals.get(rname, 0) + q
        shape_id = self._shape_id(info, cid, totals, K, F)
        if not features.enabled("SchedulingEquivalenceHashing") or strict:
            tok = -1
        else:
            ckey = (cid, info.scheduling_hash())
            tok = self._class_toks.get(ckey)
            if tok is None:
                tok = len(self._tok_root)
                self._class_toks[ckey] = tok
                self._tok_root.append(int(root))
        usage_fs = usage_qs = None
        admit_ts = 0.0
        if wl.status.admission is not None:
            fs, qs = [], []
            for fr, q in info.usage().items():
                j = self._fr_index.get(fr)
                if j is not None:
                    fs.append(j)
                    qs.append(q)
            usage_fs = np.asarray(fs, dtype=np.int64)
            usage_qs = np.asarray(qs, dtype=np.int64)
            admit_ts = quota_reservation_time(wl, 0.0)
        return _WlRow(
            stamp, cid, effective_priority(wl), wl.uid,
            queue_order_timestamp(wl), wl.is_evicted, shape_id, tok,
            f"{wl.namespace}/{wl.queue_name}", totals, usage_fs, usage_qs,
            admit_ts)

    def _shape_id(self, info: WorkloadInfo, cid: int,
                  totals: dict[str, int], K: int, F: int) -> int:
        wl = info.obj
        spec = self.store.cluster_queues[info.cluster_queue]
        if not spec.resource_groups:
            return 0
        shape_key = (
            cid, wl.allowed_flavor, tuple(sorted(totals.items())),
            tuple((tuple(sorted(ps.node_selector.items())),
                   tuple(ps.tolerations)) for ps in wl.podsets),
        )
        sid = self._shape_ids.get(shape_key)
        if sid is not None:
            return sid
        covered = self._cq_covered[cid]
        if (any(q > 0 and r not in covered for r, q in totals.items())
                and not ignore_undeclared_resources()):
            # Undeclared resource: no option can ever fit; the solver
            # parks it (oracle parity). Intern to the all-invalid row.
            self._shape_ids[shape_key] = 0
            return 0
        valid = np.zeros(K, dtype=bool)
        req = np.zeros((K, max(1, F)), dtype=np.int64)
        k = -1
        for g, rg in enumerate(spec.resource_groups):
            allowed_keys = self._cq_allowed_keys[cid][g]
            for fq in rg.flavors:
                k += 1
                flavor = self.store.resource_flavors.get(fq.name)
                if flavor is None:
                    continue
                # A concurrent-admission variant is pinned to one flavor
                # (flavorassigner IsFlavorAllowedForVariant).
                if (wl.allowed_flavor is not None
                        and fq.name != wl.allowed_flavor):
                    continue
                if not _flavor_compatible(info, flavor, allowed_keys):
                    continue
                valid[k] = True
                for rname, q in totals.items():
                    if rname in rg.covered_resources:
                        req[k, self._fr_index[(fq.name, rname)]] = q
        sid = len(self._shape_valid)
        self._shape_ids[shape_key] = sid
        self._shape_valid.append(valid)
        self._shape_req.append(req)
        return sid


def order_nodes(forest) -> list:
    """Cohort-forest nodes in parents-first BFS order — THE node axis
    ordering every export (classic and columnar) shares. A deque keeps
    the traversal O(n); the previous list ``pop(0)`` was O(n²), which
    showed up at 10k-CQ cohort forests."""
    nodes = []
    queue: deque = deque()
    for root in forest.roots():
        queue.append(root)
        while queue:
            n = queue.popleft()
            nodes.append(n)
            queue.extend(n.children.values())
    return nodes


def export_problem(
    store: Store,
    pending: dict[str, list[WorkloadInfo]],
    snapshot: Optional[Snapshot] = None,
    include_admitted: bool = False,
    parked: Optional[dict[str, list[WorkloadInfo]]] = None,
    afs=None,
    now: float = 0.0,
    cache: Optional[ExportCache] = None,
    columnar: bool = True,
) -> SolverProblem:
    """Build a SolverProblem from the store and the pending backlog.

    ``pending`` maps CQ name -> workloads in FIFO-heap order (rank order).
    ``parked`` maps CQ name -> inadmissible (parked) workloads; they export
    with ``wl_parked0`` set so the kernel re-tries them when an in-drain
    eviction frees capacity in their cohort (the queue manager's
    capacity-freed flush). With ``include_admitted``, admitted workloads
    are appended to the same workload axis as eviction candidates (their
    admission usage rides ``ad_usage``; the node ``usage0`` still
    includes them — the kernel subtracts on eviction). Raises
    UnsupportedProblem for shapes the solver doesn't model yet
    (per-podset topology groups) so the caller can fall back to the
    oracle.
    """
    # Columnar fast path (solver/columnar.py): when the cache carries a
    # ColumnarStore and the caller did not pin an out-of-band snapshot,
    # assemble the problem from incrementally-maintained columns instead
    # of the per-row walk below. The columnar view bails (returns None)
    # on anything it cannot prove bit-identical — AFS-active exports,
    # first build, vocabulary changes — and this classic walk runs.
    col = getattr(cache, "columnar", None) if cache is not None else None
    if col is not None and snapshot is None and columnar:
        out = col.export(pending, include_admitted=include_admitted,
                         parked=parked, afs=afs, now=now)
        if out is not None:
            return out

    snapshot = snapshot or build_snapshot(store)
    forest = snapshot.forest

    nodes = order_nodes(forest)
    index = {id(n): i for i, n in enumerate(nodes)}
    n_nodes = len(nodes)
    null = n_nodes

    # ---- FR vocabulary ---------------------------------------------------
    frs: set[FlavorResource] = set()
    for n in nodes:
        frs.update(n.quotas.keys())
        frs.update(n.usage.keys())
    for infos in pending.values():
        for info in infos:
            cq = store.cluster_queues[info.cluster_queue]
            for rg in cq.resource_groups:
                for fq in rg.flavors:
                    for r in rg.covered_resources:
                        frs.add((fq.name, r))
    fr_list = sorted(frs)
    fr_index = {fr: i for i, fr in enumerate(fr_list)}
    F = max(1, len(fr_list))

    # ---- node arrays -----------------------------------------------------
    parent = np.full(n_nodes + 1, null, dtype=np.int32)
    depth = np.zeros(n_nodes + 1, dtype=np.int32)
    has_parent = np.zeros(n_nodes + 1, dtype=bool)
    nominal = np.zeros((n_nodes + 1, F), dtype=np.int64)
    subtree = np.zeros((n_nodes + 1, F), dtype=np.int64)
    local_quota = np.zeros((n_nodes + 1, F), dtype=np.int64)
    has_borrow = np.zeros((n_nodes + 1, F), dtype=bool)
    borrow_limit = np.zeros((n_nodes + 1, F), dtype=np.int64)
    usage0 = np.zeros((n_nodes + 1, F), dtype=np.int64)

    for i, n in enumerate(nodes):
        if n.parent is not None:
            parent[i] = index[id(n.parent)]
            has_parent[i] = True
            depth[i] = depth[parent[i]] + 1
        for fr, q in n.quotas.items():
            j = fr_index[fr]
            nominal[i, j] = q.nominal
            if q.borrowing_limit is not None:
                has_borrow[i, j] = True
                borrow_limit[i, j] = q.borrowing_limit
        for fr, v in n.subtree_quota.items():
            subtree[i, fr_index[fr]] = v
        for fr, v in n.usage.items():
            usage0[i, fr_index[fr]] = v
        for j, fr in enumerate(fr_list):
            local_quota[i, j] = n.local_quota(fr)

    D = int(depth.max()) + 1 if n_nodes else 1
    path = np.full((n_nodes + 1, D), null, dtype=np.int32)
    for i, n in enumerate(nodes):
        cur, d = i, 0
        while cur != null and d < D:
            path[i, d] = cur
            cur = parent[cur]
            d += 1

    # height (distance to furthest leaf, counting cohort edges only;
    # reference: classical/hierarchical_preemption.go getNodeHeight)
    height = np.zeros(n_nodes + 1, dtype=np.int32)
    for i in range(n_nodes - 1, -1, -1):
        n = nodes[i]
        h = min(len(n.children), 1)
        for c in n.children.values():
            if not c.is_cq:
                h = max(h, height[index[id(c)]] + 1)
        height[i] = h

    # ---- CQ arrays -------------------------------------------------------
    cq_names = sorted(forest.cqs.keys())
    C = len(cq_names)
    cq_node = np.zeros(C, dtype=np.int32)
    cq_strict = np.zeros(C, dtype=bool)
    cq_try_next = np.zeros(C, dtype=bool)
    cq_root_height = np.zeros(C, dtype=np.int32)
    cq_nflavors = np.zeros(C, dtype=np.int32)
    cq_within_policy = np.zeros(C, dtype=np.int32)
    cq_reclaim_policy = np.zeros(C, dtype=np.int32)
    cq_bwc_forbidden = np.zeros(C, dtype=bool)
    cq_bwc_threshold = np.full(C, NO_THRESHOLD, dtype=np.int32)
    cq_preempt_try_next = np.zeros(C, dtype=bool)
    cq_pref_pob = np.zeros(C, dtype=bool)
    cq_fair_weight = np.ones(C, dtype=np.float32)
    cq_root = np.zeros(C, dtype=np.int32)
    cq_ngroups = np.ones(C, dtype=np.int32)
    cq_option_flavors: dict[str, list[str]] = {}
    cq_resource_group: dict[str, dict[str, int]] = {}
    #: per CQ: option k -> (group idx, FlavorQuotas)
    cq_options: dict[str, list[tuple[int, str]]] = {}
    K = 1
    for cid, name in enumerate(cq_names):
        spec = store.cluster_queues[name]
        node = forest.cqs[name]
        cq_node[cid] = index[id(node)]
        cq_strict[cid] = spec.queueing_strategy == QueueingStrategy.STRICT_FIFO
        cq_try_next[cid] = (
            spec.flavor_fungibility.when_can_borrow
            == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR)
        cq_preempt_try_next[cid] = (
            spec.flavor_fungibility.when_can_preempt
            == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR)
        cq_pref_pob[cid] = (
            spec.flavor_fungibility.preference
            == FlavorFungibilityPreference.PREEMPTION_OVER_BORROWING)
        cq_root_height[cid] = height[index[id(node.root())]]
        cq_root[cid] = index[id(node.root())]
        cq_within_policy[cid] = _POLICY_CODE[
            spec.preemption.within_cluster_queue]
        cq_reclaim_policy[cid] = _POLICY_CODE[
            spec.preemption.reclaim_within_cohort]
        bwc = spec.preemption.borrow_within_cohort
        cq_bwc_forbidden[cid] = bwc.policy == PreemptionPolicyValue.NEVER
        if bwc.max_priority_threshold is not None:
            cq_bwc_threshold[cid] = bwc.max_priority_threshold
        cq_fair_weight[cid] = spec.fair_sharing.weight
        options: list[tuple[int, str]] = []
        rg_of_resource: dict[str, int] = {}
        for g, rg in enumerate(spec.resource_groups):
            for r in rg.covered_resources:
                rg_of_resource[r] = g
            for fq in rg.flavors:
                options.append((g, fq.name))
        cq_options[name] = options
        cq_option_flavors[name] = [f for _, f in options]
        cq_resource_group[name] = rg_of_resource
        cq_ngroups[cid] = max(1, len(spec.resource_groups))
        cq_nflavors[cid] = len(options)
        K = max(K, len(options))

    G_MAX = int(cq_ngroups.max()) if C else 1
    cq_opt_group = np.full((C, K), -1, dtype=np.int32)
    for cid, name in enumerate(cq_names):
        for k, (g, _) in enumerate(cq_options[name]):
            cq_opt_group[cid, k] = g

    cq_id = {name: i for i, name in enumerate(cq_names)}

    # ---- workload arrays (cache-assembled, vectorized) -------------------
    # Per-workload quantities come from ExportCache rows (built once per
    # workload state, invalidated by store events); request tensors are
    # interned by scheduling shape and assembled with one gather.
    if cache is None:
        cache = ExportCache(store, subscribe=False)
    stamp = cache.refresh(fr_list, cq_names, K, F)
    cache.cq_tables(cq_names)

    all_infos: list[WorkloadInfo] = []
    wl_cqid_l, wl_rank_l = [], []
    for name, infos in pending.items():
        for rank, info in enumerate(infos):
            all_infos.append(info)
            wl_cqid_l.append(cq_id[info.cluster_queue])
            wl_rank_l.append(rank)
    n_heap = len(all_infos)
    if parked:
        for name, infos in parked.items():
            for info in infos:
                all_infos.append(info)
                wl_cqid_l.append(cq_id[info.cluster_queue])
                wl_rank_l.append(int(BIG))
    n_pending = len(all_infos)
    if include_admitted:
        for info in store.admitted_infos():
            if info.cluster_queue in cq_id:
                all_infos.append(info)
                wl_cqid_l.append(cq_id[info.cluster_queue])
                wl_rank_l.append(int(BIG))
    W = len(all_infos)

    rows = [
        cache.row(info, cid, stamp, bool(cq_strict[cid]),
                  int(cq_root[cid]), K, F)
        for info, cid in zip(all_infos, wl_cqid_l)
    ]

    wl_cqid = np.concatenate(
        [np.asarray(wl_cqid_l, dtype=np.int32), [C]]).astype(np.int32)
    wl_rank = np.concatenate(
        [np.asarray(wl_rank_l, dtype=np.int32), [BIG]]).astype(np.int32)
    wl_prio = np.zeros(W + 1, dtype=np.int32)
    wl_ts = np.zeros(W + 1, dtype=np.int32)
    wl_uid = np.zeros(W + 1, dtype=np.int32)
    wl_req = np.zeros((W + 1, K, F), dtype=np.int64)
    wl_valid = np.zeros((W + 1, K), dtype=bool)
    wl_admitted0 = np.zeros(W + 1, dtype=bool)
    wl_admitted0[n_pending:W] = True
    wl_parked0 = np.zeros(W + 1, dtype=bool)
    wl_parked0[n_heap:n_pending] = True
    wl_evicted0 = np.zeros(W + 1, dtype=bool)
    wl_admit_rank = np.zeros(W + 1, dtype=np.int32)
    ad_usage = np.zeros((W + 1, F), dtype=np.int64)

    if W:
        wl_prio[:W] = np.fromiter((r.prio for r in rows), np.int64, W)
        wl_uid[:W] = np.fromiter((r.uid for r in rows), np.int64, W)
        wl_evicted0[:W] = np.fromiter(
            (r.evicted for r in rows), bool, W)
        shape_ids = np.fromiter(
            (r.shape_id for r in rows), np.int64, W)
        stack_valid, stack_req = cache.shape_matrices()
        wl_valid[:W] = stack_valid[shape_ids]
        wl_req[:W] = stack_req[shape_ids]

    # Scheduling-equivalence classes (per CQ; StrictFIFO and gate-off
    # workloads get the sentinel class and never dedup-park) — interned
    # tokens densified per export with np.unique.
    toks = (np.fromiter((r.class_tok for r in rows), np.int64, W)
            if W else np.zeros(0, dtype=np.int64))
    pos = toks >= 0
    if pos.any():
        uniq, inv_c = np.unique(toks[pos], return_inverse=True)
        n_classes = len(uniq)
        wl_class = np.full(W + 1, n_classes, dtype=np.int32)
        wl_class[np.nonzero(pos)[0]] = inv_c
        tok_root = np.asarray(cache._tok_root, dtype=np.int32)
        class_root = np.concatenate(
            [tok_root[uniq], [n_nodes]]).astype(np.int32)
    else:
        n_classes = 0
        wl_class = np.zeros(W + 1, dtype=np.int32)
        class_root = np.asarray([n_nodes], dtype=np.int32)

    # Timestamps are exported as dense ranks: only relative order matters
    # for entry sorting, and float32 would collapse epoch-scale values
    # less than ~128s apart (ties must stay ties for the uid tiebreak).
    from kueue_oss_tpu import features as _features
    from kueue_oss_tpu.scheduler.preemption import (
        TIMESTAMP_PREEMPTION_BUFFER_S,
    )

    wl_ts_buf = np.zeros(W + 1, dtype=np.int32)
    wl_raw_ts = np.zeros(W + 1, dtype=np.float64)
    wl_raw_admit_ts = np.zeros(W + 1, dtype=np.float64)
    n_ts = 0
    n_admit_rank = 0
    if W:
        raw_ts = np.fromiter((r.raw_ts for r in rows), np.float64, W)
        wl_raw_ts[:W] = raw_ts
        distinct_ts, inv_ts = np.unique(raw_ts, return_inverse=True)
        n_ts = len(distinct_ts)
        wl_ts[:W] = inv_ts
        if _features.enabled("SchedulerTimestampPreemptionBuffer"):
            wl_ts_buf[:W] = np.searchsorted(
                distinct_ts, raw_ts + TIMESTAMP_PREEMPTION_BUFFER_S,
                side="right") - 1
        else:
            wl_ts_buf[:W] = inv_ts
    if W > n_pending:
        raw_admit = np.fromiter(
            (r.admit_ts for r in rows[n_pending:]), np.float64,
            W - n_pending)
        wl_raw_admit_ts[n_pending:W] = raw_admit
        distinct_admit, inv_a = np.unique(raw_admit, return_inverse=True)
        n_admit_rank = len(distinct_admit)
        wl_admit_rank[n_pending:W] = inv_a + 1
        for w in range(n_pending, W):
            r = rows[w]
            if r.usage_fs is not None and r.usage_fs.size:
                ad_usage[w, r.usage_fs] = r.usage_qs

    # ---- unit scaling ----------------------------------------------------
    # The gcd must cover every quantity that gets divided — including the
    # lending-limit-derived local_quota and subtree sums, which otherwise
    # truncate and change availability. The interned shape matrix covers
    # every wl_req row (a superset of the shapes present this export —
    # any common divisor of the superset still divides every present
    # quantity).
    scale = 0
    for arr in (nominal, borrow_limit[has_borrow], usage0, subtree,
                local_quota, cache.shape_matrices()[1], ad_usage):
        flat = np.asarray(arr, dtype=np.int64).ravel()
        if flat.size:
            scale = math.gcd(scale, int(np.gcd.reduce(flat)))
    scale = max(scale, 1)

    def scaled(a: np.ndarray) -> np.ndarray:
        out = a // scale
        if out.size and out.max() >= MAX_QUANTITY:
            raise UnsupportedProblem(
                "quantities too large for int32 solver tensors")
        return out.astype(np.int32)

    # resource-name vocabulary (fair-sharing DRS groups borrow by resource)
    resources = sorted({fr[1] for fr in fr_list}) or ["_"]
    res_index = {r: i for i, r in enumerate(resources)}
    fr_resource = np.asarray([res_index[fr[1]] for fr in fr_list]
                             or [0], dtype=np.int32)
    node_fair_weight = np.ones(n_nodes + 1, dtype=np.float32)
    for i, n in enumerate(nodes):
        node_fair_weight[i] = n.fair_weight

    # ---- admission fair sharing (KEP-4136): dense LQ ids + penalties ----
    # Only UsageBasedAdmissionFairSharing CQs participate; the penalty
    # increment is flavor-independent (requests are per-resource), so it
    # exports as one scalar per workload (afs/entry_penalties.go).
    wl_lq = np.zeros(W + 1, dtype=np.int32)
    wl_afs_penalty = np.zeros(W + 1, dtype=np.float32)
    cq_afs = np.zeros(C, dtype=bool)
    lq_pen_list: list[float] = [0.0]
    if afs is not None:
        lq_index: dict[str, int] = {}
        for cid, name in enumerate(cq_names):
            scope = store.cluster_queues[name].admission_scope
            cq_afs[cid] = (
                scope is not None
                and scope.admission_mode == "UsageBasedAdmissionFairSharing")
        if cq_afs.any():
            weights = afs.config.resource_weights
            from kueue_oss_tpu.core.afs import _DEFAULT_WEIGHT

            for w, r in enumerate(rows):
                if not cq_afs[r.cid]:
                    continue
                lq_key = r.lq_key
                li = lq_index.get(lq_key)
                if li is None:
                    li = len(lq_pen_list)
                    lq_index[lq_key] = li
                    lq_pen_list.append(
                        float(afs.weighted_usage(lq_key, now)))
                wl_lq[w] = li
                total = 0.0
                for rname, q in r.totals.items():
                    total += weights.get(rname, _DEFAULT_WEIGHT) * q
                lq_w = afs.lq_weights.get(lq_key, 1.0)
                wl_afs_penalty[w] = (total / lq_w if lq_w > 0
                                     else np.float32(np.inf))
    lq_penalty0 = np.asarray(lq_pen_list, dtype=np.float32)

    return SolverProblem(
        parent=parent,
        depth=depth,
        height=height,
        has_parent=has_parent,
        path=path,
        nominal=scaled(nominal),
        subtree=scaled(subtree),
        local_quota=scaled(local_quota),
        has_borrow=has_borrow,
        borrow_limit=np.where(has_borrow, scaled(borrow_limit),
                              BIG).astype(np.int32),
        usage0=scaled(usage0),
        cq_node=cq_node,
        cq_strict=cq_strict,
        cq_try_next=cq_try_next,
        cq_root_height=cq_root_height,
        cq_nflavors=cq_nflavors,
        wl_cqid=wl_cqid,
        wl_rank=wl_rank,
        wl_prio=wl_prio,
        wl_ts=wl_ts,
        wl_uid=wl_uid,
        wl_req=scaled(wl_req),
        wl_valid=wl_valid,
        wl_parked0=wl_parked0,
        wl_admitted0=wl_admitted0,
        wl_evicted0=wl_evicted0,
        wl_admit_rank=wl_admit_rank,
        ad_usage=scaled(ad_usage),
        cq_within_policy=cq_within_policy,
        cq_reclaim_policy=cq_reclaim_policy,
        cq_bwc_forbidden=cq_bwc_forbidden,
        cq_bwc_threshold=cq_bwc_threshold,
        cq_preempt_try_next=cq_preempt_try_next,
        cq_pref_pob=cq_pref_pob,
        cq_fair_weight=cq_fair_weight,
        cq_root=cq_root,
        cq_opt_group=cq_opt_group,
        cq_ngroups=cq_ngroups,
        fr_resource=fr_resource,
        node_fair_weight=node_fair_weight,
        wl_class=wl_class,
        class_root=class_root,
        n_classes=n_classes,
        wl_lq=wl_lq,
        wl_afs_penalty=wl_afs_penalty,
        wl_ts_buf=wl_ts_buf,
        lq_penalty0=lq_penalty0,
        cq_afs=cq_afs,
        wl_raw_ts=wl_raw_ts,
        wl_raw_admit_ts=wl_raw_admit_ts,
        wl_class_tok=np.concatenate([toks, [-1]]).astype(np.int64),
        class_tok_root=np.asarray(cache._tok_root, dtype=np.int32),
        n_resources=len(resources),
        ts_evict_base=n_ts + 1,
        admit_rank_base=n_admit_rank + 2,
        fr_list=fr_list,
        node_names=[n.name for n in nodes],
        cq_names=cq_names,
        wl_keys=[i.key for i in all_infos],
        cq_option_flavors=cq_option_flavors,
        cq_resource_group=cq_resource_group,
        scale=scale,
    )
