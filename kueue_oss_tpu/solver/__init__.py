"""Batched TPU admission solver (JAX/pjit/Pallas).

This is the framework's defining component: the per-cycle scheduling core —
hierarchical quota availability, flavor assignment, entry ordering, and the
one-admission-at-a-time cohort contract — reformulated over dense
[node x flavor-resource] tensors and executed as a single jitted program.
One solver invocation drains an entire pending backlog (multi-round
wavefront), where the reference's Go loop needs one cycle per admission
wave. The scalar oracle in kueue_oss_tpu.scheduler remains the correctness
reference; parity tests diff the two on randomized scenarios.
"""

from kueue_oss_tpu.solver.tensors import SolverProblem, export_problem  # noqa: F401
from kueue_oss_tpu.solver.kernels import solve_backlog  # noqa: F401
from kueue_oss_tpu.solver.engine import SolverEngine  # noqa: F401
from kueue_oss_tpu.solver.resilience import (  # noqa: F401
    SolverHealth,
    SolverUnavailable,
)
from kueue_oss_tpu.solver.delta import (  # noqa: F401
    DeviceResidentProblem,
    HostDeltaSession,
    ProblemDelta,
    SessionFrame,
)
