"""Pallas TPU kernel for the TAS phase-1 leaf-state computation.

The hottest regular op in topology-aware placement is fillInCounts'
leaf pass (tas_flavor_snapshot.go:1568): for every leaf domain, the
number of pods that fit is ``min over resources of capacity // request``
(and, with a leader podset, the same over the capacity left after
hosting the leader). It is pure VPU work — elementwise integer division
and a lane-axis min-reduction over a [D_leaves, R] tile — so it maps
onto an (8, 128) vector-unit tile directly: leaves ride the sublane
axis, the resource vocabulary pads to one 128-lane register row.

``leaf_states`` is the fused kernel producing the plain state, the
with-leader state, and the leader-fit flag in ONE pass over the
capacity tile (the jnp reference reads the tile three times);
``tas_kernels.fill_counts_ext`` routes through it on TPU backends (or
when KUEUE_TPU_PALLAS=1; =0 disables), with the jnp path as the
fallback and the parity oracle (tests/test_pallas_tas.py runs the
kernel in interpret mode against it).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

BIG = np.int32(1 << 30)

#: sublane tile for the leaf axis; lane axis is the 128-wide resource row
_TILE_D = 256
_LANES = 128


def use_pallas() -> bool:
    env = os.environ.get("KUEUE_TPU_PALLAS")
    if env == "1":
        return True
    if env == "0":
        return False
    return jax.default_backend() == "tpu"


def interpret_mode() -> bool:
    """Pallas lowers natively only on TPU (Mosaic); every other backend
    runs the kernel in interpret mode so KUEUE_TPU_PALLAS=1 exercises
    the exact kernel code path anywhere (slow but correct)."""
    return jax.default_backend() != "tpu"


def _leaf_states_kernel(cap_ref, req_ref, leader_ref, flags_ref,
                        st_ref, swl_ref, ls_ref):
    cap = cap_ref[:]                                   # [TILE_D, LANES]
    req = req_ref[:]                                   # [1, LANES]
    leader = leader_ref[:]                             # [1, LANES]
    has_leader = flags_ref[0, 0] > 0
    nz = req > 0
    safe_req = jnp.maximum(req, 1)
    per_dom = jnp.where(nz, cap // safe_req, BIG)
    st = jnp.min(per_dom, axis=1)                      # [TILE_D]
    lnz = leader > 0
    fits_leader = jnp.all(~lnz | (cap >= leader), axis=1) & has_leader
    rem = cap - jnp.where(fits_leader[:, None], leader, 0)
    per_dom_l = jnp.where(nz, rem // safe_req, BIG)
    swl = jnp.min(per_dom_l, axis=1)
    # outputs are [TILE_D, 1] columns (sublane-major); Mosaic pads the
    # single lane internally
    st_ref[:] = jnp.minimum(st, BIG)[:, None]
    swl_ref[:] = jnp.minimum(swl, BIG)[:, None]
    ls_ref[:] = fits_leader.astype(jnp.int32)[:, None]


@functools.partial(jax.jit, static_argnames=("interpret",))
def leaf_states(leaf_capacity, per_pod, leader_per_pod, has_leader,
                interpret: bool = False):
    """Fused phase-1 leaf pass.

    leaf_capacity [D, R] int32; per_pod / leader_per_pod [R] int32;
    has_leader scalar bool. Returns (st [D], swl [D], ls [D] int32) —
    exactly fill_counts_ext's leaf-level st/swl/ls.
    """
    from jax.experimental import pallas as pl

    D, R = leaf_capacity.shape
    if R > _LANES:
        raise ValueError(f"resource vocabulary {R} exceeds one lane row")
    d_pad = max(_TILE_D, -(-D // _TILE_D) * _TILE_D)
    cap = jnp.zeros((d_pad, _LANES), dtype=jnp.int32)
    cap = cap.at[:D, :R].set(leaf_capacity.astype(jnp.int32))
    req = jnp.zeros((1, _LANES), dtype=jnp.int32)
    req = req.at[0, :R].set(per_pod.astype(jnp.int32))
    leader = jnp.zeros((1, _LANES), dtype=jnp.int32)
    leader = leader.at[0, :R].set(leader_per_pod.astype(jnp.int32))
    flags = jnp.asarray(has_leader, dtype=jnp.int32).reshape(1, 1)

    grid = (d_pad // _TILE_D,)
    st, swl, ls = pl.pallas_call(
        _leaf_states_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_D, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((1, _LANES), lambda i: (0, 0)),
            pl.BlockSpec((1, _LANES), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((_TILE_D, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_D, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_D, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((d_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((d_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((d_pad, 1), jnp.int32),
        ],
        interpret=interpret,
    )(cap, req, leader, flags)
    return st[:D, 0], swl[:D, 0], ls[:D, 0]


def leaf_states_reference(leaf_capacity, per_pod, leader_per_pod,
                          has_leader):
    """The jnp formulation (fill_counts_ext's leaf block) — fallback on
    non-TPU backends and the parity oracle for the kernel."""
    nz = per_pod > 0
    per_dom = jnp.where(nz[None, :],
                        leaf_capacity // jnp.maximum(per_pod, 1)[None, :],
                        BIG)
    st = jnp.minimum(jnp.min(per_dom, axis=1), BIG)
    lnz = leader_per_pod > 0
    fits_leader = jnp.all(
        ~lnz[None, :] | (leaf_capacity >= leader_per_pod[None, :]),
        axis=1) & has_leader
    rem = leaf_capacity - jnp.where(fits_leader[:, None],
                                    leader_per_pod[None, :], 0)
    per_dom_l = jnp.where(nz[None, :],
                          rem // jnp.maximum(per_pod, 1)[None, :], BIG)
    swl = jnp.minimum(jnp.min(per_dom_l, axis=1), BIG)
    return st, swl, fits_leader.astype(jnp.int32)
