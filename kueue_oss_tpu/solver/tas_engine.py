"""Production device-TAS placement for the solver engine.

The round-4 device placer (solver/tas_kernels.py) was bench/test-only:
the engine excluded every TAS ClusterQueue from the backlog, so
production TAS placement was 100% host. This module puts the placer in
the drain path: TAS workloads whose shapes the extended placer supports
(single podset; required/preferred/unconstrained levels; single-layer
podset slices; BestFit/LeastFreeCapacity profiles) are admitted by the
quota kernel like any other workload, then placed ON DEVICE by the
sequential placer in admission order; the host tree machinery remains
the mop-up path for everything else (balanced placement, multi-layer
slice constraints, podset groups, leaders, partial admission, node
replacement).

A placement failure simply drops the admission from the committed plan:
the workload stays in its heap and the host cycle after the drain runs
the full host placement for it — the optimistic-device/host-mop-up
pattern the solver uses everywhere (SURVEY.md §7 step 4). Dropping an
admission can only under-consume quota relative to the kernel's plan,
so later plan entries stay valid.

Reference parity: scheduler.go:759-783 (TAS assignment after quota),
tas_flavor_snapshot.go:804-999 (findTopologyAssignment — the placer's
contract), clusterqueue_snapshot.go:191.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from kueue_oss_tpu.api.types import (
    TopologyAssignment,
    TopologyDomainAssignment,
)
from kueue_oss_tpu.core.workload_info import (
    WorkloadInfo,
    effective_per_pod_requests,
)


def _topology_of_cq(store, spec) -> Optional[str]:
    """The single topology name shared by EVERY flavor of the CQ, or
    None when the CQ mixes TAS and non-TAS flavors (or topologies) —
    those keep the host path so the chosen option always needs the same
    tree."""
    topo = None
    for rg in spec.resource_groups:
        for fq in rg.flavors:
            fl = store.resource_flavors.get(fq.name)
            if fl is None or fl.topology_name is None:
                return None
            if topo is None:
                topo = fl.topology_name
            elif fl.topology_name != topo:
                return None
    return topo


def _is_unconstrained(ps) -> bool:
    """Host's unconstrained test (tas/snapshot.py _place:662-666),
    including the implied and slice-only forms."""
    tr = ps.topology_request
    if tr is None:
        return True  # implied request on a TAS-only CQ
    if tr.unconstrained:
        return True
    return (tr.podset_slice_required_topology is not None
            and tr.required is None and tr.preferred is None)


def device_tas_supported(info: WorkloadInfo, store, spec) -> bool:
    """Shape gate: can the extended device placer reproduce the host
    placement for this workload exactly?"""
    from kueue_oss_tpu import features

    if _topology_of_cq(store, spec) is None:
        return False
    if len(info.obj.podsets) != 1:
        return False  # leaders / groups / multi-podset: host path
    if info.obj.status.unhealthy_nodes:
        return False  # node-replacement machinery is host-only
    if info.can_be_partially_admitted():
        return False  # PodSetReducer search is host-only
    ps = info.obj.podsets[0]
    tr = ps.topology_request
    if tr is not None:
        if tr.podset_group_name:
            return False
        if tr.podset_slice_constraints and len(
                tr.podset_slice_constraints) > 1:
            return False  # nested multi-layer slices: host DP
        required = tr.required is not None
        if (features.enabled("TASBalancedPlacement") and not required
                and not _is_unconstrained(ps)):
            return False  # balanced placement DP is host-only
    return True


class DeviceTASPlacer:
    """Places kernel-admitted TAS workloads via the on-device
    sequential placer, one lax.scan step per admission with the
    leaf-capacity carry between them."""

    def __init__(self, store) -> None:
        self.store = store
        #: tree-shape fingerprint -> compiled sequential placer
        self._placers: dict[tuple, object] = {}

    def _placer_for(self, levels):
        # the FULL parent structure is the compile key — the placer
        # bakes parents in at trace time, so any relabeled domain must
        # miss the cache (truncated fingerprints would silently reuse a
        # placer compiled for a different tree)
        key = tuple(np.asarray(p, dtype=np.int32).tobytes()
                    for p in levels.parents)
        placer = self._placers.get(key)
        if placer is None:
            from kueue_oss_tpu.solver.tas_kernels import (
                make_sequential_placer_ext,
            )

            placer = make_sequential_placer_ext(levels.parents)
            self._placers[key] = placer
        return placer

    def place_batch(self, snapshot, items):
        """Place ``items`` (admission-ordered list of (info, flavor))
        on device. Returns {workload key: TopologyAssignment | None} —
        None marks a placement failure (workload stays pending for the
        host mop-up)."""
        import jax
        import jax.numpy as jnp

        from kueue_oss_tpu.solver.tas_kernels import build_levels

        out: dict[str, Optional[TopologyAssignment]] = {}
        by_flavor: dict[str, list] = {}
        for info, flavor in items:
            by_flavor.setdefault(flavor, []).append(info)

        for flavor, infos in by_flavor.items():
            snap = snapshot.tas_flavors.get(flavor)
            if snap is None:
                for info in infos:
                    out[info.key] = None
                continue
            levels = build_levels(snap)
            R = len(levels.resources)
            res_idx = {r: j for j, r in enumerate(levels.resources)}
            leaf_l = len(levels.parents) - 1
            M = len(infos)
            per_pod = np.zeros((M, max(1, R)), dtype=np.int32)
            count = np.zeros((M,), dtype=np.int32)
            level = np.zeros((M,), dtype=np.int32)
            required = np.zeros((M,), dtype=bool)
            unconstrained = np.zeros((M,), dtype=bool)
            least_free = np.zeros((M,), dtype=bool)
            sl_size = np.ones((M,), dtype=np.int32)
            sl_level = np.full((M,), leaf_l, dtype=np.int32)
            feasible = np.ones((M,), dtype=bool)
            for m, info in enumerate(infos):
                ps = info.obj.podsets[0]
                tr = ps.topology_request
                reqs = effective_per_pod_requests(ps, info.obj.namespace)
                for r, v in reqs.items():
                    j = res_idx.get(r)
                    if j is None:
                        if v > 0:
                            feasible[m] = False  # resource absent from tree
                    else:
                        per_pod[m, j] = v
                count[m] = info.total_requests[0].count
                unc = _is_unconstrained(ps)
                unconstrained[m] = unc
                least_free[m] = unc and snap.profile_mixed
                key_level = None
                if tr is not None and tr.required is not None:
                    required[m] = True
                    key_level = tr.required
                elif tr is not None and tr.preferred is not None:
                    key_level = tr.preferred
                if unc or key_level is None:
                    level[m] = leaf_l
                else:
                    idx = snap.level_index(key_level)
                    if idx is None:
                        feasible[m] = False
                        idx = leaf_l
                    level[m] = idx
                if (tr is not None
                        and tr.podset_slice_required_topology is not None):
                    sidx = snap.level_index(
                        tr.podset_slice_required_topology)
                    if (sidx is None or tr.podset_slice_size is None
                            or level[m] > sidx
                            or count[m] % max(tr.podset_slice_size, 1)):
                        feasible[m] = False
                    else:
                        sl_level[m] = sidx
                        sl_size[m] = tr.podset_slice_size

            # rows the host pre-check rejected must not consume capacity
            # inside the scan (later rows would see a smaller tree)
            bad = ~feasible
            count[bad] = 0
            per_pod[bad] = 0
            sl_size[bad] = 1
            placer = self._placer_for(levels)
            args = (jnp.asarray(levels.leaf_capacity),
                    jnp.asarray(per_pod), jnp.asarray(count),
                    jnp.asarray(level), jnp.asarray(required),
                    jnp.asarray(unconstrained), jnp.asarray(least_free),
                    jnp.asarray(sl_size), jnp.asarray(sl_level),
                    jnp.zeros((M, max(1, R)), dtype=jnp.int32),
                    jnp.zeros((M,), dtype=bool))
            sels, _leads, oks, _cap = placer(*args)
            sels = np.asarray(sels)
            oks = np.asarray(oks) & feasible
            # buildAssignment parity (tas_flavor_snapshot.go:1490-1501):
            # hostname-only values when the lowest level is the hostname
            lvl0 = (len(snap.levels) - 1 if snap.is_lowest_level_node
                    else 0)
            for m, info in enumerate(infos):
                if not oks[m]:
                    out[info.key] = None
                    continue
                domains = [
                    TopologyDomainAssignment(
                        values=list(levels.leaf_names[d][lvl0:]),
                        count=int(sels[m, d]))
                    for d in np.nonzero(sels[m])[0]
                ]
                out[info.key] = TopologyAssignment(
                    levels=list(snap.levels[lvl0:]),
                    domains=domains,
                )
        return out
